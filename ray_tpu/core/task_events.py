"""Worker-side task flight recorder: phase events + span shipping.

Role-equivalent to the reference's ``TaskEventBuffer``
(ray: src/ray/core_worker/task_event_buffer.h:206): every worker buffers
fine-grained per-task events locally — here, the phase breakdown of each
execution (scheduling delay, queue wait, arg fetch+deserialize, user-code
execute, result serialize+store) plus the tracing spans that finished in
this process — and a daemon flusher ships batches to the controller (the
GcsTaskManager analog) over the existing control connection.

Shipping uses the worker's reconnecting ``CoreClient``: a batch that fails
to deliver (controller bouncing) re-buffers and retries on the next tick,
so events recorded across a controller restart land on the NEW controller
once the worker re-registers. The buffer is a bounded deque — a controller
unreachable longer than the buffer covers drops oldest-first rather than
growing worker memory.

Everything is gated on ``RTPU_TASK_EVENTS``: when off, the execution hot
path pays one flag check and nothing is buffered, flushed, or shipped.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import flags

# Phase keys a worker may report, in execution order. The controller maps
# each to its derived Prometheus histogram (rtpu_task_<phase>).
PHASE_KEYS = (
    "scheduling_delay_s",  # driver submit -> spec arrival at the worker
    "queue_wait_s",        # spec arrival -> execution start (pool/mailbox)
    "arg_fetch_s",         # dependency location lookup + fetch + deserialize
    "exec_s",              # user code (incl. awaited coroutine time)
    "result_store_s",      # result serialize + object-store put
)


def enabled() -> bool:
    return bool(flags.get("RTPU_TASK_EVENTS"))


class _Recorder:
    """Bounded per-process buffer of phase events, flushed to the controller
    (same daemon-flusher shape as util/metrics.py's _Aggregator)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.events: Optional[collections.deque] = None  # created lazily
        self._pending_spans: List[Dict[str, Any]] = []
        self._thread: Optional[threading.Thread] = None
        self._thread_up = False  # cheap liveness flag (is_alive per record
        # showed up in worker execution profiles)

    def record(self, event: Dict[str, Any]) -> None:
        with self.lock:
            if self.events is None:
                self.events = collections.deque(
                    maxlen=max(16, flags.get("RTPU_TASK_EVENTS_BUF")))
            self.events.append(event)
        if not self._thread_up:
            self._ensure_flusher()

    def _ensure_flusher(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread_up = True
        self._thread = threading.Thread(
            target=self._run, name="rtpu-task-events-flush", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            time.sleep(flags.get("RTPU_TASK_EVENTS_FLUSH_S"))
            try:
                self.flush()
            except Exception:
                pass  # the recorder must never take a worker down

    def flush(self, timeout: float = 30.0) -> bool:
        """Ship everything buffered; False (and re-buffer) on failure.

        The request rides the worker's reconnecting client, so a batch in
        flight when the controller dies blocks in the reconnect loop and
        delivers to the restarted controller — events survive the bounce.
        """
        from ray_tpu.util import tracing

        from . import context as ctx

        with self.lock:
            events = list(self.events) if self.events else []
            if self.events is not None:
                self.events.clear()
            spans, self._pending_spans = self._pending_spans, []
        spans = spans + [tracing.span_to_dict(s)
                         for s in tracing.drain_finished_spans()]
        if not events and not spans:
            return True
        if not ctx.is_initialized():
            self._requeue(events, spans)
            return False
        try:
            wc = ctx.get_worker_context()
            wc.client.request({"kind": "task_phase_events",
                               "events": events, "spans": spans},
                              timeout=timeout)
            return True
        except Exception:
            self._requeue(events, spans)
            return False

    def _requeue(self, events: List[Dict[str, Any]],
                 spans: List[Dict[str, Any]]) -> None:
        with self.lock:
            if events:
                if self.events is None:
                    self.events = collections.deque(
                        maxlen=max(16, flags.get("RTPU_TASK_EVENTS_BUF")))
                # Preserve order; the deque bound drops oldest on overflow.
                self.events.extendleft(reversed(events))
            self._pending_spans.extend(spans)
            del self._pending_spans[:-4096]  # spans are bounded too


_recorder = _Recorder()


def record(event: Dict[str, Any]) -> None:
    """Buffer one finished-task phase event (worker execution path)."""
    _recorder.record(event)


def flush_task_events(timeout: float = 30.0) -> bool:
    """Force a flush (tests / shutdown hooks)."""
    return _recorder.flush(timeout=timeout)
