"""Per-process worker/driver context (reference: ray._private.worker.Worker
singleton, python/ray/_private/worker.py:411)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from .client import CoreClient


@dataclass
class WorkerContext:
    client: CoreClient
    node_id: str
    role: str  # "driver" | "worker"
    namespace: str = "default"
    extra: Dict[str, Any] = field(default_factory=dict)


_context: Optional[WorkerContext] = None
task_local = threading.local()
_pubsub_callbacks: Dict[str, List[Callable[[Any], None]]] = {}


def set_worker_context(c: Optional[WorkerContext]) -> None:
    global _context
    _context = c


def get_worker_context() -> WorkerContext:
    if _context is None:
        raise RuntimeError("ray_tpu is not initialized; call ray_tpu.init() first")
    return _context


def is_initialized() -> bool:
    return _context is not None


def current_task_id() -> Optional[str]:
    return getattr(task_local, "task_id", None)


def current_actor_id() -> Optional[str]:
    return getattr(task_local, "actor_id", None)


def on_pubsub(channel: str, cb: Callable[[Any], None]) -> None:
    _pubsub_callbacks.setdefault(channel, []).append(cb)


def deliver_pubsub(channel: str, data: Any) -> None:
    for cb in _pubsub_callbacks.get(channel, []):
        try:
            cb(data)
        except Exception:
            pass
