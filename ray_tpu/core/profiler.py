"""Sampling wall-clock profiler + flamegraph rendering (no py-spy).

Parity target: the reference's py-spy-backed `ray stack --native` /
dashboard flamegraph button. py-spy is an external Rust binary that needs
ptrace rights; inside our own workers a pure-Python
``sys._current_frames()`` sampler gets the same wall-clock picture of
Python code for free: the controller fans a ``profile`` RPC out to the
target workers, each samples its threads for the requested duration,
ships collapsed stacks back, and the controller merges them into one
cluster-wide profile rendered as a self-contained flamegraph HTML.

Collapsed-stack format is the Brendan Gregg interchange text: one line
per unique stack, frames root->leaf joined by ';', then a space and the
sample count — so the output also feeds external flamegraph.pl /
speedscope tooling unchanged.
"""
from __future__ import annotations

import html
import json
import sys
import threading
import time
from typing import Dict, List, Optional

MAX_DEPTH = 128


def _frame_name(frame) -> str:
    co = frame.f_code
    fn = co.co_filename.rsplit("/", 1)[-1]
    # def-line, not current line: the same function paused at different
    # lines must merge into ONE flamegraph frame or hot functions shatter
    # into per-line slivers.
    return f"{co.co_name} ({fn}:{co.co_firstlineno})"


def sample_stacks(duration_s: float, hz: float = 67.0,
                  skip_threads: Optional[set] = None) -> Dict[str, int]:
    """Sample every thread's Python stack for ``duration_s`` at ``hz``.

    Returns collapsed-stack -> count. The sampler's own thread is skipped
    (it would otherwise dominate every profile with its sleep loop), as is
    any thread id in ``skip_threads``.
    """
    period = 1.0 / max(1.0, float(hz))
    deadline = time.monotonic() + max(0.05, float(duration_s))
    counts: Dict[str, int] = {}
    self_id = threading.get_ident()
    skip = set(skip_threads or ())
    skip.add(self_id)
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            if tid in skip:
                continue
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < MAX_DEPTH:
                stack.append(_frame_name(f))
                f = f.f_back
            stack.append(f"thread:{names.get(tid, tid)}")
            key = ";".join(reversed(stack))
            counts[key] = counts.get(key, 0) + 1
        elapsed = time.monotonic() - t0
        if elapsed < period:
            time.sleep(period - elapsed)
    return counts


def profile_and_encode(duration_s: float, hz: float = 67.0) -> str:
    """Worker-side entry point: sample and JSON-encode for the
    profile_result reply (rides the same gather path as stack_dump)."""
    t0 = time.monotonic()
    stacks = sample_stacks(duration_s, hz)
    return json.dumps({
        "stacks": stacks,
        "samples": sum(stacks.values()),
        "duration_s": round(time.monotonic() - t0, 3),
    })


def merge_collapsed(per_worker: Dict[str, str]) -> Dict[str, dict]:
    """Merge worker profile_result texts (JSON from profile_and_encode).

    Returns {"stacks": {collapsed: count}, "samples": int,
    "workers": {worker_id: samples|error-string}} — a worker whose reply
    failed to parse is reported, never fatal (partial profiles are still
    profiles, same contract as profile_workers).
    """
    stacks: Dict[str, int] = {}
    samples = 0
    workers: Dict[str, object] = {}
    for wid, text in per_worker.items():
        try:
            payload = json.loads(text)
            if "error" in payload:
                workers[wid] = str(payload["error"])
                continue
            for key, n in payload.get("stacks", {}).items():
                stacks[key] = stacks.get(key, 0) + int(n)
            n = int(payload.get("samples", 0))
            samples += n
            workers[wid] = n
        except Exception as e:
            workers[wid] = f"unparseable reply: {e}"
    return {"stacks": stacks, "samples": samples, "workers": workers}


# ------------------------------------------------------------- rendering


def _build_tree(stacks: Dict[str, int]) -> dict:
    root = {"name": "all", "value": 0, "children": {}}
    for key, count in stacks.items():
        root["value"] += count
        node = root
        for frame in key.split(";"):
            child = node["children"].get(frame)
            if child is None:
                child = node["children"][frame] = {
                    "name": frame, "value": 0, "children": {}}
            child["value"] += count
            node = child
    return root


def _tree_to_json(node: dict) -> dict:
    return {"n": node["name"], "v": node["value"],
            "c": [_tree_to_json(c) for c in
                  sorted(node["children"].values(),
                         key=lambda x: -x["value"])]}


_HTML_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>__TITLE__</title>
<style>
body { font: 12px -apple-system, Segoe UI, sans-serif; margin: 12px;
       background: #1b1f27; color: #dde; }
h1 { font-size: 15px; }
#meta { color: #8892a6; margin-bottom: 8px; }
#fg { position: relative; width: 100%; }
.fr { position: absolute; height: 17px; box-sizing: border-box;
      overflow: hidden; white-space: nowrap; font-size: 11px;
      line-height: 17px; padding: 0 3px; border: 1px solid #1b1f27;
      border-radius: 2px; cursor: pointer; color: #201a10; }
.fr:hover { border-color: #fff; }
#tip { position: fixed; background: #000c; color: #fff; padding: 4px 8px;
       border-radius: 4px; pointer-events: none; display: none;
       max-width: 70vw; font-size: 11px; z-index: 9; }
</style></head><body>
<h1>__TITLE__</h1>
<div id="meta">__META__ &mdash; click a frame to zoom, click the root to
reset</div>
<div id="fg"></div><div id="tip"></div>
<script>
var DATA = __DATA__;
var fg = document.getElementById('fg'), tip = document.getElementById('tip');
var ROW = 18, focusNode = DATA;
function color(s) {
  var h = 0; for (var i = 0; i < s.length; i++) h = (h * 31 + s.charCodeAt(i)) >>> 0;
  return 'hsl(' + (20 + h % 40) + ',' + (60 + h % 30) + '%,' + (52 + h % 16) + '%)';
}
function depth(n) { var d = 1, m = 0;
  n.c.forEach(function(c){ m = Math.max(m, depth(c)); }); return d + m; }
function render() {
  fg.innerHTML = '';
  var W = fg.clientWidth || 960;
  fg.style.height = (depth(focusNode) * ROW + 4) + 'px';
  function draw(node, x, w, row) {
    if (w < 1) return;
    var d = document.createElement('div');
    d.className = 'fr';
    d.style.left = x + 'px'; d.style.top = (row * ROW) + 'px';
    d.style.width = w + 'px';
    d.style.background = color(node.n);
    d.textContent = w > 28 ? node.n : '';
    d.onclick = function(ev) { ev.stopPropagation();
      focusNode = (node === focusNode) ? DATA : node; render(); };
    d.onmousemove = function(ev) {
      tip.style.display = 'block';
      tip.style.left = Math.min(ev.clientX + 12, innerWidth - 320) + 'px';
      tip.style.top = (ev.clientY + 12) + 'px';
      tip.textContent = node.n + ' — ' + node.v + ' samples (' +
        (100 * node.v / DATA.v).toFixed(1) + '%)';
    };
    d.onmouseout = function() { tip.style.display = 'none'; };
    fg.appendChild(d);
    var cx = x;
    node.c.forEach(function(ch) {
      var cw = w * ch.v / node.v; draw(ch, cx, cw, row + 1); cx += cw;
    });
  }
  draw(focusNode, 0, W, 0);
}
window.onresize = render; render();
</script></body></html>
"""


def render_flamegraph_html(stacks: Dict[str, int],
                           title: str = "rtpu profile",
                           meta: str = "") -> str:
    """Self-contained flamegraph page (zero external assets — it must
    open from a laptop with no network path back to the cluster)."""
    tree = _tree_to_json(_build_tree(stacks))
    total = sum(stacks.values())
    info = meta or f"{total} samples, {len(stacks)} unique stacks"
    return (_HTML_TEMPLATE
            .replace("__TITLE__", html.escape(title))
            .replace("__META__", html.escape(info))
            .replace("__DATA__", json.dumps(tree)))


def save_flamegraph(path: str, stacks: Dict[str, int],
                    title: str = "rtpu profile", meta: str = "") -> None:
    with open(path, "w") as f:
        f.write(render_flamegraph_html(stacks, title=title, meta=meta))


def to_collapsed_text(stacks: Dict[str, int]) -> str:
    """flamegraph.pl / speedscope interchange text."""
    return "".join(f"{k} {v}\n"
                   for k, v in sorted(stacks.items(),
                                      key=lambda kv: -kv[1]))
