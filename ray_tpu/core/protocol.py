"""Wire protocol for the control/data plane.

Length-prefixed pickled message dicts over TCP, with request/response
correlation and server-push support. This plays the role of the reference's
gRPC layer (ray: src/ray/rpc/) — a thin, asyncio-native RPC substrate. The
message schema is a plain dict: {"kind": str, "rid": int|None, ...payload}.

Design notes (TPU-first):
- The control plane carries *references and metadata only*; bulk array bytes
  move through the shared-memory object store (see object_store.py) or stay
  resident in XLA device buffers. Keeping the RPC layer tiny and in Python is
  fine because it is never on the per-step hot path of a training loop — the
  hot path is inside one jitted XLA program.
"""
from __future__ import annotations

import asyncio
import itertools
import pickle
import socket
import struct
import threading
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

_LEN = struct.Struct("!Q")

# Frame-length flag bit: the payload is [8B raw_len][pickled msg][raw bytes]
# instead of one pickled dict. Bulk data-plane messages (streamed pull
# chunks, replicate chains) ride the raw tail so a chunk is never copied
# through pickle on either end — the receiver hands the handler a
# zero-copy memoryview under msg["data"].
_RAW_BIT = 1 << 63


def _set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on a connection's socket. The write batcher already
    coalesces frames into one send per loop iteration, so Nagle can only
    ADD latency by holding small control messages for the peer's delayed
    ack. asyncio defaults TCP_NODELAY on for TCP transports, but that is
    an implementation detail of the selector transport — set it explicitly
    so every route (controller, agent, worker, direct) has it by contract."""
    try:
        sock = writer.get_extra_info("socket")
        if sock is not None and sock.family in (socket.AF_INET,
                                                socket.AF_INET6):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except Exception:
        pass  # non-TCP transport (tests may pipe) — nothing to disable


class NeverSentError(ConnectionError):
    """The connection was already closed when the request was submitted:
    the bytes PROVABLY never left this process. Callers with at-most-once
    semantics (direct actor calls) may safely resubmit on another path —
    unlike a generic ConnectionError, where the peer may have executed the
    request before the connection dropped."""

# ------------------------------------------------------- handler accounting
# Per-kind served-message count + cumulative handler seconds for this
# process (reference: the per-RPC event stats gRPC servers surface). The
# controller exports these on /metrics (rtpu_rpc_handled_total /
# rtpu_rpc_handler_seconds_total) so the control-plane leg of task latency
# is visible next to the worker-side phase histograms.
_handler_stats_lock = threading.Lock()
_handler_stats: Dict[str, list] = {}  # kind -> [count, total_seconds]


def _record_handler_stat(kind: Optional[str], dt: float) -> None:
    with _handler_stats_lock:
        st = _handler_stats.get(kind or "?")
        if st is None:
            st = _handler_stats[kind or "?"] = [0, 0.0]
        st[0] += 1
        st[1] += dt


def handler_stats() -> Dict[str, Tuple[int, float]]:
    """Snapshot of this process's served-message stats: kind -> (count,
    total handler seconds — awaits inside the handler included)."""
    with _handler_stats_lock:
        return {k: (v[0], v[1]) for k, v in _handler_stats.items()}

# --------------------------------------------------------- fault injection
# RTPU_TESTING_RPC_DELAY_MS (reference: RAY_testing_asio_delay_us) delays
# the server-side handler of matching message kinds — deterministic
# reconnect/race testing without sleeps sprinkled through product code.
# Format: "kind=ms,kind2=ms" or "*=ms" (every kind). Parsed lazily and
# cached per raw value so the hot path costs one env read + dict lookup.
_delay_cache: tuple = (None, {})


def testing_delay_s(kind: Optional[str]) -> float:
    """Injected handler delay in seconds for one message kind (0 = none)."""
    from ray_tpu import flags

    raw = flags.raw("RTPU_TESTING_RPC_DELAY_MS")
    if not raw:
        return 0.0
    global _delay_cache
    cached_raw, table = _delay_cache
    if raw != cached_raw:
        table = {}
        for part in raw.split(","):
            name, _, ms = part.partition("=")
            try:
                table[name.strip()] = float(ms) / 1000.0
            except ValueError:
                continue
        _delay_cache = (raw, table)
    return table.get(kind or "", table.get("*", 0.0))


# RTPU_TESTING_RPC_DROP: per-kind probabilities of silently DISCARDING a
# received message before its handler runs (no response ever sent) — models
# a lossy network / one-way partition. Same "kind=value" spec shape and
# lazy-parse cache as the delay hook.
_drop_cache: tuple = (None, {})


def testing_drop_prob(kind: Optional[str]) -> float:
    """Injected drop probability for one message kind (0 = never drop)."""
    from ray_tpu import flags

    raw = flags.raw("RTPU_TESTING_RPC_DROP")
    if not raw:
        return 0.0
    global _drop_cache
    cached_raw, table = _drop_cache
    if raw != cached_raw:
        table = {}
        for part in raw.split(","):
            name, _, p = part.partition("=")
            try:
                table[name.strip()] = float(p)
            except ValueError:
                continue
        _drop_cache = (raw, table)
    return table.get(kind or "", table.get("*", 0.0))


# Symmetric process blackhole (testing.NetworkPartitioner): a process whose
# RTPU_TESTING_NET_ID appears in the shared partition file's "isolated" list
# drops every inbound AND outbound frame at this layer — TCP connections
# stay open, bytes vanish, exactly like a network partition. The verdict is
# cached and re-read at most every 50ms so the per-frame cost when the
# feature is unused is one monotonic() read and two comparisons.
_partition_state = {"next": 0.0, "active": False}


def partition_active() -> bool:
    st = _partition_state
    now = time.monotonic()
    if now < st["next"]:
        return st["active"]
    st["next"] = now + 0.05
    from ray_tpu import flags

    path = flags.raw("RTPU_TESTING_PARTITION_FILE")
    my_id = flags.raw("RTPU_TESTING_NET_ID") if path else None
    active = False
    if path and my_id:
        import json as _json

        try:
            with open(path, "r", encoding="utf-8") as f:
                data = _json.load(f)
            active = my_id in (data.get("isolated") or ())
        except Exception:
            active = False
    st["active"] = active
    return active

# Messages are small control-plane payloads; large values go via the object
# store.  A high cap catches protocol bugs (accidentally inlined tensors).
MAX_MSG_BYTES = 1 << 31


def dumps(msg: Dict[str, Any]) -> bytes:
    return pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)


def loads(data: bytes) -> Dict[str, Any]:
    return pickle.loads(data)


async def read_msg(reader: asyncio.StreamReader) -> Dict[str, Any]:
    header = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(header)
    if n & _RAW_BIT:
        n &= ~_RAW_BIT
        if n > MAX_MSG_BYTES:
            raise ValueError(f"message too large: {n} bytes")
        data = await reader.readexactly(n)
        mv = memoryview(data)
        (raw_len,) = _LEN.unpack_from(data, 0)
        msg = loads(mv[_LEN.size : n - raw_len])
        msg["data"] = mv[n - raw_len :]
        return msg
    if n > MAX_MSG_BYTES:
        raise ValueError(f"message too large: {n} bytes")
    data = await reader.readexactly(n)
    return loads(data)


def write_msg(writer: asyncio.StreamWriter, msg: Dict[str, Any]) -> None:
    data = dumps(msg)
    writer.write(_LEN.pack(len(data)))
    writer.write(data)


def encode_raw_prefix(msg: Dict[str, Any], raw) -> bytes:
    """Frame prefix for a raw-tail message: length word (with _RAW_BIT),
    raw length, pickled header. The caller writes this prefix and then the
    raw bytes; read_msg on the other end reassembles msg["data"] as a
    zero-copy memoryview. One encoder shared by the asyncio transport
    (Connection.send_with_raw) and synchronous blocking-socket senders
    (transfer.RawStreamSender) so the framing cannot drift."""
    header = dumps(msg)
    raw_len = memoryview(raw).nbytes
    total = _LEN.size + len(header) + raw_len
    return _LEN.pack(total | _RAW_BIT) + _LEN.pack(raw_len) + header


class Connection:
    """A bidirectional message channel with request/response correlation.

    Both peers may issue requests; `handler` serves the remote peer's requests
    and unsolicited pushes. One reader task demultiplexes responses (matched on
    "rid") from incoming requests.
    """

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        handler: Optional[Callable[["Connection", Dict[str, Any]], Awaitable[None]]] = None,
        name: str = "",
    ):
        self.reader = reader
        self.writer = writer
        _set_nodelay(writer)
        self.handler = handler
        self.name = name
        self._rid = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._send_lock = asyncio.Lock()
        self.closed = asyncio.Event()
        # Write coalescing: frames queued during one loop iteration flush as
        # ONE transport.write (one syscall). On this class of host a socket
        # send costs ~50-100us, and the control plane's bursts (a driver
        # firing 500 submits, the controller dispatching a wave, a worker
        # returning results) are exactly the pattern that benefits; a lone
        # frame still flushes within the same iteration via call_soon, so
        # request latency is unchanged.
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._outbuf: list = []
        self._outbuf_bytes = 0
        self._flush_scheduled = False

    _FLUSH_BYTES = 1 << 20  # flush immediately past 1MB buffered

    def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._reader_task = self._loop.create_task(self._read_loop())

    # ------------------------------------------------------- write batching

    def _buffered_write(self, data: bytes) -> None:
        """Queue one framed message; flushed once per loop iteration."""
        if partition_active():
            return  # blackholed process: outbound frames vanish (testing)
        if self._loop is None:  # not started (shouldn't happen): direct path
            self.writer.write(data)
            return
        self._outbuf.append(data)
        self._outbuf_bytes += len(data)
        if self._outbuf_bytes >= self._FLUSH_BYTES:
            self._flush()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            self._loop.call_soon(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if not self._outbuf:
            return
        data = b"".join(self._outbuf) if len(self._outbuf) > 1 \
            else self._outbuf[0]
        self._outbuf.clear()
        self._outbuf_bytes = 0
        try:
            self.writer.write(data)
        except Exception:
            pass  # the reader task notices the broken pipe and closes

    def _frame(self, msg: Dict[str, Any]) -> bytes:
        data = dumps(msg)
        return _LEN.pack(len(data)) + data

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await read_msg(self.reader)
                if partition_active():
                    continue  # blackholed process: inbound frames vanish
                if msg.get("kind") == "__response__":
                    fut = self._pending.pop(msg["rid"], None)
                    if fut is not None and not fut.done():
                        if msg.get("error") is not None:
                            fut.set_exception(msg["error"])
                        else:
                            fut.set_result(msg.get("result"))
                elif self.handler is not None:
                    # Serve concurrently: a handler may itself await RPCs.
                    asyncio.get_running_loop().create_task(self._serve(msg))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError, EOFError):
            pass
        except Exception as e:  # noqa: BLE001 — diagnose, then close as usual
            import sys as _sys
            import traceback as _tb

            _sys.stderr.write(f"[protocol] read loop {self.name!r} died "
                              f"unexpectedly: {e!r}\n{_tb.format_exc()}\n")
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"connection {self.name!r} closed"))
            self._pending.clear()
            self.closed.set()
            try:
                self.writer.close()
            except Exception:
                pass

    # Above this much buffered output, response writers start awaiting
    # drain() so a slow reader applies backpressure instead of growing the
    # transport buffer without bound (e.g. 10k-object get_locations bursts).
    _DRAIN_ABOVE = 4 * 1024 * 1024

    async def _serve(self, msg: Dict[str, Any]) -> None:
        rid = msg.get("rid")
        try:
            drop = testing_drop_prob(msg.get("kind"))
            if drop:
                import random as _random

                if _random.random() < drop:
                    return  # message lost en route: no handler, no response
            delay = testing_delay_s(msg.get("kind"))
            if delay:
                await asyncio.sleep(delay)
            t0 = time.perf_counter()
            result = await self.handler(self, msg)
            _record_handler_stat(msg.get("kind"),
                                 time.perf_counter() - t0)
            if rid is not None:
                # Buffered write on the connection's loop: frames cannot
                # interleave and responses produced in the same iteration
                # coalesce into one syscall. Order is preserved (the later
                # drain only waits, it doesn't write).
                self._buffered_write(self._frame(
                    {"kind": "__response__", "rid": rid, "result": result}))
                if (self.writer.transport.get_write_buffer_size()
                        > self._DRAIN_ABOVE):
                    await self.writer.drain()
        except Exception as e:  # noqa: BLE001 — errors propagate to the caller
            if rid is not None:
                try:
                    self._buffered_write(self._frame(
                        {"kind": "__response__", "rid": rid, "error": e}))
                except Exception:
                    pass

    async def send(self, msg: Dict[str, Any]) -> None:
        """Fire-and-forget push (no response expected)."""
        async with self._send_lock:
            self._buffered_write(self._frame(msg))
            if (self.writer.transport.get_write_buffer_size()
                    > self._DRAIN_ABOVE):
                await self.writer.drain()

    async def send_with_raw(self, msg: Dict[str, Any], raw) -> None:
        """Push `msg` with a raw byte tail (delivered as msg["data"]).

        The payload bytes go straight from the caller's buffer to the
        transport — no pickle embedding, no frame concatenation — which
        halves the per-byte copy count of the bulk data plane (the chunk
        cost is what bounds transfer GB/s on a CPU-bound host)."""
        if partition_active():
            return  # blackholed process (testing): the chunk vanishes
        prefix = encode_raw_prefix(msg, raw)
        async with self._send_lock:
            self._flush()  # previously queued frames keep their order
            try:
                w = self.writer
                w.write(prefix)
                w.write(raw)
            except Exception:
                return  # reader task notices the broken pipe and closes
            if (self.writer.transport.get_write_buffer_size()
                    > self._DRAIN_ABOVE):
                await self.writer.drain()

    def send_with_raw_threadsafe(self, msg: Dict[str, Any], raw) -> None:
        """Fire-and-forget raw-tail push from a non-loop thread.

        Serialization happens on the calling thread; the loop thread only
        queues bytes (same division of labor as request_threadsafe). The
        raw payload is copied here — the caller's buffer (a channel slot)
        may be rewritten before the loop flushes. Compiled-DAG edges that
        terminate at the driver ride this over the driver's existing
        control connection to the worker, so a cross-host terminal needs
        no extra listening socket on the driver."""
        prefix = encode_raw_prefix(msg, raw)
        payload = bytes(raw)

        def _send() -> None:
            try:
                self._buffered_write(prefix)
                self._buffered_write(payload)
            except Exception:
                pass  # reader task notices the broken pipe and closes

        self._loop.call_soon_threadsafe(_send)

    async def request(self, msg: Dict[str, Any], timeout: Optional[float] = None) -> Any:
        """Send a request and await the correlated response."""
        rid = next(self._rid)
        msg = dict(msg, rid=rid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        # A request on an ALREADY-closed connection must fail fast: the
        # read loop's cleanup (which fails pending futures) already ran, so
        # a future registered now would hang forever. Checked after
        # registration — no await in between, so the close path either sees
        # the future or this check sees the close.
        if self.closed.is_set():
            self._pending.pop(rid, None)
            raise NeverSentError(f"connection {self.name!r} closed")
        await self.send(msg)
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    def request_threadsafe(self, msg: Dict[str, Any]):
        """Pipelined request from a non-loop thread.

        Serialization happens on the calling thread (true parallelism under
        the GIL); the loop thread only registers the pending future and
        writes bytes. One call_soon_threadsafe instead of a full
        run_coroutine_threadsafe round — the hot path for direct dispatch.
        Returns a concurrent.futures.Future with the correlated response.
        """
        import concurrent.futures

        rid = next(self._rid)
        data = dumps(dict(msg, rid=rid))
        cfut: "concurrent.futures.Future" = concurrent.futures.Future()

        def _send() -> None:
            if self.closed.is_set():
                cfut.set_exception(
                    NeverSentError(f"connection {self.name!r} closed"))
                return
            fut = self._loop.create_future()
            self._pending[rid] = fut

            def _done(f: "asyncio.Future") -> None:
                if cfut.done():
                    return
                if f.cancelled():
                    cfut.cancel()
                elif f.exception() is not None:
                    cfut.set_exception(f.exception())
                else:
                    cfut.set_result(f.result())

            fut.add_done_callback(_done)
            try:
                self._buffered_write(_LEN.pack(len(data)) + data)
            except Exception as e:  # noqa: BLE001
                self._pending.pop(rid, None)
                if not cfut.done():
                    cfut.set_exception(e)

        try:
            self._loop.call_soon_threadsafe(_send)
        except RuntimeError as e:  # loop closed
            cfut.set_exception(ConnectionError(str(e)))
        return cfut

    async def close(self) -> None:
        self._flush()  # don't strand queued frames
        if self._reader_task is not None:
            self._reader_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        self.closed.set()


async def connect(
    host: str,
    port: int,
    handler: Optional[Callable[[Connection, Dict[str, Any]], Awaitable[None]]] = None,
    name: str = "",
) -> Connection:
    reader, writer = await asyncio.open_connection(host, port)
    conn = Connection(reader, writer, handler, name=name)
    conn.start()
    return conn
