"""Inter-node object transfer: the object-plane fast path.

Role-equivalent to the reference's object manager push/pull protocol
(ray: src/ray/object_manager/object_manager.h, object_manager.proto Push/Pull
chunked transfer + the pull manager, pull_manager.h):

- **Streamed pulls** (RTPU_PULL_STREAM, default on): one ``pull_stream``
  request ships every chunk back-to-back under a credit window
  (RTPU_PULL_WINDOW) instead of one request/response round trip per
  RTPU_PULL_CHUNK bytes. Chunks land zero-copy into one preallocated
  buffer; the serial per-chunk loop remains as the disabled path.
- **Producer serving**: the process that produced an object serves its own
  bytes over its existing direct/ref server (``ObjectLocation.serve_addr``);
  the host agent is the fallback when the producer is gone — mid-pull death
  resumes at the last verified offset instead of restarting.
- **Parallel pulls**: when the controller attaches broadcast replicas to a
  location, the byte range splits across source hosts (RTPU_PULL_PARALLEL).
- **Replicate chains** (broadcast): ``replicate_begin/chunk/end`` pushes a
  full copy down a pipelined chain of hosts so the source ships each byte
  once regardless of fan-out (the weight-distribution path; reference:
  ray.experimental.channel / collective broadcast over the object store).

Serving side: `read_location_range(loc, offset, length)` — runs on any
process on the producer's host; it attaches the arena / shm segment named
in the location and returns raw bytes. The ObjectLocation itself is the
capability.
"""
from __future__ import annotations

import asyncio
import pickle
import secrets
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import flags

from .object_store import ObjectLocation

PULL_CHUNK = 4 * 1024 * 1024
# Per-chunk (serial) / per-progress (streamed) deadline: generous for a
# loaded host, small enough that a dead peer turns into a refresh instead of
# a hung get().
PULL_CHUNK_TIMEOUT_S = 20.0

# Spans smaller than this are not worth splitting across parallel sources.
_PARALLEL_MIN_SPAN = 8 * 1024 * 1024


class RawStreamSender:
    """Persistent raw-tail stream to a peer's direct server.

    One long-lived blocking TCP connection carrying `encode_raw_prefix`
    frames — the cross-host leg of a compiled-DAG channel (and any future
    worker→worker push stream). Unlike the asyncio Connection this is
    callable from an actor's mailbox thread with no loop hop: the resident
    DAG loop writes a frame with two sendall()s and returns to compute.
    The receiver is the peer worker's ordinary direct server; frames with
    no rid get no response, so the stream is strictly one-way and the
    socket is never read. Thread-safe (frames cannot interleave)."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0):
        import socket as _socket

        self._sock = _socket.create_connection((host, port),
                                               timeout=connect_timeout)
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        self.addr = (host, port)

    def send(self, msg: Dict[str, Any], raw) -> None:
        from . import protocol

        prefix = protocol.encode_raw_prefix(msg, raw)
        with self._lock:
            self._sock.sendall(prefix)
            if memoryview(raw).nbytes:
                self._sock.sendall(raw)

    def close(self) -> None:
        try:
            self._sock.close()
        except Exception:
            pass


def read_location_range(loc: ObjectLocation, offset: int, length: int) -> bytes:
    """Serve `length` bytes at `offset` of the object at `loc` (local host)."""
    if loc.inline is not None:
        return bytes(loc.inline[offset : offset + length])
    if loc.spill_path is not None:
        with open(loc.spill_path, "rb") as f:
            f.seek(offset)
            return f.read(length)
    if loc.arena is not None:
        from . import native_store

        arena = native_store.get_arena()
        if arena is None or arena.name != loc.arena:
            arena = native_store.attach_named(loc.arena)
        if arena is None:
            raise RuntimeError(f"cannot attach arena {loc.arena!r} to serve pull")
        view = arena.get(loc.arena_oid)
        if view is None:
            raise KeyError(f"object {loc.object_id[:8]} missing from arena")
        try:
            return bytes(view[offset : offset + length])
        finally:
            del view
            arena.release(loc.arena_oid)
    assert loc.shm_name is not None
    from .object_store import _segments

    seg = _segments.attach(loc.shm_name)
    return bytes(seg.buf[offset : offset + length])


def read_location_view(loc: ObjectLocation, offset: int, length: int):
    """Zero-copy serving read: ``(view, release)`` where `view` aliases the
    object's storage directly (no bytes() copy) and `release` drops the
    read pin once the bytes are on the wire. Spill files and inline
    payloads fall back to a plain copy."""
    if loc.arena is not None:
        from . import native_store

        arena = native_store.get_arena()
        if arena is None or arena.name != loc.arena:
            arena = native_store.attach_named(loc.arena)
        if arena is None:
            raise RuntimeError(f"cannot attach arena {loc.arena!r} to serve pull")
        view = arena.get(loc.arena_oid)
        if view is None:
            raise KeyError(f"object {loc.object_id[:8]} missing from arena")
        return (view[offset : offset + length],
                lambda: arena.release(loc.arena_oid))
    if loc.shm_name is not None:
        from .object_store import _segments

        seg = _segments.attach(loc.shm_name)
        return seg.buf[offset : offset + length], (lambda: None)
    return read_location_range(loc, offset, length), (lambda: None)


def decode_value(loc: ObjectLocation, buf) -> Any:
    """Unpickle an object's assembled bytes using the location's layout.

    ``buf`` may be bytes or a bytearray — the streamed pull path hands the
    preallocated assembly buffer straight in (no bytes() copy; the
    reconstructed arrays privately alias it)."""
    data = bytes(buf[loc.pickle_off : loc.pickle_off + loc.pickle_len])
    mv = memoryview(buf)
    bufs = [mv[off : off + n] for off, n in loc.buffers]
    return pickle.loads(data, buffers=bufs)


# --------------------------------------------------------------- accounting
# Per-process transfer counters, mirrored to /metrics via util.metrics
# (rtpu_transfer_bytes_total{path} + rtpu_pull_seconds) and readable
# in-process by tests/benchmarks via transfer_stats().

_stats_lock = threading.Lock()
_stats: Dict[str, int] = {}
_metrics = None


def _metric_handles():
    global _metrics
    if _metrics is None:
        from ray_tpu.util import metrics as um

        _metrics = (
            um.Counter(
                "rtpu_transfer_bytes_total",
                description="Object bytes moved by the transfer plane, "
                            "by path (stream/serial/broadcast)",
                tag_keys=("path",)),
            um.Histogram(
                "rtpu_pull_seconds",
                description="Wall seconds per remote object pull",
                boundaries=[0.001, 0.01, 0.05, 0.25, 1.0, 5.0, 30.0]),
        )
    return _metrics


def _account(path: str, nbytes: int, seconds: Optional[float] = None) -> None:
    with _stats_lock:
        _stats[path] = _stats.get(path, 0) + nbytes
    try:
        bytes_total, pull_seconds = _metric_handles()
        if nbytes:
            bytes_total.inc(nbytes, tags={"path": path})
        if seconds is not None:
            pull_seconds.observe(seconds)
    except Exception:
        pass  # metrics must never fail a transfer


def transfer_stats() -> Dict[str, int]:
    """Snapshot of this process's transfer byte counters, by path."""
    with _stats_lock:
        return dict(_stats)


# ---------------------------------------------------------------- pull client

_agent_addr_cache: Dict[str, Tuple[str, int]] = {}  # node_id -> (host, port)
_conn_cache: Dict[Tuple[str, int], "object"] = {}  # addr -> CoreClient
_cache_lock = threading.Lock()

# Pooled blocking sockets for the streamed data plane: addr -> [socket].
# The consumer thread is synchronous anyway (it's inside get()), and a raw
# socket lets chunk payloads recv_into() the destination buffer directly —
# zero client-side assembly copies, no event-loop hop per chunk.
_sync_socks: Dict[Tuple[str, int], List["object"]] = {}


def _resolve_serving_addr(node_id: Optional[str]) -> Tuple[str, int]:
    from . import context as ctx

    with _cache_lock:
        addr = _agent_addr_cache.get(node_id or "")
    if addr is not None:
        return addr
    wc = ctx.get_worker_context()
    info = wc.client.request({"kind": "get_node_agent", "node_id": node_id})
    addr = (info["host"], int(info["port"]))
    with _cache_lock:
        _agent_addr_cache[node_id or ""] = addr
    return addr


def _serving_client(addr: Tuple[str, int]):
    from .client import CoreClient

    with _cache_lock:
        cli = _conn_cache.get(addr)
    if cli is not None:
        return cli
    cli = CoreClient(addr[0], addr[1])
    with _cache_lock:
        prev = _conn_cache.get(addr)
        if prev is not None:
            cli.close()
            return prev
        _conn_cache[addr] = cli
    return cli


def _evict_client(addr: Tuple[str, int], cli) -> None:
    with _cache_lock:
        if _conn_cache.get(addr) is cli:
            _conn_cache.pop(addr, None)
    try:
        cli.close()
    except Exception:
        pass


# ---------------------------------------------------- sync streamed client

def _sync_sock(addr: Tuple[str, int]):
    import socket

    with _cache_lock:
        pool = _sync_socks.get(addr)
        if pool:
            return pool.pop()
    sock = socket.create_connection(addr, timeout=PULL_CHUNK_TIMEOUT_S)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _return_sock(addr: Tuple[str, int], sock) -> None:
    with _cache_lock:
        _sync_socks.setdefault(addr, []).append(sock)


def _sock_frame(msg: Dict[str, Any]) -> bytes:
    from . import protocol

    data = protocol.dumps(msg)
    return protocol._LEN.pack(len(data)) + data


def _recv_exact_into(sock, mv: memoryview) -> None:
    while mv.nbytes:
        n = sock.recv_into(mv)
        if n == 0:
            raise ConnectionError("pull connection closed mid-stream")
        mv = mv[n:]


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


class _PullPartial(ConnectionError):
    """A streamed pull died mid-flight; `received` bytes landed
    contiguously (in-order TCP stream), so the caller resumes there."""

    def __init__(self, received: int, cause: BaseException):
        super().__init__(f"pull interrupted after {received} bytes: {cause!r}")
        self.received = received
        self.cause = cause


def _sync_stream_pull(addr: Tuple[str, int], loc: ObjectLocation,
                      mv: memoryview, offset: int, length: int) -> None:
    """Stream [offset, offset+length) into `mv` over a pooled blocking
    socket: one pull_stream request, then chunk payloads recv_into() the
    destination directly (raw-tail frames — no pickle, no assembly copy).
    Raises _PullPartial carrying the contiguous progress on failure."""
    from . import protocol

    sid = secrets.token_hex(8)
    received = 0
    try:
        sock = _sync_sock(addr)
    except OSError as e:
        raise _PullPartial(0, e) from e
    credit = _sock_frame({"kind": "pull_credit", "sid": sid, "n": 1})
    try:
        sock.sendall(_sock_frame({
            "kind": "pull_stream", "sid": sid, "loc": loc,
            "offset": offset, "length": length,
            "chunk": flags.get("RTPU_PULL_CHUNK"),
            "window": flags.get("RTPU_PULL_WINDOW"),
            "rid": 1,
        }))
        while True:
            (n,) = protocol._LEN.unpack(_recv_exact(sock, 8))
            if n & protocol._RAW_BIT:
                n &= ~protocol._RAW_BIT
                (raw_len,) = protocol._LEN.unpack(_recv_exact(sock, 8))
                header = _recv_exact(sock, n - 8 - raw_len)
                msg = protocol.loads(header)
                if msg.get("kind") != "pull_data" or msg.get("sid") != sid:
                    _recv_exact(sock, raw_len)  # drop stray frame
                    continue
                rel = msg["off"] - offset
                if rel != received or rel + raw_len > length:
                    raise ConnectionError(
                        f"pull chunk out of order at {msg['off']}")
                _recv_exact_into(sock, mv[rel : rel + raw_len])
                received += raw_len
                sock.sendall(credit)
                continue
            msg = protocol.loads(_recv_exact(sock, n))
            if msg.get("kind") == "__response__":
                if msg.get("error") is not None:
                    raise msg["error"]
                if received != length:
                    raise ConnectionError(
                        f"pull ended short: {received}/{length} bytes")
                _return_sock(addr, sock)
                return
            # Unrelated push on a pooled socket (shouldn't happen): skip.
    except _PullPartial:
        raise
    except BaseException as e:  # noqa: BLE001 — progress survives as resume point
        try:
            sock.close()
        except Exception:
            pass
        raise _PullPartial(received, e) from e


def _candidate_addrs(loc: ObjectLocation) -> List[Tuple[str, int]]:
    """Pull sources for one location, best first: the producing process's
    own server (worker-serving), then the host agent for its node."""
    out: List[Tuple[str, int]] = []
    if loc.serve_addr and flags.get("RTPU_WORKER_SERVE"):
        host, _, port = loc.serve_addr.rpartition(":")
        try:
            out.append((host, int(port)))
        except ValueError:
            pass
    try:
        agent = _resolve_serving_addr(loc.node_id)
        if agent not in out:
            out.append(agent)
    except Exception:
        pass  # controller unreachable for the moment: producer may still work
    return out


def _serial_range(addr: Tuple[str, int], loc: ObjectLocation,
                  mv: memoryview, offset: int, length: int) -> None:
    """The pre-stream pull loop: one request/response round trip per chunk.
    Kept as the RTPU_PULL_STREAM=0 path and the measured baseline."""
    cli = _serving_client(addr)
    end = offset + length
    off = offset
    chunk = flags.get("RTPU_PULL_CHUNK")
    while off < end:
        n = min(chunk, end - off)
        try:
            data = cli.request(
                {"kind": "pull_chunk", "loc": loc, "offset": off,
                 "length": n},
                timeout=PULL_CHUNK_TIMEOUT_S,
            )
        except Exception:
            _evict_client(addr, cli)
            raise
        if not data:
            raise ConnectionError(
                f"short pull of object {loc.object_id[:8]} at offset {off}")
        mv[off - offset : off - offset + len(data)] = data
        off += len(data)


def _pull_span(sources: List[ObjectLocation], mv: memoryview,
               offset: int, length: int, streamed: bool) -> None:
    """Fill one byte span, failing over producer -> agent -> next replica
    and RESUMING at the verified offset after each failure (a mid-pull
    worker death costs the tail, not the whole object — the in-order
    stream makes the received count a contiguous high-water mark)."""
    last_err: Optional[BaseException] = None
    done = 0
    for src in sources:
        for addr in _candidate_addrs(src):
            if done >= length:
                return
            base = offset + done
            sub = memoryview(mv)[done:length]
            try:
                if streamed:
                    _sync_stream_pull(addr, src, sub, base, length - done)
                else:
                    _serial_range(addr, src, sub, base, length - done)
                done = length
            except _PullPartial as e:
                done += e.received
                last_err = e.cause
                continue
            except Exception as e:  # noqa: BLE001 — retry from the next source
                last_err = e
                continue
            return
    raise ConnectionError(
        f"pull of object {sources[0].object_id[:8]} failed at offset "
        f"{offset + done}: {last_err!r}") from last_err


def fetch_remote_value(loc: ObjectLocation):
    """Pull a remote object's bytes from its producer/replica hosts and
    decode. Streamed (one request, chunks back-to-back under a credit
    window) with parallel range-splitting across replica hosts; serial
    per-chunk under RTPU_PULL_STREAM=0. Failures fail over producer ->
    host agent -> replicas with offset resume; exhausting every source
    raises ConnectionError so the caller's refresh path re-resolves (and
    possibly lineage-reconstructs) the object."""
    t0 = time.perf_counter()
    streamed = bool(flags.get("RTPU_PULL_STREAM"))
    buf = bytearray(loc.size)
    mv = memoryview(buf)
    sources = [loc] + [r for r in (loc.replicas or ())
                       if r.inline is None and not r.is_error]
    fanout = min(len(sources), max(1, flags.get("RTPU_PULL_PARALLEL")),
                 max(1, loc.size // _PARALLEL_MIN_SPAN))
    if not streamed or fanout <= 1:
        _pull_span(sources, mv, 0, loc.size, streamed)
    else:
        # Split the byte range across source hosts; each span prefers a
        # different source first but can fail over to any of them.
        span = (loc.size + fanout - 1) // fanout
        spans = []
        for i in range(fanout):
            a = i * span
            b = min(loc.size, a + span)
            if a >= b:
                continue
            order = sources[i % len(sources):] + sources[: i % len(sources)]
            spans.append((order, a, b - a))
        errs: List[BaseException] = []

        def run(order, a, n):
            try:
                _pull_span(order, memoryview(buf)[a:a + n], a, n, True)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=run, args=s, daemon=True)
                   for s in spans[1:]]
        for t in threads:
            t.start()
        run(*spans[0])
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
    _account("stream" if streamed else "serial", loc.size,
             time.perf_counter() - t0)
    return decode_value(loc, buf)


def reset_transfer_caches() -> None:
    """Drop cached agent addresses/connections (shutdown / re-init)."""
    with _cache_lock:
        conns = list(_conn_cache.values())
        _conn_cache.clear()
        _agent_addr_cache.clear()
        socks = [s for pool in _sync_socks.values() for s in pool]
        _sync_socks.clear()
    for c in conns:
        try:
            c.close()
        except Exception:
            pass
    for s in socks:
        try:
            s.close()
        except Exception:
            pass


# ----------------------------------------------------------------- pull server
# Shared by every serving process: host agents (peer + controller conns),
# the controller (head-host objects), workers (direct server) and drivers
# (ref server). `pull_chunk` is the one-shot range read; `pull_stream`
# ships a whole range back-to-back under a credit window.

_server_credits: Dict[Tuple[int, str], asyncio.Semaphore] = {}


async def handle_pull_server_message(conn, msg: Dict[str, Any]) -> Any:
    kind = msg["kind"]
    if kind == "pull_chunk":
        return read_location_range(msg["loc"], msg["offset"], msg["length"])
    if kind == "pull_credit":
        sem = _server_credits.get((id(conn), msg["sid"]))
        if sem is not None:
            for _ in range(int(msg.get("n", 1))):
                sem.release()
        return None
    if kind == "pull_stream":
        return await _serve_pull_stream(conn, msg)
    raise ValueError(f"pull server: unknown message kind {kind!r}")


async def _serve_pull_stream(conn, msg: Dict[str, Any]) -> Dict[str, Any]:
    from .protocol import testing_delay_s

    loc: ObjectLocation = msg["loc"]
    off = int(msg.get("offset", 0))
    end = off + int(msg.get("length", loc.size - off))
    chunk = int(msg.get("chunk") or flags.get("RTPU_PULL_CHUNK"))
    window = max(1, int(msg.get("window") or flags.get("RTPU_PULL_WINDOW")))
    sid = msg["sid"]
    key = (id(conn), sid)
    sem = _server_credits[key] = asyncio.Semaphore(window)
    sent = 0
    try:
        while off < end:
            await asyncio.wait_for(sem.acquire(), PULL_CHUNK_TIMEOUT_S)
            n = min(chunk, end - off)
            # Zero-copy serve: the shm/arena view goes straight to the
            # transport (the pin drops once the write returns — by then
            # the bytes are sent or buffered). Raw-tail frames then skip
            # pickle on both ends: per-byte copy count is what bounds
            # GB/s on a CPU-bound host, not the socket.
            view, release = read_location_view(loc, off, n)
            try:
                if len(view) != n:
                    raise ConnectionError(
                        f"short read serving {loc.object_id[:8]} at {off}")
                d = testing_delay_s("pull_data")  # chaos: per-chunk pacing
                if d:
                    await asyncio.sleep(d)
                await conn.send_with_raw(
                    {"kind": "pull_data", "sid": sid, "off": off}, view)
            finally:
                release()
            off += n
            sent += n
    finally:
        _server_credits.pop(key, None)
    return {"ok": True, "sent": sent}


# ------------------------------------------------------------ replicate chain
# One-hop broadcast: the source pushes chunks to the first hop; every hop
# writes locally and forwards downstream while still receiving (pipelined),
# so the source ships each byte once regardless of fan-out. Used by the
# controller (head-host sources / head-node sinks) and host agents.

_sinks: Dict[str, Dict[str, Any]] = {}  # bid -> hop state
_push_credits: Dict[Tuple[int, str], asyncio.Semaphore] = {}


class ReplicaSink:
    """Local storage writer for one incoming replica: prefers the node
    arena, falls back to a per-object shm segment, then a spill file —
    the same layouts every read path already understands."""

    def __init__(self, src: ObjectLocation, node_id: str):
        from multiprocessing import shared_memory

        from . import native_store
        from .object_store import (_arena_oid, _untrack, current_host_id,
                                   spill_dir)

        self.src = src
        self.node_id = node_id
        self.host_id = current_host_id()
        self._view = None
        self._arena = None
        self._seg = None
        self._file = None
        self._spill_path = None
        arena = native_store.get_arena()
        if arena is not None:
            oid = _arena_oid(src.object_id)
            view = arena.create_object(oid, src.size)
            if view is not None:
                self._arena, self._arena_oid, self._view = arena, oid, view
                return
        try:
            name = "rtpu_" + secrets.token_hex(8)
            seg = shared_memory.SharedMemory(
                name=name, create=True, size=max(src.size, 1))
            _untrack(name)
            self._seg, self._view = seg, seg.buf
            return
        except OSError:
            pass
        import os

        self._spill_path = os.path.join(
            spill_dir(), f"{src.object_id[:32]}.rep.bin")
        self._file = open(self._spill_path, "wb")
        self._file.truncate(src.size)

    def write(self, off: int, data) -> None:
        if self._view is not None:
            self._view[off : off + len(data)] = data
        else:
            self._file.seek(off)
            self._file.write(data)

    def finish(self) -> ObjectLocation:
        import dataclasses as _dc

        src = self.src
        common = dict(
            object_id=src.object_id, size=src.size, node_id=self.node_id,
            buffers=list(src.buffers), pickle_off=src.pickle_off,
            pickle_len=src.pickle_len, host_id=self.host_id)
        if self._arena is not None:
            del self._view
            self._arena.seal(self._arena_oid)
            return ObjectLocation(arena=self._arena.name,
                                  arena_oid=self._arena_oid, **common)
        if self._seg is not None:
            self._seg.close()
            return ObjectLocation(shm_name=self._seg.name, **common)
        self._file.close()
        return ObjectLocation(spill_path=self._spill_path, **common)

    def abort(self) -> None:
        import os

        try:
            if self._arena is not None:
                del self._view
                self._arena.delete(self._arena_oid, force=True)
            elif self._seg is not None:
                name = self._seg.name
                self._seg.close()
                from .object_store import free_segment

                free_segment(name)
            elif self._file is not None:
                self._file.close()
                os.unlink(self._spill_path)
        except Exception:
            pass


async def push_replicate_chain(loc: ObjectLocation,
                               chain: List[Dict[str, Any]],
                               bid: str,
                               chunk: Optional[int] = None,
                               window: Optional[int] = None) -> int:
    """Source side of a broadcast: stream `loc`'s bytes to the first hop
    (which forwards down `chain[1:]`). Returns bytes shipped — each byte
    leaves the source exactly once, however long the chain is."""
    from . import protocol
    from .protocol import testing_delay_s

    chunk = chunk or flags.get("RTPU_PULL_CHUNK")
    window = max(1, window or flags.get("RTPU_PULL_WINDOW"))
    first = chain[0]

    async def on_msg(conn, msg):
        if msg.get("kind") == "replicate_credit":
            sem = _push_credits.get((id(conn), msg["bid"]))
            if sem is not None:
                for _ in range(int(msg.get("n", 1))):
                    sem.release()
        return None

    conn = await protocol.connect(first["host"], int(first["port"]),
                                  handler=on_msg, name="replicate-push")
    sem = _push_credits[(id(conn), bid)] = asyncio.Semaphore(window)
    sent = 0
    try:
        await conn.request(
            {"kind": "replicate_begin", "bid": bid, "loc": loc,
             "chain": chain[1:], "window": window}, timeout=30)
        off = 0
        while off < loc.size:
            await asyncio.wait_for(sem.acquire(), PULL_CHUNK_TIMEOUT_S)
            n = min(chunk, loc.size - off)
            view, release = read_location_view(loc, off, n)
            try:
                d = testing_delay_s("replicate_chunk")  # chaos pacing
                if d:
                    await asyncio.sleep(d)
                await conn.send_with_raw(
                    {"kind": "replicate_chunk", "bid": bid, "off": off}, view)
            finally:
                release()
            off += n
            sent += n
        await conn.request({"kind": "replicate_end", "bid": bid}, timeout=60)
    finally:
        _push_credits.pop((id(conn), bid), None)
        try:
            await conn.close()
        except Exception:
            pass
    _account("broadcast", sent)
    return sent


async def handle_replicate_message(conn, msg: Dict[str, Any], *,
                                   node_id: str, report) -> Any:
    """One chain hop: write incoming chunks locally AND forward them
    downstream while the upstream is still sending (pipelined). `report`
    is an async callable(payload) delivering replica_added to the
    controller when the local copy is sealed."""
    kind = msg["kind"]
    bid = msg["bid"]
    if kind == "replicate_begin":
        sink = await asyncio.to_thread(ReplicaSink, msg["loc"], node_id)
        st = _sinks[bid] = {
            "sink": sink, "loc": msg["loc"], "size": msg["loc"].size,
            "received": 0, "forwarded": 0,
            "done": asyncio.Event(), "fwd_done": asyncio.Event(),
            "next": None, "next_sem": None,
            "window": max(1, int(msg.get("window", 8))),
        }
        chain = msg.get("chain") or []
        if st["size"] == 0:
            st["done"].set()
            st["fwd_done"].set()
        if chain:
            from . import protocol

            async def on_down(dconn, dmsg):
                if dmsg.get("kind") == "replicate_credit":
                    sem = _push_credits.get((id(dconn), dmsg["bid"]))
                    if sem is not None:
                        for _ in range(int(dmsg.get("n", 1))):
                            sem.release()
                return None

            nxt = chain[0]
            dconn = await protocol.connect(
                nxt["host"], int(nxt["port"]), handler=on_down,
                name="replicate-fwd")
            st["next"] = dconn
            st["next_sem"] = _push_credits[(id(dconn), bid)] = \
                asyncio.Semaphore(st["window"])
            await dconn.request(
                {"kind": "replicate_begin", "bid": bid, "loc": msg["loc"],
                 "chain": chain[1:], "window": st["window"]}, timeout=30)
        else:
            st["fwd_done"].set()
        return {"ok": True}
    st = _sinks.get(bid)
    if st is None:
        raise ValueError(f"replicate: unknown broadcast {bid!r}")
    if kind == "replicate_chunk":
        data = msg["data"]
        # Synchronous local write BEFORE any await: chunk handlers are
        # spawned in arrival order, so writes stay ordered and complete
        # exactly when `received` says they do.
        st["sink"].write(msg["off"], data)
        st["received"] += len(data)
        if st["received"] >= st["size"]:
            st["done"].set()
        if st["next"] is not None:
            await asyncio.wait_for(st["next_sem"].acquire(),
                                   PULL_CHUNK_TIMEOUT_S)
            await st["next"].send_with_raw(
                {"kind": "replicate_chunk", "bid": bid, "off": msg["off"]},
                data)
            st["forwarded"] += len(data)
            if st["forwarded"] >= st["size"]:
                st["fwd_done"].set()
        # Upstream credit only after the local write and the forward are
        # both enqueued: chain backpressure propagates to the source.
        await conn.send({"kind": "replicate_credit", "bid": bid, "n": 1})
        return None
    if kind == "replicate_end":
        try:
            await asyncio.wait_for(st["done"].wait(), 120)
            loc2 = await asyncio.to_thread(st["sink"].finish)
            try:
                await report({"kind": "replica_added", "bid": bid,
                              "object_id": st["loc"].object_id, "loc": loc2,
                              "node_id": node_id,
                              "bytes_in": st["received"]})
            except Exception:
                pass
            if st["next"] is not None:
                try:
                    await asyncio.wait_for(st["fwd_done"].wait(), 120)
                    await st["next"].request(
                        {"kind": "replicate_end", "bid": bid}, timeout=60)
                except Exception:
                    pass  # downstream failure is re-routed by the controller
            return {"ok": True}
        except asyncio.TimeoutError:
            st["sink"].abort()
            raise ConnectionError(
                f"replica of {st['loc'].object_id[:8]} incomplete: "
                f"{st['received']}/{st['size']} bytes")
        finally:
            nxt = st.get("next")
            if nxt is not None:
                _push_credits.pop((id(nxt), bid), None)
                try:
                    await nxt.close()
                except Exception:
                    pass
            _sinks.pop(bid, None)
    raise ValueError(f"replicate: unknown message kind {kind!r}")


PULL_SERVER_KINDS = ("pull_chunk", "pull_stream", "pull_credit")
REPLICATE_KINDS = ("replicate_begin", "replicate_chunk", "replicate_end")
