"""Inter-node object transfer: chunked pull of object bytes over TCP.

Role-equivalent to the reference's object manager push/pull protocol
(ray: src/ray/object_manager/object_manager.h, object_manager.proto Push/Pull
chunked transfer), collapsed to a pull-only design: the consumer asks the
node that *produced* an object for byte ranges and reassembles locally.

Serving side: `read_location_range(loc, offset, length)` — runs on any
process on the producer's host (the host agent, or the controller for the
head node); it attaches the arena / shm segment named in the location and
returns raw bytes. No per-agent object directory is needed: the
ObjectLocation itself is the capability.

Consumer side: `fetch_remote_value(loc)` — resolves the producer node's
serving address via the controller (cached), pulls `PULL_CHUNK`-sized ranges,
and unpickles with the out-of-band buffer table from the location.
"""
from __future__ import annotations

import pickle
import threading
from typing import Dict, Optional, Tuple

from .object_store import ObjectLocation

PULL_CHUNK = 4 * 1024 * 1024
# Per-chunk pull deadline: generous for a loaded host, small enough that a
# dead peer turns into a refresh instead of a hung get().
PULL_CHUNK_TIMEOUT_S = 20.0


def read_location_range(loc: ObjectLocation, offset: int, length: int) -> bytes:
    """Serve `length` bytes at `offset` of the object at `loc` (local host)."""
    if loc.inline is not None:
        return bytes(loc.inline[offset : offset + length])
    if loc.spill_path is not None:
        with open(loc.spill_path, "rb") as f:
            f.seek(offset)
            return f.read(length)
    if loc.arena is not None:
        from . import native_store

        arena = native_store.get_arena()
        if arena is None or arena.name != loc.arena:
            arena = native_store.attach_named(loc.arena)
        if arena is None:
            raise RuntimeError(f"cannot attach arena {loc.arena!r} to serve pull")
        view = arena.get(loc.arena_oid)
        if view is None:
            raise KeyError(f"object {loc.object_id[:8]} missing from arena")
        try:
            return bytes(view[offset : offset + length])
        finally:
            del view
            arena.release(loc.arena_oid)
    assert loc.shm_name is not None
    from .object_store import _segments

    seg = _segments.attach(loc.shm_name)
    return bytes(seg.buf[offset : offset + length])


def decode_value(loc: ObjectLocation, buf: bytes):
    """Unpickle an object's assembled bytes using the location's layout."""
    data = buf[loc.pickle_off : loc.pickle_off + loc.pickle_len]
    mv = memoryview(buf)
    bufs = [mv[off : off + n] for off, n in loc.buffers]
    return pickle.loads(data, buffers=bufs)


# ---------------------------------------------------------------- pull client

_agent_addr_cache: Dict[str, Tuple[str, int]] = {}  # node_id -> (host, port)
_conn_cache: Dict[Tuple[str, int], "object"] = {}  # addr -> CoreClient
_cache_lock = threading.Lock()


def _resolve_serving_addr(node_id: Optional[str]) -> Tuple[str, int]:
    from . import context as ctx

    with _cache_lock:
        addr = _agent_addr_cache.get(node_id or "")
    if addr is not None:
        return addr
    wc = ctx.get_worker_context()
    info = wc.client.request({"kind": "get_node_agent", "node_id": node_id})
    addr = (info["host"], int(info["port"]))
    with _cache_lock:
        _agent_addr_cache[node_id or ""] = addr
    return addr


def _serving_client(addr: Tuple[str, int]):
    from .client import CoreClient

    with _cache_lock:
        cli = _conn_cache.get(addr)
    if cli is not None:
        return cli
    cli = CoreClient(addr[0], addr[1])
    with _cache_lock:
        prev = _conn_cache.get(addr)
        if prev is not None:
            cli.close()
            return prev
        _conn_cache[addr] = cli
    return cli


def fetch_remote_value(loc: ObjectLocation):
    """Pull a remote object's bytes from its producer host and decode.

    Every chunk request carries a timeout and any failure evicts the
    cached connection: location caches mean a pull can target a host that
    died since the location was learned, and an unbounded request there
    hangs the whole get() instead of letting the caller's refresh path
    re-resolve (and possibly lineage-reconstruct) the object."""
    addr = _resolve_serving_addr(loc.node_id)
    cli = _serving_client(addr)
    buf = bytearray(loc.size)
    off = 0
    while off < loc.size:
        n = min(PULL_CHUNK, loc.size - off)
        try:
            chunk = cli.request(
                {"kind": "pull_chunk", "loc": loc, "offset": off,
                 "length": n},
                timeout=PULL_CHUNK_TIMEOUT_S,
            )
        except Exception as e:
            with _cache_lock:
                if _conn_cache.get(addr) is cli:
                    _conn_cache.pop(addr, None)
            try:
                cli.close()
            except Exception:
                pass
            raise ConnectionError(
                f"pull of object {loc.object_id[:8]} from {addr} failed "
                f"at offset {off}: {e!r}") from e
        if not chunk:
            raise ConnectionError(
                f"short pull of object {loc.object_id[:8]} at offset {off}"
            )
        buf[off : off + len(chunk)] = chunk
        off += len(chunk)
    return decode_value(loc, bytes(buf))


def reset_transfer_caches() -> None:
    """Drop cached agent addresses/connections (shutdown / re-init)."""
    with _cache_lock:
        conns = list(_conn_cache.values())
        _conn_cache.clear()
        _agent_addr_cache.clear()
    for c in conns:
        try:
            c.close()
        except Exception:
            pass
