"""Durable actor checkpoints: record format + host-local file store.

Crash-consistent actor fault tolerance (reference: the actor checkpointing
story of the Ray paper, 1712.05889 §4.2.3, and gcs_actor_manager restart
semantics): an actor's hosting worker periodically serializes the live
instance TOGETHER with its exactly-once call journal and a monotonic epoch
into one record. The record is written to a host-local file (cheap, survives
worker SIGKILL) and an async copy ships to the controller (survives whole-
node loss). A crash restart restores the newest reachable record instead of
re-running the constructor; the journal inside it lets retried calls
short-circuit to their published results instead of re-executing.

The same record format is used by drain-migration snapshots
(worker._snapshot_actor), so a migrated replayable actor keeps its dedup
journal — ``decode`` also accepts the legacy raw-instance blobs those
snapshots used to carry.
"""
from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu import flags

RECORD_VERSION = 1

# File name shape: <actor_id>.<epoch zero-padded>.ckpt — lexicographic order
# IS epoch order, so "newest" is one sorted listing.
_SUFFIX = ".ckpt"


def checkpoint_dir() -> str:
    d = flags.get("RTPU_CHECKPOINT_DIR")
    if not d:
        d = os.path.join(tempfile.gettempdir(), "rtpu_checkpoints")
    os.makedirs(d, exist_ok=True)
    return d


def encode(instance: Any, journal: Dict[str, Dict[int, Any]],
           epoch: int) -> bytes:
    """One checkpoint record: instance + exactly-once journal + epoch."""
    return cloudpickle.dumps({
        "v": RECORD_VERSION,
        "epoch": int(epoch),
        "instance": instance,
        "journal": journal,
    })


def decode(blob: bytes) -> Dict[str, Any]:
    """Record dict from a blob; legacy raw-instance blobs (pre-checkpoint
    drain snapshots) decode to an epoch-0 record with an empty journal."""
    obj = cloudpickle.loads(blob)
    if isinstance(obj, dict) and obj.get("v") == RECORD_VERSION \
            and "instance" in obj:
        obj.setdefault("journal", {})
        obj.setdefault("epoch", 0)
        return obj
    return {"v": 0, "epoch": 0, "instance": obj, "journal": {}}


def _path(actor_id: str, epoch: int) -> str:
    return os.path.join(checkpoint_dir(),
                        f"{actor_id}.{int(epoch):020d}{_SUFFIX}")


def write_local(actor_id: str, epoch: int, blob: bytes) -> str:
    """Atomically write one epoch's record; older epochs of the same actor
    are pruned (the newest record subsumes them)."""
    path = _path(actor_id, epoch)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    prune_local(actor_id, keep_epoch=epoch)
    return path


def _list_local(actor_id: str):
    d = checkpoint_dir()
    prefix = actor_id + "."
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith(prefix) and n.endswith(_SUFFIX)]
    except OSError:
        return []
    out = []
    for n in names:
        try:
            epoch = int(n[len(prefix):-len(_SUFFIX)])
        except ValueError:
            continue
        out.append((epoch, os.path.join(d, n)))
    return sorted(out)


def newest_local(actor_id: str) -> Optional[Tuple[int, bytes]]:
    """(epoch, blob) of the newest readable local record, or None."""
    for epoch, path in reversed(_list_local(actor_id)):
        try:
            with open(path, "rb") as f:
                return epoch, f.read()
        except OSError:
            continue
    return None


def prune_local(actor_id: str, keep_epoch: Optional[int] = None) -> None:
    """Delete local records older than ``keep_epoch`` (all, when None —
    actor retired for good)."""
    for epoch, path in _list_local(actor_id):
        if keep_epoch is not None and epoch >= keep_epoch:
            continue
        try:
            os.unlink(path)
        except OSError:
            pass
