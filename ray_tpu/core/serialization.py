"""ObjectRef and argument serialization.

The reference threads ObjectRefs in-band through cloudpickle with an ownership
sidecar (ray: python/ray/_private/serialization.py); here an ObjectRef pickles
to its id and reconstructs bound to whatever process deserializes it. Top-level
task arguments that are ObjectRefs are replaced by ArgRef markers and become
scheduling dependencies (values are resolved worker-side before execution);
nested refs travel as refs — the same semantics as the reference.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import cloudpickle

from .ids import ObjectID


class ObjectRef:
    """A distributed future. `ray_tpu.get(ref)` resolves it."""

    __slots__ = ("object_id",)

    def __init__(self, object_id: str):
        self.object_id = object_id

    def hex(self) -> str:
        return self.object_id

    def __reduce__(self):
        return (ObjectRef, (self.object_id,))

    def __hash__(self) -> int:
        return hash(self.object_id)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self) -> str:
        return f"ObjectRef({self.object_id[:16]})"

    # Allow `await ref` inside async code paths (parity with ray's awaitable refs).
    def __await__(self):
        from . import api

        yield
        return api.get(self)


@dataclass(frozen=True)
class ArgRef:
    """Marker for a top-level ObjectRef argument (resolved before execution)."""

    index: Any
    object_id: str


def pack_args(args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Tuple[bytes, List[str]]:
    """Replace top-level ObjectRefs with ArgRef markers; return (blob, dep ids)."""
    deps: List[str] = []

    def sub(i: Any, v: Any) -> Any:
        if isinstance(v, ObjectRef):
            deps.append(v.object_id)
            return ArgRef(i, v.object_id)
        return v

    new_args = tuple(sub(i, a) for i, a in enumerate(args))
    new_kwargs = {k: sub(k, v) for k, v in kwargs.items()}
    blob = cloudpickle.dumps((new_args, new_kwargs))
    return blob, deps
