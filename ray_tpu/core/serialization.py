"""ObjectRef and argument serialization.

The reference threads ObjectRefs in-band through cloudpickle with an ownership
sidecar (ray: python/ray/_private/serialization.py); here an ObjectRef pickles
to its id and reconstructs bound to whatever process deserializes it. Top-level
task arguments that are ObjectRefs are replaced by ArgRef markers and become
scheduling dependencies (values are resolved worker-side before execution);
nested refs travel as refs — the same semantics as the reference.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import cloudpickle

from . import ownership as _ownership
from .ids import ObjectID

# Thread-local nested-ref capture: while a payload is being pickled, every
# ObjectRef it contains registers itself here so the serializing process can
# pin it for the stored object's benefit (ownership.pin_nested).
_capture = threading.local()


@contextmanager
def capture_nested_refs(out: List["ObjectRef"]):
    prev = getattr(_capture, "refs", None)
    _capture.refs = out
    try:
        yield out
    finally:
        _capture.refs = prev


class ObjectRef:
    """A distributed future. `ray_tpu.get(ref)` resolves it.

    Handles participate in distributed ownership (reference:
    reference_count.h:35): construction/destruction adjust the process-local
    count in core.ownership, which registers this process as a borrower with
    the owner on the first handle and reports the drop on the last. ``owner``
    is the owning process's ref-channel address ("host:port|token", empty
    when ownership tracking is off) and travels with the pickle."""

    __slots__ = ("object_id", "owner")

    def __init__(self, object_id: str, owner: str = ""):
        self.object_id = object_id
        self.owner = owner
        _ownership.on_ref_created(object_id, owner)

    def hex(self) -> str:
        return self.object_id

    def __del__(self):
        try:
            _ownership.on_ref_deleted(self.object_id)
        except Exception:
            pass  # interpreter teardown: modules may be half-gone

    def __reduce__(self):
        cap = getattr(_capture, "refs", None)
        if cap is not None:
            cap.append(self)
        owner = self.owner or _ownership.owner_addr_for(self.object_id)
        return (ObjectRef, (self.object_id, owner))

    def __hash__(self) -> int:
        return hash(self.object_id)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self) -> str:
        return f"ObjectRef({self.object_id[:16]})"

    # Allow `await ref` inside async code paths (parity with ray's awaitable refs).
    def __await__(self):
        from . import api

        yield
        return api.get(self)


@dataclass(frozen=True)
class ArgRef:
    """Marker for a top-level ObjectRef argument (resolved before execution)."""

    index: Any
    object_id: str


def pack_args(
    args: Tuple[Any, ...], kwargs: Dict[str, Any]
) -> Tuple[bytes, List[str], List["ObjectRef"]]:
    """Replace top-level ObjectRefs with ArgRef markers; return
    (blob, dep ids, nested refs).

    Nested refs (inside containers) are captured during pickling: they are
    NOT scheduling dependencies (the task starts without their values — the
    reference's semantics), but the submitter must hold them for the life of
    the in-flight spec exactly like deps, or the only handle dying right
    after submit frees an object the spec still carries (reference: the
    ReferenceCounter counts ids serialized into a task spec)."""
    if not args and not kwargs:
        # Argument-less calls (ubiquitous in fan-out waves) share one
        # constant blob: no cloudpickle pass, no capture scope.
        return _EMPTY_ARGS_BLOB, [], []
    deps: List[str] = []

    def sub(i: Any, v: Any) -> Any:
        if isinstance(v, ObjectRef):
            deps.append(v.object_id)
            return ArgRef(i, v.object_id)
        return v

    new_args = tuple(sub(i, a) for i, a in enumerate(args))
    new_kwargs = {k: sub(k, v) for k, v in kwargs.items()}
    nested: List[ObjectRef] = []
    with capture_nested_refs(nested):
        blob = cloudpickle.dumps((new_args, new_kwargs))
    return blob, deps, nested


_EMPTY_ARGS_BLOB = cloudpickle.dumps(((), {}))
