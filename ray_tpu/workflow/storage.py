"""Durable workflow storage: filesystem event-sourced step state.

Parity: reference python/ray/workflow/workflow_storage.py (880 LoC) —
step results, the serialized DAG, and lifecycle events are persisted so a
workflow can resume after driver/cluster death. Layout::

    <root>/<workflow_id>/
        workflow.json          # status + timestamps
        dag.pkl                # cloudpickled output DAGNode
        events.jsonl           # append-only lifecycle log
        steps/<step_id>.pkl    # checkpointed result (or exception)
        steps/<step_id>.json   # per-step state

The root defaults to ``$RTPU_WORKFLOW_STORAGE`` or
``~/.ray_tpu/workflows`` so durability survives cluster restarts (the
reference defaults to ``~/.ray/workflow_data``-style local storage too).
"""
from __future__ import annotations

from ray_tpu import flags

import json
import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle


def _write_json_atomic(path: str, obj) -> None:
    # Same tmp+replace discipline as result pkls: a crash or concurrent
    # reader must never see truncated JSON (that would make the workflow
    # unresumable).
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _rebuild_durable(kind: str, blob: bytes, options):
    from ray_tpu.core.api import ActorClass, RemoteFunction

    target = cloudpickle.loads(blob)
    return (ActorClass if kind == "actor" else RemoteFunction)(target, options)


class _DurablePickler(cloudpickle.Pickler):
    """Serialize RemoteFunction/ActorClass *by value* for storage.

    The in-flight ``__reduce__`` ships them by controller function-table id
    (cheap; survives restarts only with RTPU_STATE_PATH persistence). A
    stored workflow should carry its code, so the wrapped callable goes
    into the blob as a nested cloudpickle payload. A self-referential
    closure (recursive continuation: fn → handle → fn) terminates because
    the *nested* dump serializes the inner handle by table id.
    """

    def reducer_override(self, obj):
        from ray_tpu.core.api import ActorClass, RemoteFunction

        if isinstance(obj, RemoteFunction):
            return (_rebuild_durable,
                    ("fn", cloudpickle.dumps(obj._fn), dict(obj._options)))
        if isinstance(obj, ActorClass):
            return (_rebuild_durable,
                    ("actor", cloudpickle.dumps(obj._cls), dict(obj._options)))
        return NotImplemented


def default_storage_root() -> str:
    return flags.get(
        "RTPU_WORKFLOW_STORAGE",
        default=os.path.join(os.path.expanduser("~"), ".ray_tpu", "workflows"),
    )


class WorkflowStorage:
    def __init__(self, workflow_id: str, root: Optional[str] = None):
        self.workflow_id = workflow_id
        self.root = root or default_storage_root()
        self.dir = os.path.join(self.root, workflow_id)

    def _ensure_dir(self) -> None:
        # Created lazily on first WRITE: read-only calls (get_status on a
        # typo'd id, list_all) must not litter empty workflow dirs.
        os.makedirs(os.path.join(self.dir, "steps"), exist_ok=True)

    # -- workflow-level ----------------------------------------------------
    def save_dag(self, node: Any, name: str = "dag.pkl",
                 *, exclusive: bool = False) -> None:
        """Atomically persist the DAG (tmp + rename: a crash mid-pickle must
        never leave a truncated dag.pkl that wedges the id). With
        ``exclusive`` the publish is an os.link, which fails with
        FileExistsError if another racer already claimed the id — the
        atomic claim backing workflow.run()'s fresh-id check."""
        self._ensure_dir()
        path = os.path.join(self.dir, name)
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                _DurablePickler(f).dump(node)
            if exclusive:
                os.link(tmp, path)  # atomic create-if-absent
            else:
                os.replace(tmp, path)
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def load_dag(self, name: str = "dag.pkl") -> Any:
        with open(os.path.join(self.dir, name), "rb") as f:
            return cloudpickle.load(f)

    def has_dag(self, name: str = "dag.pkl") -> bool:
        return os.path.exists(os.path.join(self.dir, name))

    def set_status(self, status: str) -> None:
        self._ensure_dir()
        meta = self.get_meta()
        meta["status"] = status
        meta.setdefault("created_at", time.time())
        if status in ("SUCCESSFUL", "FAILED", "CANCELED"):
            meta["finished_at"] = time.time()
        _write_json_atomic(os.path.join(self.dir, "workflow.json"), meta)

    def get_meta(self) -> Dict[str, Any]:
        path = os.path.join(self.dir, "workflow.json")
        if not os.path.exists(path):
            return {}
        with open(path) as f:
            return json.load(f)

    # -- ownership / liveness ---------------------------------------------
    # A RUNNING status alone cannot distinguish "another process is driving
    # this workflow right now" from "the driver died mid-run" — both matter:
    # the first must refuse a concurrent resume (duplicate side effects),
    # the second must surface as RESUMABLE. The driving process maintains a
    # heartbeat file; liveness = heartbeat fresher than LIVENESS_S.
    HEARTBEAT_S = 2.0
    LIVENESS_S = 10.0

    def _owner_path(self) -> str:
        return os.path.join(self.dir, "owner.json")

    def touch_owner(self) -> None:
        import socket

        self._ensure_dir()
        _write_json_atomic(
            self._owner_path(),
            {"pid": os.getpid(), "host": socket.gethostname(),
             "ts": time.time()})

    def clear_owner(self) -> None:
        try:
            os.remove(self._owner_path())
        except OSError:
            pass

    def owner_alive(self) -> bool:
        try:
            with open(self._owner_path()) as f:
                ts = json.load(f).get("ts", 0)
        except (OSError, ValueError):
            return False
        return (time.time() - ts) < self.LIVENESS_S

    def request_cancel(self) -> None:
        self._ensure_dir()
        _write_json_atomic(os.path.join(self.dir, "cancel.json"),
                           {"ts": time.time()})

    def cancel_requested(self) -> bool:
        return os.path.exists(os.path.join(self.dir, "cancel.json"))

    def clear_cancel(self) -> None:
        try:
            os.remove(os.path.join(self.dir, "cancel.json"))
        except OSError:
            pass

    def log_event(self, event: str, **fields) -> None:
        self._ensure_dir()
        rec = {"ts": time.time(), "event": event, **fields}
        with open(os.path.join(self.dir, "events.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    # -- step-level --------------------------------------------------------
    def _step_paths(self, step_id: str):
        base = os.path.join(self.dir, "steps", step_id)
        return base + ".pkl", base + ".json"

    def save_step_result(self, step_id: str, value: Any,
                         *, is_exception: bool = False) -> None:
        self._ensure_dir()
        pkl, meta = self._step_paths(step_id)
        tmp = f"{pkl}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            # DurablePickler: a continuation checkpoint is a DAGNode holding
            # RemoteFunction handles — those must carry their code.
            _DurablePickler(f).dump(value)
        os.replace(tmp, pkl)  # atomic: a crash never leaves a half checkpoint
        _write_json_atomic(
            meta,
            {"state": "FAILED" if is_exception else "SUCCESSFUL",
             "ts": time.time()})

    def step_state(self, step_id: str) -> Optional[str]:
        _, meta = self._step_paths(step_id)
        if not os.path.exists(meta):
            return None
        with open(meta) as f:
            return json.load(f).get("state")

    def load_step_result(self, step_id: str) -> Any:
        pkl, _ = self._step_paths(step_id)
        with open(pkl, "rb") as f:
            return cloudpickle.load(f)

    def sub_storage(self, step_id: str) -> "WorkflowStorage":
        """Namespaced storage for a dynamic continuation of one step."""
        sub = WorkflowStorage.__new__(WorkflowStorage)
        sub.workflow_id = self.workflow_id
        sub.root = self.root
        sub.dir = os.path.join(self.dir, "steps", step_id + ".sub")
        return sub


def list_workflows(root: Optional[str] = None) -> List[Dict[str, Any]]:
    root = root or default_storage_root()
    out = []
    if not os.path.isdir(root):
        return out
    for wid in sorted(os.listdir(root)):
        meta_path = os.path.join(root, wid, "workflow.json")
        if not os.path.isfile(meta_path):
            continue  # stray files / unrelated dirs are not workflows
        with open(meta_path) as f:
            out.append({"workflow_id": wid, **json.load(f)})
    return out
