"""Workflow executor: wave-parallel DAG execution with per-step checkpoints.

Parity: reference python/ray/workflow/workflow_executor.py +
task_executor.py. Semantics kept from the reference:

- every step's *value* is checkpointed before dependents consume it, so
  resume never re-runs a completed step;
- independent branches run concurrently (ready steps are all submitted,
  completion harvested with ``api.wait``);
- a step returning a DAG node is a **continuation** (reference
  ``workflow.continuation``): the sub-DAG is executed under the step's
  namespace and its output becomes the step's value;
- ``catch_exceptions`` on a step converts its outcome to
  ``(result, None) | (None, exception)``;
- task-level ``max_retries`` rides the core runtime's retry machinery
  rather than being re-implemented here.

Step ids are assigned by deterministic topological traversal (same DAG →
same ids), which is what makes the checkpoint store addressable across
driver restarts.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.core import api
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.workflow.storage import WorkflowStorage


class WorkflowCanceled(RuntimeError):
    pass


def _catches(node: DAGNode) -> bool:
    fn_opts = getattr(getattr(node, "_remote_fn", None), "_options", {}) or {}
    return bool(getattr(node, "_options", {}).get("catch_exceptions")
                or fn_opts.get("catch_exceptions"))


def assign_step_ids(output: DAGNode) -> Dict[int, str]:
    """Stable ids: topological position + a human hint."""
    ids: Dict[int, str] = {}
    counts: Dict[str, int] = {}
    for node in output.topological():
        hint = node._name_hint()
        n = counts.get(hint, 0)
        counts[hint] = n + 1
        ids[id(node)] = f"{hint}.{n}"
    return ids


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage,
                 cancel_event: Optional[threading.Event] = None):
        self.storage = storage
        self.cancel_event = cancel_event or threading.Event()

    # The executor walks the DAG in dependency waves. ``memo`` maps node id
    # -> computed *value* (not ref): workflow steps are checkpointed at the
    # driver, so values are already local when dependents are submitted.
    def run(self, output: DAGNode, run_input=((), {})) -> Any:
        ids = assign_step_ids(output)
        nodes = output.topological()
        memo: Dict[int, Any] = {"__input__": run_input}

        # Dependency bookkeeping over checkpointable nodes.
        pending: Dict[int, DAGNode] = {id(n): n for n in nodes}
        inflight: Dict[str, tuple] = {}  # object id str -> (node, ref)

        def checkpointable(n: DAGNode) -> bool:
            return isinstance(n, (FunctionNode, ClassMethodNode))

        def deps_ready(n: DAGNode) -> bool:
            return all(id(u) in memo for u in n._upstream())

        def resolve_local(n: DAGNode) -> Any:
            """Evaluate non-task nodes (input selectors, actor creation).

            Passing the live memo is safe: every upstream is already
            resolved, so _execute_memo only reads (plus writes this node's
            own entry, which the caller overwrites with the same value).
            """
            if isinstance(n, InputNode) or isinstance(n, InputAttributeNode) \
                    or isinstance(n, MultiOutputNode) or isinstance(n, ClassNode):
                return n._execute_memo(memo)
            raise AssertionError(type(n))

        while pending:
            if self.cancel_event.is_set() or self.storage.cancel_requested():
                raise WorkflowCanceled(self.storage.workflow_id)
            progressed = False
            for nid, node in list(pending.items()):
                if not deps_ready(node):
                    continue
                step_id = ids[nid]
                if not checkpointable(node):
                    memo[nid] = resolve_local(node)
                    del pending[nid]
                    progressed = True
                    continue
                state = self.storage.step_state(step_id)
                if state == "SUCCESSFUL":
                    value = self.storage.load_step_result(step_id)
                    if isinstance(value, DAGNode):
                        # Stored continuation: drive/resume it, then apply
                        # catch wrapping to its *final* value (mirrors the
                        # fresh path below).
                        value = self._maybe_continue(step_id, value)
                        if _catches(node):
                            value = (value, None)
                    memo[nid] = value
                    del pending[nid]
                    progressed = True
                    continue
                # Submit: upstream values are plain objects in memo.
                ref = node._execute_impl(memo)
                self.storage.log_event("step_started", step=step_id)
                # Normalize num_returns variants: a list of refs (wait on
                # the first, get them all) or None for num_returns=0.
                refs = ref if isinstance(ref, list) else (
                    [] if ref is None else [ref])
                if not refs:
                    self.storage.save_step_result(step_id, None)
                    self.storage.log_event("step_finished", step=step_id)
                    memo[nid] = None
                else:
                    inflight[refs[0].object_id] = (node, ref, step_id)
                del pending[nid]
                progressed = True

            if inflight:
                first_refs = [
                    (r[1][0] if isinstance(r[1], list) else r[1])
                    for r in inflight.values()
                ]
                ready, _ = api.wait(first_refs, num_returns=1, timeout=1.0)
                for r in ready:
                    node, ref, step_id = inflight.pop(r.object_id)
                    catch = _catches(node)
                    try:
                        value = api.get(ref)
                    except Exception as e:  # step failed
                        if catch:
                            value = (None, e)
                            self.storage.save_step_result(step_id, value)
                            self.storage.log_event("step_finished",
                                                   step=step_id, caught=True)
                        else:
                            self.storage.save_step_result(
                                step_id, e, is_exception=True)
                            self.storage.log_event("step_failed", step=step_id,
                                                   error=repr(e))
                            raise
                    else:
                        if isinstance(value, DAGNode):
                            # Continuation: checkpoint the step as SUCCESSFUL
                            # with the DAG node as its value BEFORE driving
                            # the sub-DAG — a crash mid-continuation must not
                            # re-run this step's body (side effects!). Resume
                            # then re-enters the continuation via
                            # _maybe_continue on the stored DAGNode value.
                            # catch_exceptions wraps the continuation's FINAL
                            # value, not the intermediate node.
                            self.storage.save_step_result(step_id, value)
                            value = self._maybe_continue(step_id, value)
                            if catch:
                                value = (value, None)
                        else:
                            if catch:
                                value = (value, None)
                            self.storage.save_step_result(step_id, value)
                        self.storage.log_event("step_finished", step=step_id)
                    memo[id(node)] = value
                progressed = True
            elif not progressed and pending:
                raise RuntimeError(
                    f"workflow deadlock: unsatisfiable deps for "
                    f"{[ids[i] for i in pending]}")
        return memo[id(output)]

    def _maybe_continue(self, step_id: str, value: Any):
        """Execute (or resume) a dynamic continuation of a finished step.

        The continuation DAG *is* the step's checkpointed value; its own
        steps checkpoint under ``steps/<id>.sub/``, so resume re-enters
        here (via the loaded value) and skips completed sub-steps.
        """
        if not isinstance(value, DAGNode):
            return value
        sub = WorkflowExecutor(self.storage.sub_storage(step_id),
                               self.cancel_event)
        return sub.run(value)
