"""Workflow public API: run / resume / inspect durable DAG executions.

Parity: reference python/ray/workflow/api.py (``workflow.run``,
``run_async``, ``resume``, ``resume_async``, ``get_status``,
``get_output``, ``list_all``, ``cancel``, ``delete``). Authoring uses the
same ``.bind()`` DAG surface as the reference (a workflow *is* a DAG plus
durability), so any ``ray_tpu.dag`` graph is runnable here.
"""
from __future__ import annotations

import threading
import uuid
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import DAGNode
from ray_tpu.workflow.executor import WorkflowCanceled, WorkflowExecutor
from ray_tpu.workflow.storage import WorkflowStorage, list_workflows

# In-process registry of live runs so cancel() can interrupt them.
_running: Dict[str, threading.Event] = {}
_lock = threading.Lock()


def _execute(storage: WorkflowStorage, dag: DAGNode) -> Any:
    if storage.get_meta().get("status") == "RUNNING" and storage.owner_alive():
        raise RuntimeError(
            f"workflow {storage.workflow_id!r} is already being driven by "
            f"another process — concurrent execution would duplicate steps")
    cancel = threading.Event()
    with _lock:
        _running[storage.workflow_id] = cancel
    storage.clear_cancel()
    storage.touch_owner()
    hb_stop = threading.Event()

    def heartbeat():
        while not hb_stop.wait(storage.HEARTBEAT_S):
            storage.touch_owner()

    hb = threading.Thread(target=heartbeat, daemon=True,
                          name=f"wf-heartbeat-{storage.workflow_id}")
    hb.start()
    storage.set_status("RUNNING")
    try:
        result = WorkflowExecutor(storage, cancel).run(dag)
    except WorkflowCanceled:
        storage.set_status("CANCELED")
        raise
    except Exception:
        storage.set_status("FAILED")
        raise
    else:
        storage.save_step_result("__output__", result)
        storage.set_status("SUCCESSFUL")
        return result
    finally:
        hb_stop.set()
        storage.clear_owner()
        with _lock:
            _running.pop(storage.workflow_id, None)


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None) -> Any:
    """Execute a DAG durably; blocks until the final output is computed."""
    if not isinstance(dag, DAGNode):
        raise TypeError("workflow.run expects a DAG node (use .bind())")
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    st = WorkflowStorage(workflow_id, storage)
    _claim_fresh(st, dag)
    return _execute(st, dag)


def _claim_fresh(st: WorkflowStorage, dag: DAGNode) -> None:
    """Atomically claim a workflow id by publishing its DAG.

    A second run() with the same id would overwrite dag.pkl while step
    checkpoints from the OLD dag still exist; colliding step ids would then
    replay stale results into the new DAG. The reference resumes the stored
    workflow unchanged or errors; we raise and point at resume()/delete().
    The claim is an exclusive link (no check-then-act window), so two
    concurrent run() calls on one id cannot both start executing.
    """
    try:
        st.save_dag(dag, exclusive=True)
    except FileExistsError:
        raise ValueError(
            f"workflow {st.workflow_id!r} already exists "
            f"(status={st.get_meta().get('status')}). Use workflow.resume() "
            f"to continue it, or workflow.delete() before reusing the id."
        ) from None


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              storage: Optional[str] = None) -> Future:
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    st = WorkflowStorage(workflow_id, storage)
    _claim_fresh(st, dag)
    fut: Future = Future()

    def body():
        try:
            fut.set_result(_execute(st, dag))
        except BaseException as e:
            fut.set_exception(e)

    threading.Thread(target=body, daemon=True,
                     name=f"workflow-{workflow_id}").start()
    return fut


def resume(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    """Re-drive a stored workflow; completed steps load from checkpoints."""
    st = WorkflowStorage(workflow_id, storage)
    if not st.has_dag():
        raise ValueError(f"no stored workflow {workflow_id!r}")
    if st.get_meta().get("status") == "SUCCESSFUL":
        return st.load_step_result("__output__")
    return _execute(st, st.load_dag())


def resume_async(workflow_id: str, *, storage: Optional[str] = None) -> Future:
    fut: Future = Future()

    def body():
        try:
            fut.set_result(resume(workflow_id, storage=storage))
        except BaseException as e:
            fut.set_exception(e)

    threading.Thread(target=body, daemon=True).start()
    return fut


def get_status(workflow_id: str, *, storage: Optional[str] = None) -> str:
    st = WorkflowStorage(workflow_id, storage)
    status = st.get_meta().get("status")
    if status == "RUNNING" and not st.owner_alive():
        # The driving process (any process — liveness is heartbeat-based,
        # not this-process-based) died mid-run; the state is resumable.
        return "RESUMABLE"
    return status or "UNKNOWN"


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    st = WorkflowStorage(workflow_id, storage)
    if st.get_meta().get("status") != "SUCCESSFUL":
        raise ValueError(
            f"workflow {workflow_id!r} has no output "
            f"(status={st.get_meta().get('status')})")
    return st.load_step_result("__output__")


def list_all(*, storage: Optional[str] = None) -> List[Dict[str, Any]]:
    rows = list_workflows(storage)
    for r in rows:
        if r.get("status") == "RUNNING" and not WorkflowStorage(
                r["workflow_id"], storage).owner_alive():
            r["status"] = "RESUMABLE"
    return rows


def cancel(workflow_id: str, *, storage: Optional[str] = None) -> None:
    st = WorkflowStorage(workflow_id, storage)
    if not st.has_dag():
        # No such workflow: writing cancel.json would litter an empty dir.
        return
    with _lock:
        ev = _running.get(workflow_id)
    if ev is not None:
        ev.set()  # in-process: interrupt between waves immediately
    st.request_cancel()  # cross-process: the owner's executor polls this
    if not st.owner_alive() and st.get_meta().get("status") == "RUNNING":
        st.set_status("CANCELED")


def delete(workflow_id: str, *, storage: Optional[str] = None) -> None:
    import shutil

    st = WorkflowStorage(workflow_id, storage)
    shutil.rmtree(st.dir, ignore_errors=True)
