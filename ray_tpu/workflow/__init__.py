"""Durable workflows over the ray_tpu DAG layer.

Parity: reference python/ray/workflow/ — storage-backed resume,
continuations, catch_exceptions, lifecycle API.
"""
from ray_tpu.workflow.api import (
    cancel,
    delete,
    get_output,
    get_status,
    list_all,
    resume,
    resume_async,
    run,
    run_async,
)
from ray_tpu.workflow.storage import WorkflowStorage

__all__ = [
    "run",
    "run_async",
    "resume",
    "resume_async",
    "get_status",
    "get_output",
    "list_all",
    "cancel",
    "delete",
    "WorkflowStorage",
]
