"""Cluster launcher: ``rtpu up cluster.yaml`` and friends.

Parity: reference python/ray/scripts/scripts.py (up/down/attach/exec),
python/ray/autoscaler/_private/command_runner.py (SSHCommandRunner) and
_private/updater.py (NodeUpdater) — collapsed for the TPU-pod setting where
a "worker node" is a host that joins as a host agent, and redesigned around
one state file per cluster instead of the reference's tag-based rediscovery.

Config schema (YAML)::

    cluster_name: demo
    provider:
      type: local | ssh             # where nodes come from
      head_ip: 10.0.0.2             # ssh: required
      worker_ips: [10.0.0.3, ...]   # ssh: required
    auth:                           # ssh only
      ssh_user: ubuntu
      ssh_private_key: ~/.ssh/id_rsa
    head:
      port: 6380                    # 0/absent -> pick a free port
      num_cpus: 8                   # optional resource overrides
    workers:
      count: 2                      # local: processes; ssh: len(worker_ips)
      num_cpus: 4
    setup_commands:                 # run on every node before start
      - pip install -e .
    env:                            # exported to every started process
      RTPU_ARENA_SIZE: "2147483648"

``type: local`` starts every node as a local subprocess through the same
CommandRunner/NodeUpdater machinery the ssh path uses — it is both the
single-machine story and the e2e test harness for the launcher itself
(reference fake_multi_node analog).
"""
from __future__ import annotations

import json
import os
import shlex
import socket
import subprocess
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_STATE_DIR = os.path.join(tempfile.gettempdir(), "rtpu_clusters")


# ---------------------------------------------------------------------------
# config


@dataclass
class ClusterConfig:
    cluster_name: str
    provider_type: str
    head_ip: str
    worker_ips: List[str]
    head_port: int
    head_num_cpus: Optional[int]
    worker_count: int
    worker_num_cpus: Optional[int]
    setup_commands: List[str]
    env: Dict[str, str]
    ssh_user: str = ""
    ssh_key: str = ""
    raw: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "ClusterConfig":
        import yaml

        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        return cls.from_dict(doc)

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "ClusterConfig":
        name = doc.get("cluster_name")
        if not name:
            raise ValueError("cluster_name is required")
        prov = doc.get("provider") or {}
        ptype = prov.get("type", "local")
        if ptype not in ("local", "ssh"):
            raise ValueError(f"provider.type must be local|ssh, got {ptype!r}")
        head = doc.get("head") or {}
        workers = doc.get("workers") or {}
        auth = doc.get("auth") or {}
        worker_ips = list(prov.get("worker_ips") or [])
        if ptype == "ssh":
            if not prov.get("head_ip"):
                raise ValueError("provider.head_ip is required for type: ssh")
            if not auth.get("ssh_user"):
                raise ValueError("auth.ssh_user is required for type: ssh")
        count = int(workers.get("count", len(worker_ips)))
        return cls(
            cluster_name=str(name),
            provider_type=ptype,
            head_ip=prov.get("head_ip", "127.0.0.1"),
            worker_ips=worker_ips,
            head_port=int(head.get("port", 0)),
            head_num_cpus=head.get("num_cpus"),
            worker_count=count,
            worker_num_cpus=workers.get("num_cpus"),
            setup_commands=list(doc.get("setup_commands") or []),
            env={k: str(v) for k, v in (doc.get("env") or {}).items()},
            ssh_user=auth.get("ssh_user", ""),
            ssh_key=os.path.expanduser(auth.get("ssh_private_key", "")),
            raw=doc,
        )


# ---------------------------------------------------------------------------
# command runners (reference: command_runner.py CommandRunnerInterface)


class CommandRunner:
    """Run shell commands on one node."""

    def run(self, cmd: str, env: Optional[Dict[str, str]] = None,
            timeout: float = 120.0) -> str:
        raise NotImplementedError

    def run_background(self, cmd: str,
                       env: Optional[Dict[str, str]] = None) -> int:
        """Start a long-lived process; return its (remote) pid.

        ``exec`` makes the reported $! the actual command (a forked shell
        in between would absorb the later kill), and ``setsid`` gives it a
        fresh process group so teardown can sweep the node process AND
        everything it spawned (worker subprocesses) with one group kill."""
        wrapped = (f"setsid nohup sh -c {shlex.quote('exec ' + cmd)} "
                   f">/tmp/rtpu_launch_$$.log 2>&1 & echo $!")
        out = self.run(wrapped, env=env)
        return int(out.strip().splitlines()[-1])

    def kill_tree(self, pid: int) -> None:
        """Terminate a run_background process group; escalate to KILL."""
        self.run(f"kill -TERM -- -{pid} 2>/dev/null || "
                 f"kill -TERM {pid} 2>/dev/null || true; sleep 1; "
                 f"kill -KILL -- -{pid} 2>/dev/null || true", timeout=30)


class LocalCommandRunner(CommandRunner):
    """Execute on this machine (provider type local + launcher tests).

    Started nodes must import ray_tpu regardless of the operator's cwd, so
    the package's parent directory is prepended to PYTHONPATH (ssh nodes
    are expected to have their own install, reference-style)."""

    def run(self, cmd: str, env=None, timeout: float = 120.0) -> str:
        from ray_tpu import flags

        full_env = flags.child_env(**(env or {}))
        pkg_parent = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))
        full_env["PYTHONPATH"] = (
            pkg_parent + os.pathsep + full_env.get("PYTHONPATH", ""))
        proc = subprocess.run(["sh", "-c", cmd], capture_output=True,
                              text=True, timeout=timeout, env=full_env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"command failed ({proc.returncode}): {cmd}\n{proc.stderr}")
        return proc.stdout


class SSHCommandRunner(CommandRunner):
    """Reference command_runner.py:SSHCommandRunner over plain `ssh`."""

    def __init__(self, ip: str, user: str, key: str = "",
                 ssh_options: Optional[List[str]] = None):
        self.ip = ip
        self.user = user
        self.key = key
        self.ssh_options = ssh_options or [
            "-o", "StrictHostKeyChecking=no",
            "-o", "ConnectTimeout=10",
            "-o", "BatchMode=yes",
        ]

    def _base(self) -> List[str]:
        cmd = ["ssh", *self.ssh_options]
        if self.key:
            cmd += ["-i", self.key]
        cmd.append(f"{self.user}@{self.ip}" if self.user else self.ip)
        return cmd

    def run(self, cmd: str, env=None, timeout: float = 120.0) -> str:
        exports = "".join(
            f"export {k}={shlex.quote(v)}; " for k, v in (env or {}).items())
        proc = subprocess.run(
            self._base() + [exports + cmd],
            capture_output=True, text=True, timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(
                f"ssh {self.ip} failed ({proc.returncode}): "
                f"{cmd}\n{proc.stderr}")
        return proc.stdout


# ---------------------------------------------------------------------------
# node updater (reference: updater.py NodeUpdater.do_update)


class NodeUpdater:
    """Bring one node from bare to running: setup commands, then start."""

    def __init__(self, runner: CommandRunner, config: ClusterConfig):
        self.runner = runner
        self.config = config

    def setup(self) -> None:
        for cmd in self.config.setup_commands:
            self.runner.run(cmd, env=self.config.env, timeout=600)

    def start_head(self, port: int) -> int:
        cpus = self.config.head_num_cpus
        cmd = (f"{_python()} -m ray_tpu.cli start --head --port {port}"
               + (f" --num-cpus {cpus}" if cpus else ""))
        return self.runner.run_background(cmd, env=self.config.env)

    def start_worker(self, address: str) -> int:
        cpus = self.config.worker_num_cpus
        cmd = (f"{_python()} -m ray_tpu.cli start --address {address}"
               + (f" --num-cpus {cpus}" if cpus else ""))
        return self.runner.run_background(cmd, env=self.config.env)


def _python() -> str:
    import sys

    return shlex.quote(sys.executable)


# ---------------------------------------------------------------------------
# launcher


class ClusterLauncher:
    def __init__(self, config: ClusterConfig):
        self.config = config

    # -- runners ------------------------------------------------------------

    def _runner_for(self, ip: str) -> CommandRunner:
        if self.config.provider_type == "local":
            return LocalCommandRunner()
        return SSHCommandRunner(ip, self.config.ssh_user, self.config.ssh_key)

    def _worker_targets(self) -> List[str]:
        if self.config.provider_type == "local":
            return ["127.0.0.1"] * self.config.worker_count
        ips = self.config.worker_ips
        if self.config.worker_count and self.config.worker_count < len(ips):
            ips = ips[: self.config.worker_count]
        return ips

    # -- verbs --------------------------------------------------------------

    def up(self) -> Dict[str, Any]:
        cfg = self.config
        port = cfg.head_port or _free_port()
        address = f"{cfg.head_ip}:{port}"
        head_runner = self._runner_for(cfg.head_ip)
        head_up = NodeUpdater(head_runner, cfg)
        head_up.setup()
        # State is saved INCREMENTALLY — the moment anything starts, a
        # failure (head wait timeout, a worker's setup raising mid-loop)
        # must leave `down` able to find and kill what's already running,
        # not orphan live processes behind a missing state file.
        state = {
            "cluster_name": cfg.cluster_name,
            "provider_type": cfg.provider_type,
            "address": address,
            "head": {},
            "workers": [],
            "started_at": time.time(),
        }
        try:
            head_pid = head_up.start_head(port)
            state["head"] = {"ip": cfg.head_ip, "pid": head_pid}
            _save_state(cfg.cluster_name, state)
            _wait_for_head(address, timeout=30)
            for ip in self._worker_targets():
                up = NodeUpdater(self._runner_for(ip), cfg)
                up.setup()
                pid = up.start_worker(address)
                state["workers"].append({"ip": ip, "pid": pid})
                _save_state(cfg.cluster_name, state)
            _wait_for_nodes(address, 1 + len(state["workers"]), timeout=60)
        except BaseException:
            self.down()  # reap whatever already started
            raise
        return state

    def down(self) -> None:
        state = _load_state(self.config.cluster_name)
        if state is None:
            return
        for w in reversed(state.get("workers", [])):
            try:
                self._runner_for(w["ip"]).kill_tree(w["pid"])
            except Exception:
                pass
        head = state.get("head") or {}
        if head:
            try:
                self._runner_for(head["ip"]).kill_tree(head["pid"])
            except Exception:
                pass
        _delete_state(self.config.cluster_name)

    def exec(self, cmd: str, timeout: float = 600.0) -> str:
        """Run a command on the head with the cluster address exported."""
        state = _load_state(self.config.cluster_name)
        if state is None:
            raise RuntimeError(
                f"cluster {self.config.cluster_name!r} is not up")
        runner = self._runner_for(state["head"]["ip"])
        env = dict(self.config.env)
        env["RTPU_ADDRESS"] = state["address"]
        return runner.run(cmd, env=env, timeout=timeout)

    def attach_command(self) -> List[str]:
        """The interactive command `rtpu attach` should exec."""
        state = _load_state(self.config.cluster_name)
        if state is None:
            raise RuntimeError(
                f"cluster {self.config.cluster_name!r} is not up")
        if self.config.provider_type == "local":
            return ["sh", "-c",
                    f"RTPU_ADDRESS={state['address']} exec ${{SHELL:-sh}}"]
        r = SSHCommandRunner(state["head"]["ip"], self.config.ssh_user,
                             self.config.ssh_key)
        return r._base() + ["-t",
                            f"export RTPU_ADDRESS={state['address']}; "
                            f"exec $SHELL -l"]


# ---------------------------------------------------------------------------
# helpers


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_for_head(address: str, timeout: float) -> None:
    host, _, port = address.rpartition(":")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, int(port)), timeout=2):
                return
        except OSError:
            time.sleep(0.3)
    raise TimeoutError(f"head at {address} did not come up in {timeout}s")


def _wait_for_nodes(address: str, n: int, timeout: float) -> None:
    """Block until the controller reports n alive nodes."""
    from ray_tpu.core import protocol
    from ray_tpu.core.client import EventLoopThread

    host, _, port = address.rpartition(":")
    deadline = time.monotonic() + timeout
    io = EventLoopThread(name="launcher-wait")
    try:
        conn = io.call(protocol.connect(host, int(port), name="launcher"),
                       timeout=10)
        while time.monotonic() < deadline:
            state = io.call(conn.request({"kind": "cluster_state"}),
                            timeout=10)
            alive = [x for x in state.get("nodes", []) if x.get("alive")]
            if len(alive) >= n:
                return
            time.sleep(0.5)
        raise TimeoutError(
            f"only {len(alive)}/{n} nodes joined within {timeout}s")
    finally:
        io.stop()


def _state_path(name: str) -> str:
    os.makedirs(_STATE_DIR, exist_ok=True)
    return os.path.join(_STATE_DIR, f"{name}.json")


def _save_state(name: str, state: Dict[str, Any]) -> None:
    with open(_state_path(name), "w") as f:
        json.dump(state, f, indent=1)


def _load_state(name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except OSError:
        return None


def _delete_state(name: str) -> None:
    try:
        os.unlink(_state_path(name))
    except OSError:
        pass
