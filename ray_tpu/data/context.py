"""DataContext: per-driver execution knobs (reference: data/context.py
DataContext — target block sizes, execution options)."""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # Streaming executor backpressure: max concurrently running tasks per
    # map stage (reference: ConcurrencyCapBackpressurePolicy +
    # ReservationOpResourceAllocator, resource_manager.py:29).
    max_tasks_in_flight: int = 8
    # Memory-aware backpressure (reference ReservationOpResourceAllocator,
    # resource_manager.py:259): when the local object-store arena is more
    # than memory_high_water full, map stages shrink their in-flight cap to
    # memory_pressure_cap so a fast producer drains into a slow consumer
    # through bounded memory instead of filling the arena and leaning on
    # spilling. 0 disables the check.
    memory_high_water: float = 0.75
    memory_pressure_cap: int = 2
    preserve_order: bool = True
    default_batch_format: str = "numpy"
    # Shuffle fan-out (#output partitions defaults to #input blocks).
    shuffle_partitions: Optional[int] = None
    read_parallelism: int = 8

    _lock = threading.Lock()
    _current: Optional["DataContext"] = None

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current
