"""Dependency-free TFRecord + tf.train.Example parsing.

Role parity: the reference's read_tfrecords
(python/ray/data/read_api.py read_tfrecords) decodes Example protos into
columns; it leans on tensorflow/protobuf, neither of which this stack
wants at runtime. The two formats involved are small and stable:

TFRecord framing (tensorflow/core/lib/io/record_writer.h)::

    uint64 length | uint32 masked_crc(length) | bytes[length] data
    | uint32 masked_crc(data)

tf.train.Example is a protobuf ``Features { map<string, Feature> }`` where
Feature is a oneof of bytes_list / float_list / int64_list. Only the wire
types those use (varint, length-delimited, and packed/unpacked repeated
scalars) are implemented here. CRCs are not verified (the reference's fast
path skips them too).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value_bytes_or_int)."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            val, pos = _read_varint(buf, pos)
            yield field, wire, val
        elif wire == 1:  # 64-bit
            yield field, wire, buf[pos:pos + 8]
            pos += 8
        elif wire == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            yield field, wire, buf[pos:pos + ln]
            pos += ln
        elif wire == 5:  # 32-bit
            yield field, wire, buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _parse_bytes_list(buf: bytes) -> List[bytes]:
    return [v for f, w, v in _fields(buf) if f == 1 and w == 2]


def _parse_float_list(buf: bytes) -> List[float]:
    out: List[float] = []
    for f, w, v in _fields(buf):
        if f != 1:
            continue
        if w == 2:  # packed
            out.extend(struct.unpack(f"<{len(v) // 4}f", v))
        elif w == 5:
            out.append(struct.unpack("<f", v)[0])
    return out


def _parse_int64_list(buf: bytes) -> List[int]:
    out: List[int] = []
    for f, w, v in _fields(buf):
        if f != 1:
            continue
        if w == 2:  # packed varints
            pos = 0
            while pos < len(v):
                val, pos = _read_varint(v, pos)
                out.append(val)
        elif w == 0:
            out.append(v)
    return out


def _parse_feature(buf: bytes) -> Any:
    """Feature oneof: 1=bytes_list, 2=float_list, 3=int64_list."""
    for f, w, v in _fields(buf):
        if w != 2:
            continue
        if f == 1:
            vals = _parse_bytes_list(v)
        elif f == 2:
            vals = _parse_float_list(v)
        elif f == 3:
            vals = _parse_int64_list(v)
        else:
            continue
        if len(vals) == 1:
            return vals[0]
        return vals
    return None


def _parse_example(buf: bytes) -> Dict[str, Any]:
    row: Dict[str, Any] = {}
    for f, w, v in _fields(buf):  # Example: field 1 = Features
        if f != 1 or w != 2:
            continue
        for ff, fw, fv in _fields(v):  # Features: field 1 = map entry
            if ff != 1 or fw != 2:
                continue
            key = None
            feat = None
            for mf, mw, mv in _fields(fv):  # map entry: 1=key, 2=value
                if mf == 1 and mw == 2:
                    key = mv.decode("utf-8", "replace")
                elif mf == 2 and mw == 2:
                    feat = _parse_feature(mv)
            if key is not None:
                row[key] = feat
    return row


def iter_tfrecords(path: str):
    """Yield raw record payloads from a TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)  # u64 length + u32 masked crc
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"truncated record in {path}")
            f.read(4)  # data crc, unverified
            yield data


def parse_tfrecord_examples(path: str) -> Dict[str, List[Any]]:
    """File -> columnar dict (union of keys; missing values are None)."""
    rows = [_parse_example(rec) for rec in iter_tfrecords(path)]
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    return {k: [r.get(k) for r in rows] for k in keys}


def write_tfrecord_examples(path: str, columns: Dict[str, List[Any]]) -> None:
    """Inverse of parse (tests + dataset export): encode rows as Example
    protos in TFRecord framing with zeroed CRCs."""
    def varint(v: int) -> bytes:
        out = b""
        while True:
            b7 = v & 0x7F
            v >>= 7
            if v:
                out += bytes([b7 | 0x80])
            else:
                return out + bytes([b7])

    def ld(field: int, payload: bytes) -> bytes:
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    keys = list(columns)
    n = len(next(iter(columns.values()))) if columns else 0
    with open(path, "wb") as f:
        for i in range(n):
            feats = b""
            for k in keys:
                v = columns[k][i]
                vals = v if isinstance(v, (list, tuple)) else [v]
                if all(isinstance(x, (bytes, str)) for x in vals):
                    bl = b"".join(
                        ld(1, x.encode() if isinstance(x, str) else x)
                        for x in vals)
                    feat = ld(1, bl)
                elif all(isinstance(x, int) for x in vals):
                    # unpacked int64s: field 1, wire 0 per value
                    il = b"".join(varint((1 << 3) | 0) + varint(x)
                                  for x in vals)
                    feat = ld(3, il)
                else:
                    fl = varint((1 << 3) | 2) + varint(4 * len(vals)) + \
                        struct.pack(f"<{len(vals)}f", *[float(x)
                                                        for x in vals])
                    feat = ld(2, fl)
                entry = ld(1, k.encode()) + ld(2, feat)
                feats += ld(1, entry)
            example = ld(1, feats)
            f.write(struct.pack("<Q", len(example)) + b"\x00" * 4
                    + example + b"\x00" * 4)
