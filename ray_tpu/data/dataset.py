"""Dataset: lazy, distributed data pipeline.

Parity: reference python/ray/data/dataset.py (map_batches :379, iter_batches
:3725, materialize :4605, streaming_split :1222), grouped_data.py, read_api.
A Dataset is a logical-op chain executed by the StreamingExecutor on demand;
blocks are object refs in the host store. TPU-first: `iter_batches` has a
device-prefetch path (`iter_device_batches`) that overlaps host→TPU transfer
with consumption, and actor-pool map_batches reserves TPU chips per actor.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu as rt

from . import logical as L
from .block import Block, BlockAccessor, concat_blocks
from .context import DataContext
from .datasource import write_block
from .executor import StreamingExecutor, ft_get


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GB"  # pragma: no cover


class DatasetStats(str):
    """Formatted per-operator execution report (reference: DatasetStats,
    data/_internal/stats.py). Subclasses str so every existing consumer
    of the old plain-string report (``"read:" in ds.stats()``) still
    works, while the structured form rides along: ``.to_dict()`` for the
    full report, ``.operators`` for the per-op rows."""

    _report: Dict[str, Any]

    def __new__(cls, text: str, report: Dict[str, Any]) -> "DatasetStats":
        s = super().__new__(cls, text)
        s._report = report
        return s

    def to_dict(self) -> Dict[str, Any]:
        return self._report

    @property
    def operators(self) -> List[Dict[str, Any]]:
        return self._report["operators"]


def _format_stats(report: Dict[str, Any]) -> str:
    lines = []
    for op in report["operators"]:
        wall = op["wall_s"]
        rate = op["blocks"] / wall if wall > 0 else 0.0
        line = (f"{op['operator']}: {wall:.3f}s over "
                f"{op['blocks']} blocks ({rate:.1f} blocks/s)")
        if op["peak_store_pressure"] >= 0.005:
            line += (f", peak store pressure "
                     f"{op['peak_store_pressure'] * 100:.1f}%")
        if op.get("retries"):
            line += f", {op['retries']} retries"
        lines.append(line)
        detail = []
        if op["udf_s"]:
            detail.append(f"udf {op['udf_s']:.3f}s")
        if op["self_s"] and op["upstream_s"]:
            detail.append(f"self {op['self_s']:.3f}s "
                          f"(+{op['upstream_s']:.3f}s upstream)")
        if op["backpressure_s"] >= 0.0005:
            detail.append(f"backpressure wait {op['backpressure_s']:.3f}s")
        if detail:
            lines.append("    " + ", ".join(detail))
        if op["rows_in"] or op["rows_out"]:
            lines.append(
                f"    rows: {op['rows_in']} in / {op['rows_out']} out, "
                f"bytes: {_fmt_bytes(op['bytes_in'])} in / "
                f"{_fmt_bytes(op['bytes_out'])} out")
        bb = op["block_bytes"]
        if bb["count"]:
            dist = f"    block size: mean {_fmt_bytes(bb['mean'])}"
            if bb["min"] is not None and bb["max"]:
                dist += (f", min {_fmt_bytes(bb['min'])}, "
                         f"max {_fmt_bytes(bb['max'])}")
            dist += f" over {bb['count']} blocks"
            lines.append(dist)
        pool = op.get("actor_pool")
        if pool:
            lines.append(
                f"    actor pool: {pool['actors']} actors, "
                f"{pool['utilization'] * 100:.0f}% busy")
    if not lines:
        return "(no stages executed)"
    if "total_wall_s" in report:
        lines.append(
            f"Total: {report['total_wall_s']:.3f}s wall, "
            f"{report['total_rows_out']} rows out, "
            f"{_fmt_bytes(report['total_bytes_out'])} out "
            f"(per-op self time sums to {report['sum_self_s']:.3f}s)")
    return "\n".join(lines)


class Dataset:
    def __init__(self, ops: List[L.LogicalOp], ctx: Optional[DataContext] = None):
        self._ops = ops
        self._ctx = ctx or DataContext.get_current()

    # ------------------------------------------------------------- transforms

    def _append(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._ops + [op], self._ctx)

    def map_batches(
        self,
        fn: Union[Callable, type],
        *,
        batch_size: Optional[int] = None,
        batch_format: Optional[str] = None,
        compute: Any = None,
        fn_args: Tuple = (),
        fn_kwargs: Optional[Dict[str, Any]] = None,
        fn_constructor_args: Tuple = (),
        fn_constructor_kwargs: Optional[Dict[str, Any]] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        concurrency: Optional[Any] = None,
    ) -> "Dataset":
        """reference: dataset.py:379. A class `fn` runs on an actor pool
        (stateful UDF — model inference); a plain callable runs as tasks."""
        return self._append(L.MapBatches(
            fn=fn,
            batch_size=batch_size,
            batch_format=batch_format or self._ctx.default_batch_format,
            fn_args=fn_args,
            fn_kwargs=fn_kwargs or {},
            fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs or {},
            compute=compute,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            concurrency=concurrency,
        ))

    def map(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> "Dataset":
        return self._append(L.MapRows(fn))

    def flat_map(self, fn: Callable[[Dict[str, Any]], List[Dict[str, Any]]]) -> "Dataset":
        return self._append(L.FlatMap(fn))

    def filter(self, fn: Callable[[Dict[str, Any]], bool]) -> "Dataset":
        return self._append(L.Filter(fn))

    def add_column(self, name: str, fn: Callable[[Any], np.ndarray]) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch

        return self._append(L.MapBatches(fn=add, batch_format="numpy"))

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self._append(L.MapBatches(fn=drop, batch_format="numpy"))

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        return self._append(L.MapBatches(fn=select, batch_format="numpy"))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def ren(batch):
            return {mapping.get(k, k): v for k, v in batch.items()}

        return self._append(L.MapBatches(fn=ren, batch_format="numpy"))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._append(L.Repartition(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._append(L.RandomShuffle(seed))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        """Shuffle at block granularity only — cheap epoch-level reshuffle
        (reference: dataset.randomize_block_order)."""
        refs = self.to_block_refs()
        rng = np.random.default_rng(seed)
        order = rng.permutation(len(refs))
        return Dataset([L.InputData(refs=[refs[i] for i in order])], self._ctx)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._append(L.Sort(key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._append(L.Limit(n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._append(L.Union([o._ops for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._append(L.Zip(other._ops))

    def groupby(self, key: Optional[str]) -> "GroupedData":
        from .grouped import GroupedData

        return GroupedData(self, key)

    # ------------------------------------------------------------ consumption

    def _execute(self) -> Iterator[Any]:
        return StreamingExecutor(self._ctx).execute(self._ops)

    def to_block_refs(self) -> List[Any]:
        return list(self._execute())

    def materialize(self) -> "Dataset":
        """Execute fully; the result holds resolved block refs
        (reference: dataset.py:4605 → MaterializedDataset)."""
        refs = self.to_block_refs()
        rt.wait(refs, num_returns=len(refs)) if refs else None
        return Dataset([L.InputData(refs=refs)], self._ctx)

    def count(self) -> int:
        @rt.remote
        def c(b):
            return BlockAccessor(b).num_rows()

        return int(sum(rt.get([c.remote(r) for r in self._execute()]) or [0]))

    def schema(self) -> Any:
        for ref in self._execute():
            return BlockAccessor(ft_get(ref)).schema()
        return None

    def columns(self) -> List[str]:
        for ref in self._execute():
            return BlockAccessor(ft_get(ref)).column_names()
        return []

    def num_blocks(self) -> int:
        return len(self.to_block_refs())

    def take(self, n: int = 20) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for ref in self._execute():
            for row in BlockAccessor(ft_get(ref)).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Dict[str, Any]]:
        return self.take(n=1 << 62)

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "numpy") -> Any:
        blocks = []
        have = 0
        for ref in self._execute():
            b = ft_get(ref)
            blocks.append(b)
            have += BlockAccessor(b).num_rows()
            if have >= batch_size:
                break
        merged = BlockAccessor(concat_blocks(blocks))
        return BlockAccessor(merged.slice(0, min(batch_size, merged.num_rows()))).to_batch(batch_format)

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._execute():
            yield from BlockAccessor(ft_get(ref)).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Any]:
        """reference: dataset.py:3725 — re-chunk the block stream into batches."""
        from .iterator import batch_stream

        return batch_stream(
            self._execute(), batch_size, batch_format, drop_last,
            local_shuffle_buffer_size, local_shuffle_seed,
        )

    def iter_device_batches(self, *, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        """TPU ingest: numpy batches → `jax.device_put` with a prefetch queue
        so H2D transfer overlaps consumption (the reference's
        iter_torch_batches+prefetch_batches analog, TPU-native)."""
        from .iterator import device_batch_stream

        return device_batch_stream(
            self.iter_batches(batch_size=batch_size, batch_format="numpy"),
            sharding, prefetch,
        )

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False) -> "Iterator[Any]":
        """Numpy batches -> dicts of torch tensors (reference
        dataset.iter_torch_batches; torch-cpu is the supported target on a
        TPU host — device batches for the chip go through
        iter_device_batches/jax instead)."""
        import numpy as _np
        import torch

        def gen():
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy",
                                           drop_last=drop_last):
                out = {}
                for k, v in batch.items():
                    if v.dtype == _np.object_:
                        out[k] = list(v)  # ragged/object columns pass through
                        continue
                    t = torch.from_numpy(_np.ascontiguousarray(v))
                    if dtypes is not None:
                        want = dtypes.get(k) if isinstance(dtypes, dict) \
                            else dtypes
                        if want is not None:
                            t = t.to(want)
                    if device != "cpu":
                        t = t.to(device)
                    out[k] = t
                yield out

        return gen()

    def to_pandas(self):
        import pandas as pd

        dfs = [BlockAccessor(ft_get(r)).to_pandas() for r in self._execute()]
        return pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()

    def to_numpy_refs(self) -> List[Any]:
        return self.to_block_refs()

    # ------------------------------------------------------------------ split

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        refs = self.to_block_refs()
        groups: List[List[Any]] = [[] for _ in range(n)]
        for i, r in enumerate(refs):
            groups[i % n].append(r)
        return [Dataset([L.InputData(refs=g)], self._ctx) for g in groups]

    def split_shard(self, rank: int, world_size: int) -> "Dataset":
        """Deterministic round-robin block shard for DP ingest (the simple
        path behind get_dataset_shard; streaming_split is the coordinated
        variant)."""
        refs = self.to_block_refs()
        mine = [r for i, r in enumerate(refs) if i % world_size == rank]
        return Dataset([L.InputData(refs=mine)], self._ctx)

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints: Optional[List[str]] = None,
                        resume_key: Optional[str] = None) -> List[Any]:
        """reference: dataset.py:1222 — n coordinated iterators backed by an
        OutputSplitter actor feeding consumers on demand.

        With `resume_key` the coordinator gets a stable name plus
        max_restarts and a persisted handout journal: a restarted trainer
        calling streaming_split with the same key reattaches to the live
        coordinator (or a restarted one that replayed its journal), and
        each split's iterator resumes from its own journaled block
        position without re-delivering blocks.
        """
        from .iterator import IngestCursor, SplitCoordinator, SplitIterator

        key = resume_key or uuid.uuid4().hex[:8]
        name = f"rtpu_split_{key}"
        coord = None
        if resume_key is not None:
            try:
                coord = rt.get_actor(name)
            except Exception:
                coord = None
        if coord is None:
            coord_cls = rt.remote(SplitCoordinator)
            opts = {"name": name, "max_concurrency": max(4, 2 * n)}
            if resume_key is not None:
                # Coordinator failover: the constructor replays the
                # persisted handout journal against the re-executed
                # (deterministic) stream, so orphaned splits re-attach.
                opts["max_restarts"] = 3
            coord = coord_cls.options(**opts).remote(
                self._ops, self._ctx, n,
                name if resume_key is not None else None,
            )
        cursors = [IngestCursor(f"{key}_split{i}") if resume_key else None
                   for i in range(n)]
        return [SplitIterator(coord, i, cursor=cursors[i]) for i in range(n)]

    def iterator(self, *, resume_key: Optional[str] = None) -> Any:
        """A DataIterator over this dataset; with `resume_key` its batch
        iteration journals a cursor for mid-epoch resume (reference:
        Dataset.iterator → DataIterator)."""
        from .iterator import DataIterator

        return DataIterator(self, resume_key=resume_key)

    # ------------------------------------------------------------------ write

    def write_parquet(self, path: str, **kwargs) -> None:
        self._write(path, "parquet", **kwargs)

    def write_csv(self, path: str, **kwargs) -> None:
        self._write(path, "csv", **kwargs)

    def write_json(self, path: str, **kwargs) -> None:
        self._write(path, "json", **kwargs)

    def write_tfrecords(self, path: str, **kwargs) -> None:
        """tf.train.Example shards (dependency-free writer,
        data/tfrecord_lite.py; reference dataset.write_tfrecords)."""
        self._write(path, "tfrecord", **kwargs)

    def write_webdataset(self, path: str, **kwargs) -> None:
        """WebDataset tar shards, one per block (reference
        dataset.write_webdataset); rows keyed by "__key__" when present."""
        self._write(path, "tar", **kwargs)

    def write_sql(self, sql: str, connection_factory) -> None:
        """INSERT every row via a DBAPI connection per block (reference
        dataset.write_sql): `sql` is a parameterized statement, e.g.
        ``INSERT INTO t VALUES(?, ?)``; the picklable zero-arg
        `connection_factory` opens the connection inside each write task."""
        @rt.remote
        def w(block, stmt, factory):
            from .block import BlockAccessor

            def native(v):
                # DBAPI drivers store numpy scalars as blobs; unwrap them.
                return v.item() if hasattr(v, "item") else v

            conn = factory()
            try:
                cur = conn.cursor()
                cur.executemany(stmt, [tuple(native(v) for v in r.values())
                                       for r in BlockAccessor(block).iter_rows()])
                conn.commit()
            finally:
                conn.close()
            return True

        rt.get([w.remote(r, sql, connection_factory)
                for r in self._execute()])

    def _write(self, path: str, fmt: str, **kwargs) -> None:
        @rt.remote
        def w(block, i):
            return write_block(block, path, fmt, i, **kwargs)

        refs = [w.remote(r, i) for i, r in enumerate(self._execute())]
        rt.get(refs)

    # ------------------------------------------------------------------ stats

    def stats(self) -> DatasetStats:
        """Execute the pipeline in metered mode and return the
        per-operator report: wall / UDF / backpressure seconds, rows and
        bytes in/out, block-size envelope, peak store pressure, and
        actor-pool utilization. The return is a str (the formatted
        report) carrying the structured dict on ``.to_dict()``."""
        ex = StreamingExecutor(self._ctx)
        ex.collect_stats = True
        t0 = time.perf_counter()
        refs = list(ex.execute(self._ops))
        if refs:
            rt.wait(refs, num_returns=len(refs))
        report = ex.stats_report(total_wall_s=time.perf_counter() - t0)
        return DatasetStats(_format_stats(report), report)

    def __repr__(self) -> str:
        names = [type(op).__name__ for op in self._ops]
        return f"Dataset({' -> '.join(names)})"
