"""Block: the unit of distributed data.

Parity: reference python/ray/data/block.py + _internal/arrow_block.py /
pandas_block.py. Canonical block types here are **pyarrow.Table** (IO,
columnar ops) and **dict-of-numpy** (tensor batches) — the numpy form is
first-class because TPU ingest ends in `jax.device_put(numpy)`; the reference
reaches numpy through Arrow tensor extension arrays instead
(arrow_serialization.py), an indirection XLA does not need.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

try:
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None

Block = Union["pa.Table", Dict[str, np.ndarray]]
BatchFormat = str  # "numpy" | "pandas" | "pyarrow" | "default"


def is_arrow(block: Block) -> bool:
    return pa is not None and isinstance(block, pa.Table)


class BlockAccessor:
    """Uniform view over a block (reference: BlockAccessor, data/block.py)."""

    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # ------------------------------------------------------------------ basics

    def num_rows(self) -> int:
        if is_arrow(self.block):
            return self.block.num_rows
        if not self.block:
            return 0
        return len(next(iter(self.block.values())))

    def size_bytes(self) -> int:
        if is_arrow(self.block):
            return self.block.nbytes
        return int(sum(np.asarray(v).nbytes for v in self.block.values()))

    def schema(self) -> Any:
        if is_arrow(self.block):
            return self.block.schema
        return {k: np.asarray(v).dtype for k, v in self.block.items()}

    def column_names(self) -> List[str]:
        if is_arrow(self.block):
            return list(self.block.column_names)
        return list(self.block.keys())

    # ------------------------------------------------------------ conversions

    def to_numpy(self) -> Dict[str, np.ndarray]:
        if is_arrow(self.block):
            out = {}
            for name in self.block.column_names:
                col = self.block.column(name)
                out[name] = col.to_numpy(zero_copy_only=False)
            return out
        return {k: np.asarray(v) for k, v in self.block.items()}

    def to_arrow(self) -> "pa.Table":
        if is_arrow(self.block):
            return self.block
        cols, names = [], []
        for k, v in self.block.items():
            v = np.asarray(v)
            if v.ndim > 1:
                # Tensor column: store as fixed-size-list (reference uses its
                # ArrowTensorArray extension for the same purpose).
                flat = v.reshape(len(v), -1)
                arr = pa.FixedSizeListArray.from_arrays(
                    pa.array(flat.ravel()), flat.shape[1]
                )
                cols.append(arr)
            else:
                cols.append(pa.array(v))
            names.append(k)
        return pa.Table.from_arrays(cols, names=names)

    def to_pandas(self):
        import pandas as pd

        if is_arrow(self.block):
            return self.block.to_pandas()
        return pd.DataFrame({k: list(v) if np.asarray(v).ndim > 1 else v
                             for k, v in self.block.items()})

    def to_batch(self, batch_format: BatchFormat = "numpy") -> Any:
        if batch_format in ("numpy", "default", None):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ---------------------------------------------------------------- slicing

    def slice(self, start: int, end: int) -> Block:
        if is_arrow(self.block):
            return self.block.slice(start, end - start)
        return {k: np.asarray(v)[start:end] for k, v in self.block.items()}

    def take_rows(self, indices: np.ndarray) -> Block:
        if is_arrow(self.block):
            return self.block.take(pa.array(indices))
        return {k: np.asarray(v)[indices] for k, v in self.block.items()}

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        n = self.num_rows()
        cols = self.to_numpy()
        for i in range(n):
            yield {k: v[i] for k, v in cols.items()}


def block_from_batch(batch: Any) -> Block:
    """Normalize a UDF's returned batch into a block."""
    if pa is not None and isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except Exception:
        pass
    raise TypeError(
        f"map_batches UDF must return dict[str, np.ndarray], pyarrow.Table or "
        f"pandas.DataFrame, got {type(batch)}"
    )


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0] or blocks[:1]
    if not blocks:
        return {}
    if all(is_arrow(b) for b in blocks):
        return pa.concat_tables(blocks, promote_options="default")
    parts = [BlockAccessor(b).to_numpy() for b in blocks]
    keys = parts[0].keys()
    return {k: np.concatenate([p[k] for p in parts], axis=0) for k in keys}


def rows_to_block(rows: List[Dict[str, Any]]) -> Block:
    """Build a block from a list of row dicts (used by from_items/map)."""
    if not rows:
        return {}
    # Union of keys over ALL rows, first-seen order: ragged row sets (e.g.
    # WebDataset samples with differing members) must neither KeyError nor
    # silently drop fields absent from row 0.
    keys: Dict[str, None] = {}
    for r in rows:
        for k in r:
            keys[k] = None
    ragged = any(len(r) != len(keys) for r in rows)
    cols: Dict[str, Any] = {}
    numpyable = not ragged
    for k in keys:
        vals = [r.get(k) for r in rows]
        first = np.asarray(vals[0])
        if first.dtype == object:
            numpyable = False
            cols[k] = vals
        else:
            try:
                cols[k] = np.stack([np.asarray(v) for v in vals])
            except Exception:
                numpyable = False
                cols[k] = vals
    if numpyable:
        return cols
    if pa is not None:
        try:
            return pa.Table.from_pylist(rows)
        except (pa.lib.ArrowInvalid, pa.lib.ArrowTypeError):
            pass  # multi-dim ndarrays / mixed-type columns: no arrow layout
    # Object-dtype numpy columns carry anything (per-row ndarrays, dicts);
    # same representation ImageDatasource uses for ragged images.
    out: Dict[str, Any] = {}
    for k in keys:
        vals = [r.get(k) for r in rows]
        col = np.empty(len(vals), dtype=object)
        for i, v in enumerate(vals):
            col[i] = v
        out[k] = col
    return out
