"""Streaming execution of a logical plan over the task/actor plane.

Parity: reference data/_internal/execution/streaming_executor.py (:48, run
:200, _scheduling_loop_step :250), operators/ (TaskPoolMapOperator,
ActorPoolMapOperator actor_pool_map_operator.py:36), and planner/exchange for
the all-to-all ops (push-based shuffle: partition tasks fan out to reduce
tasks). Structure here: the plan is compiled into a chain of Python
generators over ObjectRefs — pulling the tail drives the whole pipeline, each
map stage keeps at most `max_tasks_in_flight` tasks running (backpressure),
and blocks stream driver-side only as refs (bytes stay in the host store).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu as rt

from . import logical as L
from .block import Block, BlockAccessor, block_from_batch, concat_blocks, rows_to_block
from .context import DataContext


# ------------------------------------------------------------- fused map fns


def _compile_map_stage(ops: List[L.LogicalOp], batch_format_default: str) -> Callable[[Block], Block]:
    """Build one block→block function applying all fused ops in order
    (reference: MapTransformer chaining, _internal/execution/map_transformer.py)."""

    def apply(block: Block) -> Block:
        for op in ops:
            acc = BlockAccessor(block)
            if isinstance(op, L.MapBatches):
                fmt = op.batch_format or batch_format_default
                bs = op.batch_size
                n = acc.num_rows()
                if bs is None or bs >= n:
                    out = op.fn(acc.to_batch(fmt), *op.fn_args, **op.fn_kwargs)
                    block = block_from_batch(out)
                else:
                    parts = []
                    for s in range(0, n, bs):
                        sub = BlockAccessor(acc.slice(s, min(s + bs, n)))
                        out = op.fn(sub.to_batch(fmt), *op.fn_args, **op.fn_kwargs)
                        parts.append(block_from_batch(out))
                    block = concat_blocks(parts)
            elif isinstance(op, L.MapRows):
                block = rows_to_block([op.fn(r) for r in acc.iter_rows()])
            elif isinstance(op, L.FlatMap):
                rows: List[Dict[str, Any]] = []
                for r in acc.iter_rows():
                    rows.extend(op.fn(r))
                block = rows_to_block(rows)
            elif isinstance(op, L.Filter):
                keep = np.array([bool(op.fn(r)) for r in acc.iter_rows()], dtype=bool)
                block = acc.take_rows(np.nonzero(keep)[0])
            else:  # pragma: no cover
                raise TypeError(f"not a fusable map op: {op}")
        return block

    return apply


class _PoolWorker:
    """Actor hosting a callable-class UDF (reference: _MapWorker inside
    ActorPoolMapOperator, actor_pool_map_operator.py)."""

    def __init__(self, cls, ctor_args, ctor_kwargs):
        self.fn = cls(*ctor_args, **ctor_kwargs)

    def apply(self, block: Block, batch_format: str, batch_size: Optional[int],
              fn_args, fn_kwargs) -> Block:
        acc = BlockAccessor(block)
        n = acc.num_rows()
        if batch_size is None or batch_size >= n:
            return block_from_batch(self.fn(acc.to_batch(batch_format), *fn_args, **fn_kwargs))
        parts = []
        for s in range(0, n, batch_size):
            sub = BlockAccessor(acc.slice(s, min(s + batch_size, n)))
            parts.append(block_from_batch(self.fn(sub.to_batch(batch_format), *fn_args, **fn_kwargs)))
        return concat_blocks(parts)


# ----------------------------------------------------------------- executor


class StreamingExecutor:
    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()
        # Per-op execution stats (reference: _StatsActor / DatasetStats):
        # per-operator wall time, block count, and peak object-store
        # pressure observed while the stage ran.
        self.stats: List[Dict[str, Any]] = []

    # -- public ---------------------------------------------------------------

    def execute(self, ops: List[L.LogicalOp]) -> Iterator[Any]:
        """Yield output block refs; pulling drives the pipeline."""
        stages = L.fuse_plan(L.optimize(ops))
        stream: Iterator[Any] = iter(())
        for stage in stages:
            op = stage[0]
            if isinstance(op, L.Read):
                stream = self._read_stage(op)
            elif isinstance(op, L.InputData):
                stream = iter(list(op.refs))
            elif isinstance(op, L.MapBatches) and op.is_actor_compute:
                stream = self._actor_pool_stage(stream, op)
            elif L.is_fusable_map(op):
                stream = self._task_map_stage(stream, stage)
            elif isinstance(op, L.Repartition):
                stream = self._repartition(stream, op.num_blocks)
            elif isinstance(op, L.RandomShuffle):
                stream = self._random_shuffle(stream, op.seed)
            elif isinstance(op, L.Sort):
                stream = self._sort(stream, op.key, op.descending)
            elif isinstance(op, L.Limit):
                stream = self._limit(stream, op.n)
            elif isinstance(op, L.Union):
                stream = self._union(stream, op.others)
            elif isinstance(op, L.Zip):
                stream = self._zip(stream, op.other)
            elif isinstance(op, L.Aggregate):
                stream = self._aggregate(stream, op)
            else:  # pragma: no cover
                raise TypeError(f"unknown logical op {op}")
        return stream

    # -- stages ---------------------------------------------------------------

    def _read_stage(self, op: L.Read) -> Iterator[Any]:
        parallelism = op.parallelism if op.parallelism > 0 else self.ctx.read_parallelism
        tasks = op.datasource.get_read_tasks(parallelism)

        @rt.remote(num_returns="streaming")
        def do_read(task):
            out = task()
            import inspect

            if inspect.isgenerator(out):
                # Multi-block read task (e.g. one block per file): each block
                # streams out as it is parsed, so downstream map stages start
                # on block 0 while the reader is still on block 1+.
                for block in out:
                    yield block
            else:
                yield out

        def stream() -> Iterator[Any]:
            import collections

            t0 = time.perf_counter()
            n = 0
            cap = max(1, self.ctx.max_tasks_in_flight)
            it = iter(tasks)
            pending: "collections.deque" = collections.deque()
            try:
                for t in it:
                    pending.append(do_read.remote(t))
                    if len(pending) >= cap:
                        break
                while pending:
                    gen = pending.popleft()
                    for ref in gen:
                        n += 1
                        yield ref
                    nxt = next(it, None)
                    if nxt is not None:
                        pending.append(do_read.remote(nxt))
            finally:  # early-stopping consumers (Limit) must still report
                self._record_stat("read", time.perf_counter() - t0, n)

        return stream()

    def _task_map_stage(self, inputs: Iterator[Any], stage: List[L.LogicalOp]) -> Iterator[Any]:
        apply = _compile_map_stage(stage, self.ctx.default_batch_format)
        mb = next((o for o in stage if isinstance(o, L.MapBatches)), None)
        opts: Dict[str, Any] = {}
        if mb is not None:
            if mb.num_cpus is not None:
                opts["num_cpus"] = mb.num_cpus
            if mb.num_tpus:
                opts["num_tpus"] = mb.num_tpus
        remote_fn = rt.remote(apply)
        if opts:
            remote_fn = remote_fn.options(**opts)
        label = "+".join(type(o).__name__ for o in stage)
        return self._bounded_submit(
            (remote_fn.remote(ref) for ref in inputs), label, None
        )

    _PRESSURE_TTL_S = 0.05

    def _record_stat(self, label: str, wall_s: float, blocks: int,
                     peak_pressure: float = 0.0) -> None:
        self.stats.append({"operator": label, "wall_s": wall_s,
                           "blocks": blocks,
                           "peak_store_pressure": peak_pressure})

    def _store_pressure(self) -> float:
        """Local object-store arena fill fraction (0.0 when no native arena
        is attached — e.g. inline-only stores). Sampled at most every
        _PRESSURE_TTL_S: this sits on the per-submission hot path and the
        reading can't move meaningfully faster than tasks complete."""
        now = time.perf_counter()
        cached = getattr(self, "_pressure_cache", None)
        if cached is not None and now - cached[0] < self._PRESSURE_TTL_S:
            return cached[1]
        try:
            from ray_tpu.core import native_store

            arena = native_store.get_arena()
            if arena is None:
                p = 0.0
            else:
                s = arena.stats()
                p = s["used"] / max(1, s["capacity"])
        except Exception:
            p = 0.0
        self._pressure_cache = (now, p)
        return p

    def _bounded_submit(self, submissions: Iterator[Any], label: str,
                        total: Optional[int]) -> Iterator[Any]:
        """Cap in-flight tasks; yield refs in submission (FIFO) order when
        preserve_order else completion order. The cap is concurrency-based
        normally and shrinks under object-store memory pressure (see
        DataContext.memory_high_water) so block production stays bounded by
        downstream consumption, not by spilling capacity."""
        base_cap = self.ctx.max_tasks_in_flight
        high_water = self.ctx.memory_high_water
        t0 = time.perf_counter()
        n = 0
        peak_pressure = 0.0
        pending: List[Any] = []
        preserve = self.ctx.preserve_order
        try:
            for ref in submissions:
                pending.append(ref)
                cap = base_cap
                pressure = self._store_pressure() if high_water else 0.0
                peak_pressure = max(peak_pressure, pressure)
                if high_water and pressure >= high_water:
                    cap = min(base_cap, max(1, self.ctx.memory_pressure_cap))
                while len(pending) >= cap:
                    if preserve:
                        out, pending = pending[0], pending[1:]
                        rt.wait([out], num_returns=1)
                    else:
                        ready, pending = rt.wait(pending, num_returns=1)
                        out = ready[0]
                    n += 1
                    yield out
            while pending:
                if preserve:
                    out, pending = pending[0], pending[1:]
                    rt.wait([out], num_returns=1)
                else:
                    ready, pending = rt.wait(pending, num_returns=1)
                    out = ready[0]
                # Drain-phase pressure matters too: the tail blocks are
                # still materializing into the store.
                if high_water:
                    peak_pressure = max(peak_pressure,
                                        self._store_pressure())
                n += 1
                yield out
        finally:
            # finally, not fallthrough: a downstream stage that stops
            # pulling early (Limit) raises GeneratorExit here — the stage
            # still ran and must still report.
            self._record_stat(label, time.perf_counter() - t0, n,
                              peak_pressure=peak_pressure)

    def _actor_pool_stage(self, inputs: Iterator[Any], op: L.MapBatches) -> Iterator[Any]:
        """Fixed/bounded actor pool (reference: ActorPoolMapOperator + _ActorPool
        autoscaling :375; TPU-aware: num_tpus reserves chips per actor so the
        pool lands one actor per TPU host — the ViT batch-inference shape)."""
        conc = op.concurrency or 1
        if isinstance(conc, (tuple, list)):
            min_actors, max_actors = conc
        else:
            min_actors = max_actors = int(conc)
        actor_opts: Dict[str, Any] = {"max_concurrency": 2}
        if op.num_cpus is not None:
            actor_opts["num_cpus"] = op.num_cpus
        if op.num_tpus:
            actor_opts["num_tpus"] = op.num_tpus
        pool_cls = rt.remote(_PoolWorker)
        actors = [
            pool_cls.options(**actor_opts).remote(op.fn, op.fn_constructor_args,
                                                  op.fn_constructor_kwargs)
            for _ in range(min_actors)
        ]
        fmt = op.batch_format or self.ctx.default_batch_format
        t0 = time.perf_counter()
        n = 0
        per_actor_cap = 2
        inflight: List[Tuple[Any, int]] = []  # (ref, actor_idx)
        load = [0] * len(actors)

        def submit(ref: Any) -> None:
            # least-loaded dispatch; grow pool if saturated and below max
            i = min(range(len(actors)), key=lambda j: load[j])
            if load[i] >= per_actor_cap and len(actors) < max_actors:
                actors.append(pool_cls.options(**actor_opts).remote(
                    op.fn, op.fn_constructor_args, op.fn_constructor_kwargs))
                load.append(0)
                i = len(actors) - 1
            load[i] += 1
            inflight.append((
                actors[i].apply.remote(ref, fmt, op.batch_size, op.fn_args, op.fn_kwargs),
                i,
            ))

        def drain_one() -> Any:
            nonlocal n
            ref, i = inflight.pop(0)
            rt.wait([ref], num_returns=1)
            load[i] -= 1
            n += 1
            return ref

        try:
            for ref in inputs:
                while len(inflight) >= per_actor_cap * len(actors):
                    yield drain_one()
                submit(ref)
            while inflight:
                yield drain_one()
        finally:
            for a in actors:
                try:
                    rt.kill(a)
                except Exception:
                    pass
            self._record_stat(f"ActorPool[{type(op.fn).__name__}]",
                              time.perf_counter() - t0, n)

    # -- all-to-all -----------------------------------------------------------

    def _counts(self, refs: List[Any]) -> List[int]:
        @rt.remote
        def count(b):
            return BlockAccessor(b).num_rows()

        return rt.get([count.remote(r) for r in refs])

    def _repartition(self, inputs: Iterator[Any], num_blocks: int) -> Iterator[Any]:
        refs = list(inputs)
        counts = self._counts(refs)
        total = sum(counts)
        bounds = [total * i // num_blocks for i in range(num_blocks + 1)]

        @rt.remote
        def build(start, end, *blocks):
            parts = []
            off = 0
            for b, c in zip(blocks, counts):
                lo, hi = max(start - off, 0), min(end - off, c)
                if lo < hi:
                    parts.append(BlockAccessor(b).slice(lo, hi))
                off += c
            return concat_blocks(parts) if parts else rows_to_block([])

        for i in range(num_blocks):
            yield build.remote(bounds[i], bounds[i + 1], *refs)

    def _random_shuffle(self, inputs: Iterator[Any], seed: Optional[int]) -> Iterator[Any]:
        """Two-round push shuffle (reference: planner/exchange push-based
        shuffle): map tasks split each block into P random parts; reduce tasks
        concat + local permute."""
        refs = list(inputs)
        P = self.ctx.shuffle_partitions or max(1, len(refs))

        def split(block, i):
            rng = np.random.default_rng(None if seed is None else seed + i)
            acc = BlockAccessor(block)
            n = acc.num_rows()
            perm = rng.permutation(n)
            out = [acc.take_rows(part) for part in np.array_split(perm, P)]
            return out if P > 1 else out[0]

        split_remote = rt.remote(split).options(num_returns=P)
        parts: List[List[Any]] = []
        for i, r in enumerate(refs):
            res = split_remote.remote(r, i)
            parts.append([res] if P == 1 else list(res))

        def reduce(j, *shards):
            rng = np.random.default_rng(None if seed is None else seed + 10_000 + j)
            merged = concat_blocks(list(shards))
            acc = BlockAccessor(merged)
            return acc.take_rows(rng.permutation(acc.num_rows()))

        reduce_remote = rt.remote(reduce)
        for j in range(P):
            yield reduce_remote.remote(j, *[parts[i][j] for i in range(len(refs))])

    def _sort(self, inputs: Iterator[Any], key: str, descending: bool) -> Iterator[Any]:
        """Sample-based range partition sort (reference: exchange/sort)."""
        refs = list(inputs)
        P = max(1, len(refs))

        @rt.remote
        def sample(b):
            cols = BlockAccessor(b).to_numpy()
            v = cols[key]
            if len(v) == 0:
                return v
            idx = np.random.default_rng(0).choice(len(v), min(20, len(v)), replace=False)
            return v[idx]

        samples = np.concatenate([s for s in rt.get([sample.remote(r) for r in refs])
                                  if len(s)]) if refs else np.array([])
        if len(samples) == 0:
            yield from refs
            return
        qs = np.quantile(np.sort(samples), np.linspace(0, 1, P + 1)[1:-1]) if P > 1 else []

        def partition(b):
            acc = BlockAccessor(b)
            v = acc.to_numpy()[key]
            ids = np.searchsorted(qs, v, side="right") if P > 1 else np.zeros(len(v), int)
            out = [acc.take_rows(np.nonzero(ids == p)[0]) for p in range(P)]
            return out if P > 1 else out[0]

        part_remote = rt.remote(partition).options(num_returns=P)
        parts = []
        for r in refs:
            res = part_remote.remote(r)
            parts.append([res] if P == 1 else list(res))

        def merge(*shards):
            merged = concat_blocks(list(shards))
            acc = BlockAccessor(merged)
            order = np.argsort(acc.to_numpy()[key], kind="stable")
            if descending:
                order = order[::-1]
            return acc.take_rows(order)

        merge_remote = rt.remote(merge)
        outs = [merge_remote.remote(*[parts[i][j] for i in range(len(refs))])
                for j in range(P)]
        yield from (outs[::-1] if descending else outs)

    def _limit(self, inputs: Iterator[Any], n: int) -> Iterator[Any]:
        taken = 0

        @rt.remote
        def head(b, k):
            return BlockAccessor(b).slice(0, k)

        @rt.remote
        def count(b):
            return BlockAccessor(b).num_rows()

        for ref in inputs:
            if taken >= n:
                break
            c = rt.get(count.remote(ref))
            if taken + c <= n:
                taken += c
                yield ref
            else:
                yield head.remote(ref, n - taken)
                taken = n

    def _union(self, inputs: Iterator[Any], other_plans: List[List[L.LogicalOp]]) -> Iterator[Any]:
        yield from inputs
        for plan in other_plans:
            yield from StreamingExecutor(self.ctx).execute(plan)

    def _zip(self, inputs: Iterator[Any], other_plan: List[L.LogicalOp]) -> Iterator[Any]:
        left = list(inputs)
        right = list(StreamingExecutor(self.ctx).execute(other_plan))
        lcounts = self._counts(left)
        rcounts = self._counts(right)
        if sum(lcounts) != sum(rcounts):
            raise ValueError(
                f"zip requires equal row counts, got {sum(lcounts)} vs {sum(rcounts)}"
            )

        @rt.remote
        def zip_slice(start, end, lblock, *rblocks):
            lcols = BlockAccessor(lblock).to_numpy()
            parts = []
            off = 0
            for rb, c in zip(rblocks, rcounts):
                lo, hi = max(start - off, 0), min(end - off, c)
                if lo < hi:
                    parts.append(BlockAccessor(rb).slice(lo, hi))
                off += c
            rcols = BlockAccessor(concat_blocks(parts)).to_numpy()
            out = dict(lcols)
            for k, v in rcols.items():
                out[k if k not in out else f"{k}_1"] = v
            return out

        off = 0
        for lb, c in zip(left, lcounts):
            yield zip_slice.remote(off, off + c, lb, *right)
            off += c

    def _aggregate(self, inputs: Iterator[Any], op: L.Aggregate) -> Iterator[Any]:
        """Hash-partition groupby + per-partition pandas aggregate
        (reference: grouped_data.py over sort-based exchange)."""
        refs = list(inputs)
        key = op.key
        aggs = op.aggs
        P = max(1, min(len(refs), 8)) if key is not None else 1

        if key is None:
            @rt.remote
            def global_agg(*blocks):
                import pandas as pd

                df = pd.concat([BlockAccessor(b).to_pandas() for b in blocks])
                row: Dict[str, Any] = {}
                for kind, col, out_name in aggs:
                    if kind == "count":
                        row[out_name] = len(df)
                    else:
                        row[out_name] = getattr(df[col], kind)()
                return rows_to_block([row])

            yield global_agg.remote(*refs)
            return

        def part_fn(b):
            import zlib

            acc = BlockAccessor(b)
            v = acc.to_numpy()[key]
            # Stable cross-process hash: Python's hash() is salted per process
            # (PYTHONHASHSEED), which would scatter one key across partitions.
            h = np.array([zlib.crc32(repr(x).encode()) % P for x in v.tolist()])
            out = [acc.take_rows(np.nonzero(h == p)[0]) for p in range(P)]
            return out if P > 1 else out[0]

        part_remote = rt.remote(part_fn).options(num_returns=P)
        parts = []
        for r in refs:
            res = part_remote.remote(r)
            parts.append([res] if P == 1 else list(res))

        def agg_fn(*shards):
            import pandas as pd

            df = pd.concat([BlockAccessor(b).to_pandas() for b in shards])
            if df.empty:
                return rows_to_block([])
            g = df.groupby(key, sort=True)
            out = pd.DataFrame(index=g.size().index)
            for kind, col, out_name in aggs:
                if kind == "count":
                    out[out_name] = g.size()
                else:
                    out[out_name] = getattr(g[col], kind)()
            out = out.reset_index()
            return {c: out[c].to_numpy() for c in out.columns}

        agg_remote = rt.remote(agg_fn)
        for j in range(P):
            yield agg_remote.remote(*[parts[i][j] for i in range(len(refs))])
