"""Streaming execution of a logical plan over the task/actor plane.

Parity: reference data/_internal/execution/streaming_executor.py (:48, run
:200, _scheduling_loop_step :250), operators/ (TaskPoolMapOperator,
ActorPoolMapOperator actor_pool_map_operator.py:36), and planner/exchange for
the all-to-all ops (push-based shuffle: partition tasks fan out to reduce
tasks). Structure here: the plan is compiled into a chain of Python
generators over ObjectRefs — pulling the tail drives the whole pipeline, each
map stage keeps at most `max_tasks_in_flight` tasks running (backpressure),
and blocks stream driver-side only as refs (bytes stay in the host store).
"""
from __future__ import annotations

import collections
import sys
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

import ray_tpu as rt
from ray_tpu import flags
from ray_tpu.core import events
from ray_tpu.core.controller import (
    ActorDiedError,
    DependencyError,
    ObjectLostError,
    WorkerCrashedError,
)
from ray_tpu.util.metrics import Counter, Gauge

from . import logical as L
from .block import Block, BlockAccessor, block_from_batch, concat_blocks, rows_to_block
from .context import DataContext


# ------------------------------------------------- fault-tolerance plumbing
#
# The streaming plane predates the robustness PRs; everything below is the
# RTPU_DATA_FT retrofit. Three pieces:
#   * self-healing actor pools (_actor_pool_stage): typed death on the
#     in-flight ref -> replace the actor in place, resubmit the batch;
#   * driver-side lineage for all-to-all shards (_derivable / ft_get): the
#     producing call is recorded per yielded shard so a shard lost to node
#     death re-derives from surviving inputs after the controller's own
#     _maybe_reconstruct path has had its chance;
#   * process-local counters mirroring the Prometheus instruments, because
#     tests and benchmarks need synchronous reads while the metrics
#     aggregator flushes asynchronously.

_retries_total = Counter(
    "rtpu_data_retries_total",
    description="Streaming data plane: input batches resubmitted after the "
                "pool actor running them died, by cause (actor_died / "
                "worker_crashed / preempted). Preempted resubmissions do "
                "not consume the per-batch retry budget.",
    tag_keys=("cause",))
_rederived_total = Counter(
    "rtpu_data_blocks_rederived_total",
    description="Streaming data plane: all-to-all output shards (shuffle / "
                "sort / repartition / aggregate / zip) re-derived from "
                "their recorded producing call after the stored copy was "
                "lost with its node.")
_inflight_gauge = Gauge(
    "rtpu_data_inflight_blocks",
    description="Streaming data plane: blocks currently in flight in one "
                "executing stage, labeled by stage.",
    tag_keys=("stage",))
_pressure_gauge = Gauge(
    "rtpu_data_store_pressure",
    description="Streaming data plane: local object-store arena fill "
                "fraction observed while a stage runs, labeled by stage "
                "(mirrors the per-op peak_store_pressure stat).",
    tag_keys=("stage",))

# Per-operator execution accounting (reference: _StatsActor +
# OpRuntimeMetrics). These four families are the data-plane face of the
# cluster TSDB: `rtpu top`'s DATA section and the Grafana data row read
# exactly these names/tags, so the executor is their single producer.
_op_blocks_total = Counter(
    "rtpu_data_operator_blocks_total",
    description="Streaming data plane: output blocks produced per "
                "operator (stage label, e.g. read / MapBatches / "
                "ActorPool[Fn] / RandomShuffle).",
    tag_keys=("operator",))
_op_rows_total = Counter(
    "rtpu_data_operator_rows_total",
    description="Streaming data plane: rows entering (dir=in) and "
                "leaving (dir=out) each metered operator; `iter` is the "
                "driver-side batch iterator.",
    tag_keys=("operator", "dir"))
_op_bytes_total = Counter(
    "rtpu_data_operator_bytes_total",
    description="Streaming data plane: block bytes entering (dir=in) "
                "and leaving (dir=out) each metered operator — dir=out "
                "approximates object-store bytes the operator "
                "materialized.",
    tag_keys=("operator", "dir"))
_op_seconds_total = Counter(
    "rtpu_data_operator_seconds_total",
    description="Streaming data plane: per-operator time by phase — "
                "wall (stage elapsed), udf (inside the user function), "
                "backpressure (driver blocked at the in-flight cap "
                "waiting for downstream to drain).",
    tag_keys=("operator", "phase"))

# Synchronous mirror of the instruments above, for tests and data_bench.
_FT_COUNTERS: Dict[str, int] = {}


def _count(key: str, delta: int = 1) -> None:
    _FT_COUNTERS[key] = _FT_COUNTERS.get(key, 0) + delta


def ft_counters() -> Dict[str, int]:
    """Snapshot of this process's data-plane fault-tolerance counters:
    ``retries`` (budget-consuming resubmits), ``preempted_retries``
    (budget-free), ``rederived`` (all-to-all shards rebuilt), and
    ``proactive_migrations`` (pool actors moved off draining nodes)."""
    out = {"retries": 0, "preempted_retries": 0, "rederived": 0,
           "proactive_migrations": 0}
    out.update(_FT_COUNTERS)
    return out


def reset_ft_counters() -> None:
    _FT_COUNTERS.clear()


# Driver-side lineage for all-to-all shards: object_id -> (thunk that
# resubmits the producing call, re-derivations so far). Bounded LRU — a
# long pipeline streams far more shards than are ever simultaneously
# recoverable, and the controller's own lineage still covers evictees.
_REDERIVE_CAP = 4096
_rederive: "collections.OrderedDict[str, Tuple[Callable[[], Any], int]]" = \
    collections.OrderedDict()


def _remember_rederive(ref: Any, make_ref: Callable[[], Any],
                       attempts: int = 0) -> Any:
    _rederive[ref.object_id] = (make_ref, attempts)
    while len(_rederive) > _REDERIVE_CAP:
        _rederive.popitem(last=False)
    return ref


def ft_get(refs: Any, timeout: Optional[float] = None) -> Any:
    """`rt.get` that re-derives all-to-all shards lost to node death.

    The controller's lineage path (`_maybe_reconstruct`) runs first — a
    `get` on a lost-but-reconstructable object simply blocks while the
    controller re-executes the producer. Only when that path gives up
    (lineage evicted, cap hit, inputs also lost at the time) does the
    stored `ObjectLostError` surface here; if the shard was registered by
    an all-to-all stage we resubmit its producing call against surviving
    inputs and retry, bounded by RTPU_MAX_RECONSTRUCTIONS per shard.
    """
    if isinstance(refs, list):
        return [ft_get(r, timeout) for r in refs]
    ref = refs
    while True:
        try:
            return rt.get(ref, timeout=timeout)
        except (ObjectLostError, WorkerCrashedError, DependencyError) as err:
            entry = _rederive.pop(ref.object_id, None)
            if entry is None or not flags.get("RTPU_DATA_FT"):
                raise
            make_ref, attempts = entry
            if attempts >= int(flags.get("RTPU_MAX_RECONSTRUCTIONS")):
                raise
            events.emit(
                "WARNING", "OBJECT_RECONSTRUCTING",
                f"re-deriving lost data shard {ref.object_id[:8]} from its "
                f"producing call (attempt {attempts + 1})",
                source="driver",
                data={"object_id": ref.object_id, "cause": type(err).__name__})
            _rederived_total.inc(1.0)
            _count("rederived")
            ref = _remember_rederive(make_ref(), make_ref, attempts + 1)


# ------------------------------------------------------------- fused map fns


def _compile_map_stage(ops: List[L.LogicalOp], batch_format_default: str) -> Callable[[Block], Block]:
    """Build one block→block function applying all fused ops in order
    (reference: MapTransformer chaining, _internal/execution/map_transformer.py)."""

    def apply(block: Block) -> Block:
        for op in ops:
            acc = BlockAccessor(block)
            if isinstance(op, L.MapBatches):
                fmt = op.batch_format or batch_format_default
                bs = op.batch_size
                n = acc.num_rows()
                if bs is None or bs >= n:
                    out = op.fn(acc.to_batch(fmt), *op.fn_args, **op.fn_kwargs)
                    block = block_from_batch(out)
                else:
                    parts = []
                    for s in range(0, n, bs):
                        sub = BlockAccessor(acc.slice(s, min(s + bs, n)))
                        out = op.fn(sub.to_batch(fmt), *op.fn_args, **op.fn_kwargs)
                        parts.append(block_from_batch(out))
                    block = concat_blocks(parts)
            elif isinstance(op, L.MapRows):
                block = rows_to_block([op.fn(r) for r in acc.iter_rows()])
            elif isinstance(op, L.FlatMap):
                rows: List[Dict[str, Any]] = []
                for r in acc.iter_rows():
                    rows.extend(op.fn(r))
                block = rows_to_block(rows)
            elif isinstance(op, L.Filter):
                keep = np.array([bool(op.fn(r)) for r in acc.iter_rows()], dtype=bool)
                block = acc.take_rows(np.nonzero(keep)[0])
            else:  # pragma: no cover
                raise TypeError(f"not a fusable map op: {op}")
        return block

    return apply


class _PoolWorker:
    """Actor hosting a callable-class UDF (reference: _MapWorker inside
    ActorPoolMapOperator, actor_pool_map_operator.py). Every apply feeds
    a running meter (rows/bytes in and out, UDF seconds) that the stage
    fetches once at drain time via ``meter()`` — per-block accounting
    with zero extra round-trips."""

    def __init__(self, cls, ctor_args, ctor_kwargs):
        import threading

        self.fn = cls(*ctor_args, **ctor_kwargs)
        self._meter_lock = threading.Lock()  # max_concurrency=2
        self._meter = {"udf_s": 0.0, "rows_in": 0, "rows_out": 0,
                       "bytes_in": 0, "bytes_out": 0, "blocks": 0}

    def apply(self, block: Block, batch_format: str, batch_size: Optional[int],
              fn_args, fn_kwargs) -> Block:
        acc = BlockAccessor(block)
        n = acc.num_rows()
        bytes_in = acc.size_bytes()
        t0 = time.perf_counter()
        if batch_size is None or batch_size >= n:
            out = block_from_batch(self.fn(acc.to_batch(batch_format), *fn_args, **fn_kwargs))
        else:
            parts = []
            for s in range(0, n, batch_size):
                sub = BlockAccessor(acc.slice(s, min(s + batch_size, n)))
                parts.append(block_from_batch(self.fn(sub.to_batch(batch_format), *fn_args, **fn_kwargs)))
            out = concat_blocks(parts)
        udf_s = time.perf_counter() - t0
        oacc = BlockAccessor(out)
        with self._meter_lock:
            m = self._meter
            m["udf_s"] += udf_s
            m["rows_in"] += n
            m["rows_out"] += oacc.num_rows()
            m["bytes_in"] += bytes_in
            m["bytes_out"] += oacc.size_bytes()
            m["blocks"] += 1
        return out

    def meter(self) -> Dict[str, Any]:
        with self._meter_lock:
            return dict(self._meter)


# ----------------------------------------------------------------- executor


class StreamingExecutor:
    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()
        # Per-op execution stats (reference: _StatsActor / DatasetStats).
        # `stats` keeps the per-stage rows the old API exposed, but
        # bounded (RTPU_DATA_STATS_ROWS): a long-lived executor re-used
        # across many runs must not grow a row list forever. The
        # unbounded view is `op_stats`: O(#operators) running aggregates
        # — wall/udf/backpressure seconds, rows and bytes in/out, block
        # count and block-size envelope — updated on every record.
        rows = max(1, int(flags.get("RTPU_DATA_STATS_ROWS")))
        self.stats: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=rows)
        self.op_stats: Dict[str, Dict[str, Any]] = {}
        # Dataset.stats() flips this on: task map stages then run a
        # metered wrapper (rows/bytes/udf seconds shipped back as a
        # second return) and actor pools fetch their workers' meters at
        # drain. Off (the default execution path) nothing extra ships.
        self.collect_stats = False

    @staticmethod
    def _timed(inputs: Iterator[Any], cell: List[float]) -> Iterator[Any]:
        """Pass-through iterator accumulating time spent blocked on the
        upstream stage into cell[0], so a stage can report self-time
        (wall minus upstream) — per-op walls in a chained generator
        pipeline otherwise all approximate the end-to-end wall."""
        it = iter(inputs)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                cell[0] += time.perf_counter() - t0
                return
            cell[0] += time.perf_counter() - t0
            yield item

    # -- public ---------------------------------------------------------------

    def execute(self, ops: List[L.LogicalOp]) -> Iterator[Any]:
        """Yield output block refs; pulling drives the pipeline."""
        stages = L.fuse_plan(L.optimize(ops))
        stream: Iterator[Any] = iter(())
        for stage in stages:
            op = stage[0]
            if isinstance(op, L.Read):
                stream = self._read_stage(op)
            elif isinstance(op, L.InputData):
                stream = iter(list(op.refs))
            elif isinstance(op, L.MapBatches) and op.is_actor_compute:
                stream = self._actor_pool_stage(stream, op)
            elif L.is_fusable_map(op):
                stream = self._task_map_stage(stream, stage)
            elif isinstance(op, L.Repartition):
                cell = [0.0]
                stream = self._observe(
                    "Repartition",
                    self._repartition(self._timed(stream, cell),
                                      op.num_blocks), cell)
            elif isinstance(op, L.RandomShuffle):
                cell = [0.0]
                stream = self._observe(
                    "RandomShuffle",
                    self._random_shuffle(self._timed(stream, cell),
                                         op.seed), cell)
            elif isinstance(op, L.Sort):
                cell = [0.0]
                stream = self._observe(
                    "Sort",
                    self._sort(self._timed(stream, cell), op.key,
                               op.descending), cell)
            elif isinstance(op, L.Limit):
                stream = self._limit(stream, op.n)
            elif isinstance(op, L.Union):
                stream = self._union(stream, op.others)
            elif isinstance(op, L.Zip):
                cell = [0.0]
                stream = self._observe(
                    "Zip", self._zip(self._timed(stream, cell), op.other),
                    cell)
            elif isinstance(op, L.Aggregate):
                cell = [0.0]
                stream = self._observe(
                    "Aggregate",
                    self._aggregate(self._timed(stream, cell), op), cell)
            else:  # pragma: no cover
                raise TypeError(f"unknown logical op {op}")
        return stream

    def _observe(self, label: str, inner: Iterator[Any],
                 upstream_cell: Optional[List[float]] = None) -> Iterator[Any]:
        """Record wall / block-count / self-time for stages that manage
        their own submission (the all-to-all exchanges)."""
        t0 = time.perf_counter()
        n = 0
        try:
            for ref in inner:
                n += 1
                yield ref
        finally:
            self._record_stat(
                label, time.perf_counter() - t0, n,
                upstream_s=upstream_cell[0] if upstream_cell else 0.0)

    # -- stages ---------------------------------------------------------------

    def _read_stage(self, op: L.Read) -> Iterator[Any]:
        parallelism = op.parallelism if op.parallelism > 0 else self.ctx.read_parallelism
        tasks = op.datasource.get_read_tasks(parallelism)

        @rt.remote(num_returns="streaming")
        def do_read(task):
            out = task()
            import inspect

            if inspect.isgenerator(out):
                # Multi-block read task (e.g. one block per file): each block
                # streams out as it is parsed, so downstream map stages start
                # on block 0 while the reader is still on block 1+.
                for block in out:
                    yield block
            else:
                yield out

        def stream() -> Iterator[Any]:
            import collections

            t0 = time.perf_counter()
            n = 0
            cap = max(1, self.ctx.max_tasks_in_flight)
            it = iter(tasks)
            pending: "collections.deque" = collections.deque()
            try:
                for t in it:
                    pending.append(do_read.remote(t))
                    if len(pending) >= cap:
                        break
                while pending:
                    gen = pending.popleft()
                    for ref in gen:
                        n += 1
                        yield ref
                    nxt = next(it, None)
                    if nxt is not None:
                        pending.append(do_read.remote(nxt))
            finally:  # early-stopping consumers (Limit) must still report
                self._record_stat("read", time.perf_counter() - t0, n)

        return stream()

    def _task_map_stage(self, inputs: Iterator[Any], stage: List[L.LogicalOp]) -> Iterator[Any]:
        apply = _compile_map_stage(stage, self.ctx.default_batch_format)
        mb = next((o for o in stage if isinstance(o, L.MapBatches)), None)
        opts: Dict[str, Any] = {}
        if mb is not None:
            if mb.num_cpus is not None:
                opts["num_cpus"] = mb.num_cpus
            if mb.num_tpus:
                opts["num_tpus"] = mb.num_tpus
        label = "+".join(type(o).__name__ for o in stage)
        cell = [0.0]
        timed = self._timed(inputs, cell)
        if not self.collect_stats:
            remote_fn = rt.remote(apply)
            if opts:
                remote_fn = remote_fn.options(**opts)
            return self._bounded_submit(
                (remote_fn.remote(ref) for ref in timed), label, None,
                upstream_cell=cell)

        # Metered execution (Dataset.stats()): the task returns
        # (block, meta) — meta is a tiny dict of rows/bytes/udf seconds
        # measured where the block actually lives. The block ref streams
        # downstream unchanged; meta refs are resolved at stage end.
        def metered(block):
            acc = BlockAccessor(block)
            rows_in, bytes_in = acc.num_rows(), acc.size_bytes()
            t0 = time.perf_counter()
            out = apply(block)
            udf_s = time.perf_counter() - t0
            oacc = BlockAccessor(out)
            return out, {"udf_s": udf_s, "rows_in": rows_in,
                         "rows_out": oacc.num_rows(), "bytes_in": bytes_in,
                         "bytes_out": oacc.size_bytes()}

        remote_fn = rt.remote(metered).options(num_returns=2, **opts)
        metas: List[Any] = []

        def submissions():
            for ref in timed:
                block_ref, meta_ref = remote_fn.remote(ref)
                metas.append(meta_ref)
                yield block_ref

        return self._bounded_submit(submissions(), label, None,
                                    upstream_cell=cell, metas=metas)

    _PRESSURE_TTL_S = 0.05

    # Aggregate fields summed across records; everything else in an
    # extra dict overwrites (gauges like utilization / actor counts).
    _SUM_FIELDS = ("wall_s", "blocks", "upstream_s", "backpressure_s",
                   "udf_s", "rows_in", "rows_out", "bytes_in", "bytes_out",
                   "retries")

    def _record_stat(self, label: str, wall_s: float, blocks: int,
                     peak_pressure: float = 0.0, **extra: Any) -> None:
        row = {"operator": label, "wall_s": wall_s, "blocks": blocks,
               "peak_store_pressure": peak_pressure}
        row.update(extra)
        self.stats.append(row)
        agg = self.op_stats.setdefault(label, {
            "operator": label, "wall_s": 0.0, "self_s": 0.0,
            "upstream_s": 0.0, "udf_s": 0.0, "backpressure_s": 0.0,
            "blocks": 0, "rows_in": 0, "rows_out": 0,
            "bytes_in": 0, "bytes_out": 0, "retries": 0,
            "peak_store_pressure": 0.0, "records": 0,
            "block_bytes": {"count": 0, "sum": 0, "min": None, "max": 0},
        })
        agg["records"] += 1
        agg["wall_s"] += wall_s
        agg["blocks"] += blocks
        agg["peak_store_pressure"] = max(agg["peak_store_pressure"],
                                         peak_pressure)
        for k in self._SUM_FIELDS[2:]:
            v = extra.get(k)
            if v:
                agg[k] += v
        agg["self_s"] = max(0.0, agg["wall_s"] - agg["upstream_s"])
        for k, v in extra.items():
            if k not in self._SUM_FIELDS and k != "block_bytes":
                agg[k] = v
        bb = extra.get("block_bytes")
        if bb and bb.get("count"):
            dist = agg["block_bytes"]
            dist["count"] += bb["count"]
            dist["sum"] += bb["sum"]
            dist["max"] = max(dist["max"], bb["max"])
            dist["min"] = bb["min"] if dist["min"] is None \
                else min(dist["min"], bb["min"])
        self._export_stat(label, wall_s, blocks, extra)

    @staticmethod
    def _export_stat(label: str, wall_s: float, blocks: int,
                     extra: Dict[str, Any]) -> None:
        """Stream the recorded row into the rtpu_data_operator_* TSDB
        families (one inc per stage record, not per block)."""
        try:
            _op_seconds_total.inc(wall_s, tags={"operator": label,
                                                "phase": "wall"})
            if blocks:
                _op_blocks_total.inc(float(blocks),
                                     tags={"operator": label})
            for phase in ("udf", "backpressure"):
                v = extra.get(f"{phase}_s")
                if v:
                    _op_seconds_total.inc(v, tags={"operator": label,
                                                   "phase": phase})
            for d in ("in", "out"):
                r = extra.get(f"rows_{d}")
                if r:
                    _op_rows_total.inc(float(r), tags={"operator": label,
                                                       "dir": d})
                b = extra.get(f"bytes_{d}")
                if b:
                    _op_bytes_total.inc(float(b), tags={"operator": label,
                                                        "dir": d})
        except Exception:
            pass  # metrics export never fails a stage

    def stats_report(self, total_wall_s: Optional[float] = None) -> Dict[str, Any]:
        """Structured per-operator report from the running aggregates
        (reference: DatasetStats.to_summary()). Ordered by first
        execution; block_bytes carries the mean alongside min/max."""
        ops = []
        for agg in self.op_stats.values():
            row = dict(agg)
            dist = dict(row["block_bytes"])
            dist["mean"] = (dist["sum"] / dist["count"]) if dist["count"] \
                else 0
            row["block_bytes"] = dist
            # dir=out bytes are what this operator materialized into the
            # object store — the census-facing holding figure.
            row["store_bytes_out"] = row["bytes_out"]
            ops.append(row)
        # Rows/bytes out of the pipeline = the LAST operator that metered
        # them (all-to-all exchanges record wall/blocks but not rows).
        metered = [o for o in ops if o["rows_out"] or o["bytes_out"]]
        tail = metered[-1] if metered else None
        report: Dict[str, Any] = {
            "operators": ops,
            "total_rows_out": tail["rows_out"] if tail else 0,
            "total_bytes_out": tail["bytes_out"] if tail else 0,
            "sum_self_s": round(sum(o["self_s"] for o in ops), 6),
        }
        if total_wall_s is not None:
            report["total_wall_s"] = total_wall_s
        return report

    def _store_pressure(self) -> float:
        """Local object-store arena fill fraction (0.0 when no native arena
        is attached — e.g. inline-only stores). Sampled at most every
        _PRESSURE_TTL_S: this sits on the per-submission hot path and the
        reading can't move meaningfully faster than tasks complete."""
        now = time.perf_counter()
        cached = getattr(self, "_pressure_cache", None)
        if cached is not None and now - cached[0] < self._PRESSURE_TTL_S:
            return cached[1]
        try:
            from ray_tpu.core import native_store

            arena = native_store.get_arena()
            if arena is None:
                p = 0.0
            else:
                s = arena.stats()
                p = s["used"] / max(1, s["capacity"])
        except Exception:
            p = 0.0
        self._pressure_cache = (now, p)
        return p

    def _note_pressure(self, label: str, pressure: float) -> None:
        """TTL-throttled export of the sampled pressure as a per-stage
        gauge; stays off the per-submission hot path."""
        now = time.perf_counter()
        if now - getattr(self, "_pressure_gauge_ts", 0.0) >= 0.25:
            self._pressure_gauge_ts = now
            _pressure_gauge.set(pressure, tags={"stage": label})

    def _derivable(self, make_ref: Callable[[], Any]) -> Any:
        """Submit an all-to-all producing call and record its recipe so
        ft_get can re-derive the shard from surviving inputs if the stored
        copy is later lost with its node (tries the controller's
        _maybe_reconstruct lineage path first — see ft_get)."""
        ref = make_ref()
        if flags.get("RTPU_DATA_FT"):
            _remember_rederive(ref, make_ref)
        return ref

    def _register(self, ref: Any, make_ref: Callable[[], Any]) -> Any:
        """Like _derivable, but for stages whose cheap initial submission
        reuses intermediate shard refs while the recovery thunk recomputes
        from the stage's ORIGINAL inputs (two-round exchanges: the
        intermediates may be lost with the same node as the output, so a
        thunk depending on them would just trade ObjectLostError for
        DependencyError)."""
        if flags.get("RTPU_DATA_FT"):
            _remember_rederive(ref, make_ref)
        return ref

    def _bounded_submit(self, submissions: Iterator[Any], label: str,
                        total: Optional[int],
                        upstream_cell: Optional[List[float]] = None,
                        metas: Optional[List[Any]] = None) -> Iterator[Any]:
        """Cap in-flight tasks; yield refs in submission (FIFO) order when
        preserve_order else completion order. The cap is concurrency-based
        normally and shrinks under object-store memory pressure (see
        DataContext.memory_high_water) so block production stays bounded by
        downstream consumption, not by spilling capacity.

        The at-cap waits in the submission loop are the operator's
        backpressure: the driver wants to submit more but must first
        drain a completed block downstream. They are timed separately
        from the tail drain (which is completion latency, not pressure).
        """
        base_cap = self.ctx.max_tasks_in_flight
        high_water = self.ctx.memory_high_water
        progress_s = float(flags.get("RTPU_DATA_PROGRESS_S")) \
            if flags.get("RTPU_DATA_PROGRESS") else 0.0
        t0 = time.perf_counter()
        last_progress = t0
        n = 0
        backpressure_s = 0.0
        peak_pressure = 0.0
        pending: List[Any] = []
        preserve = self.ctx.preserve_order

        def progress() -> None:
            nonlocal last_progress
            now = time.perf_counter()
            if progress_s and now - last_progress >= progress_s:
                last_progress = now
                elapsed = max(1e-9, now - t0)
                print(f"[data] {label}: {n} blocks out, "
                      f"{len(pending)} in flight, {elapsed:.0f}s elapsed "
                      f"({n / elapsed:.1f} blocks/s)", file=sys.stderr)

        try:
            for ref in submissions:
                pending.append(ref)
                cap = base_cap
                pressure = self._store_pressure() if high_water else 0.0
                peak_pressure = max(peak_pressure, pressure)
                if high_water:
                    self._note_pressure(label, pressure)
                if high_water and pressure >= high_water:
                    cap = min(base_cap, max(1, self.ctx.memory_pressure_cap))
                while len(pending) >= cap:
                    tw = time.perf_counter()
                    if preserve:
                        out, pending = pending[0], pending[1:]
                        rt.wait([out], num_returns=1)
                    else:
                        ready, pending = rt.wait(pending, num_returns=1)
                        out = ready[0]
                    backpressure_s += time.perf_counter() - tw
                    n += 1
                    progress()
                    yield out
            while pending:
                if preserve:
                    out, pending = pending[0], pending[1:]
                    rt.wait([out], num_returns=1)
                else:
                    ready, pending = rt.wait(pending, num_returns=1)
                    out = ready[0]
                # Drain-phase pressure matters too: the tail blocks are
                # still materializing into the store.
                if high_water:
                    peak_pressure = max(peak_pressure,
                                        self._store_pressure())
                n += 1
                progress()
                yield out
        finally:
            # finally, not fallthrough: a downstream stage that stops
            # pulling early (Limit) raises GeneratorExit here — the stage
            # still ran and must still report.
            extra: Dict[str, Any] = {
                "backpressure_s": backpressure_s,
                "upstream_s": upstream_cell[0] if upstream_cell else 0.0,
            }
            if metas is not None:
                extra.update(self._resolve_metas(metas))
            self._record_stat(label, time.perf_counter() - t0, n,
                              peak_pressure=peak_pressure, **extra)

    @staticmethod
    def _resolve_metas(metas: List[Any]) -> Dict[str, Any]:
        """Sum the per-block meter dicts shipped back by metered map
        tasks. Only already-finished metas are fetched (short wait):
        an early-stopped stage (Limit) must not block its own teardown
        on stragglers, and a block whose task raised is simply absent
        from the accounting."""
        out = {"udf_s": 0.0, "rows_in": 0, "rows_out": 0,
               "bytes_in": 0, "bytes_out": 0}
        dist = {"count": 0, "sum": 0, "min": None, "max": 0}
        if not metas:
            out["block_bytes"] = dist
            return out
        try:
            ready, _ = rt.wait(metas, num_returns=len(metas), timeout=2.0)
        except Exception:
            ready = []
        for ref in ready:
            try:
                m = rt.get(ref)
            except Exception:
                continue
            for k in out:
                out[k] += m.get(k, 0)
            b = m.get("bytes_out", 0)
            dist["count"] += 1
            dist["sum"] += b
            dist["max"] = max(dist["max"], b)
            dist["min"] = b if dist["min"] is None else min(dist["min"], b)
        out["block_bytes"] = dist
        return out

    def _actor_pool_stage(self, inputs: Iterator[Any], op: L.MapBatches) -> Iterator[Any]:
        """Fixed/bounded actor pool (reference: ActorPoolMapOperator + _ActorPool
        autoscaling :375; TPU-aware: num_tpus reserves chips per actor so the
        pool lands one actor per TPU host — the ViT batch-inference shape).

        Self-healing under RTPU_DATA_FT: a typed system death
        (ActorDiedError / NodePreemptedError / WorkerCrashedError) on the
        in-flight ref replaces the dead actor in place and resubmits the
        affected input batch, bounded per batch by RTPU_DATA_FT_RETRIES.
        Preempted deaths (drain / spot reclamation) resubmit without
        consuming the budget — the PR 4 drain semantics applied to data.
        A TTL-gated poll of cluster state proactively migrates pool actors
        off draining nodes before the drain deadline SIGKILLs them
        mid-batch (placement of the replacement already avoids draining
        and suspect nodes: the scheduler excludes them). User exceptions
        are untouched — the errored ref is yielded downstream exactly as
        the fail-fast plane yields it.
        """
        conc = op.concurrency or 1
        if isinstance(conc, (tuple, list)):
            min_actors, max_actors = conc
        else:
            min_actors = max_actors = int(conc)
        actor_opts: Dict[str, Any] = {"max_concurrency": 2}
        if op.num_cpus is not None:
            actor_opts["num_cpus"] = op.num_cpus
        if op.num_tpus:
            actor_opts["num_tpus"] = op.num_tpus
        pool_cls = rt.remote(_PoolWorker)
        # Flags are read once per stage: the per-block hot path below pays
        # one bool test, never a registry lookup.
        ft = bool(flags.get("RTPU_DATA_FT"))
        retry_budget = int(flags.get("RTPU_DATA_FT_RETRIES")) if ft else 0
        drain_poll_s = float(flags.get("RTPU_DATA_DRAIN_POLL_S")) if ft else 0.0
        label = f"ActorPool[{getattr(op.fn, '__name__', type(op.fn).__name__)}]"
        fmt = op.batch_format or self.ctx.default_batch_format
        preserve = self.ctx.preserve_order
        progress_s = float(flags.get("RTPU_DATA_PROGRESS_S")) \
            if flags.get("RTPU_DATA_PROGRESS") else 0.0
        upstream_cell = [0.0]
        inputs = self._timed(inputs, upstream_cell)
        t0 = time.perf_counter()
        last_progress = t0
        n = 0
        retries = 0
        backpressure_s = 0.0
        # At-cap waits in the submission loop are backpressure; the tail
        # drain after inputs are exhausted is completion latency.
        in_submit = [True]
        per_actor_cap = 2

        def spawn() -> Any:
            return pool_cls.options(**actor_opts).remote(
                op.fn, op.fn_constructor_args, op.fn_constructor_kwargs)

        actors = [spawn() for _ in range(min_actors)]
        load = [0] * len(actors)
        incarnation = [0] * len(actors)
        # (slot, incarnation) -> [old handle, in-flight count]: a replaced
        # actor stays alive until its in-flight batches drain (proactive
        # migration must not kill work mid-batch), then is killed.
        retired: Dict[Tuple[int, int], List[Any]] = {}
        # Entries: {"ref", "slot", "inc", "actor", "input", "attempts"}.
        inflight: List[Dict[str, Any]] = []
        last_poll = [0.0]
        last_gauge = [0.0]

        def note_inflight() -> None:
            # TTL-throttled: the gauge is observability, not bookkeeping,
            # and must not put a lock acquisition on every block.
            now = time.perf_counter()
            if now - last_gauge[0] >= 0.25:
                last_gauge[0] = now
                _inflight_gauge.set(float(len(inflight)),
                                    tags={"stage": label})

        def _client():
            from ray_tpu.core import context as cctx
            return cctx.get_worker_context().client

        def _draining_nodes() -> set:
            rows = _client().request({"kind": "cluster_state"})["nodes"]
            return {r["node_id"] for r in rows
                    if r.get("state") in ("draining", "drained", "suspect")}

        def _actor_nodes() -> Dict[str, str]:
            rows = _client().request({"kind": "list_state", "what": "actors"})
            return {r["actor_id"]: r.get("node_id") for r in rows
                    if r.get("state") == "ALIVE"}

        def replace(i: int, proactive: bool) -> None:
            old, old_inc = actors[i], incarnation[i]
            pending = load[i]
            if pending > 0:
                # In-flight batches still reference the old handle; kill it
                # only once they drain (or fail, for a reactive replace).
                retired[(i, old_inc)] = [old, pending]
            else:
                try:
                    rt.kill(old)
                except Exception:
                    pass
            actors[i] = spawn()
            incarnation[i] += 1
            load[i] = 0
            if proactive:
                _count("proactive_migrations")

        def poll_drain() -> None:
            now = time.perf_counter()
            if now - last_poll[0] < drain_poll_s:
                return
            last_poll[0] = now
            try:
                dr = _draining_nodes()
                if not dr:
                    return
                nodes = _actor_nodes()
                for i in range(len(actors)):
                    nid = nodes.get(actors[i]._actor_id)
                    if nid is not None and nid in dr:
                        replace(i, proactive=True)
            except Exception:
                pass  # a failed poll never fails the stage

        def _died_preempted(entry: Dict[str, Any],
                            err: BaseException) -> bool:
            if getattr(err, "preempted", False):
                return True
            # Direct dispatch can fabricate a plain ActorDiedError on the
            # driver before the controller classifies the death; ask the
            # cluster whether the actor's node is in fact draining.
            try:
                rows = _client().request(
                    {"kind": "list_state", "what": "actors"})
                row = next((r for r in rows
                            if r["actor_id"] == entry["actor"]._actor_id),
                           None)
                if row is None or row.get("node_id") is None:
                    return False
                return row["node_id"] in _draining_nodes()
            except Exception:
                return False

        def submit(input_ref: Any, attempts: int = 0,
                   at_front: bool = False) -> None:
            if drain_poll_s > 0:
                poll_drain()
            # least-loaded dispatch; grow pool if saturated and below max
            i = min(range(len(actors)), key=lambda j: load[j])
            if load[i] >= per_actor_cap and len(actors) < max_actors:
                actors.append(spawn())
                load.append(0)
                incarnation.append(0)
                i = len(actors) - 1
            load[i] += 1
            entry = {
                "ref": actors[i].apply.remote(input_ref, fmt, op.batch_size,
                                              op.fn_args, op.fn_kwargs),
                "slot": i, "inc": incarnation[i], "actor": actors[i],
                "input": input_ref, "attempts": attempts,
            }
            # A resubmitted batch re-enters at the front in ordered mode so
            # the output stream stays byte-identical to an uninjected run.
            inflight.insert(0, entry) if at_front else inflight.append(entry)
            note_inflight()

        def settle(entry: Dict[str, Any]) -> None:
            i, e_inc = entry["slot"], entry["inc"]
            if e_inc == incarnation[i]:
                load[i] -= 1
            else:
                r = retired.get((i, e_inc))
                if r is not None:
                    r[1] -= 1
                    if r[1] <= 0:
                        del retired[(i, e_inc)]
                        try:
                            rt.kill(r[0])
                        except Exception:
                            pass

        def progress() -> None:
            nonlocal last_progress
            now = time.perf_counter()
            if progress_s and now - last_progress >= progress_s:
                last_progress = now
                elapsed = max(1e-9, now - t0)
                print(f"[data] {label}: {n} blocks out, "
                      f"{len(inflight)} in flight on {len(actors)} actors, "
                      f"{elapsed:.0f}s elapsed ({n / elapsed:.1f} blocks/s)",
                      file=sys.stderr)

        def drain_one() -> Any:
            nonlocal n, retries, backpressure_s
            while True:
                tw = time.perf_counter()
                if preserve:
                    entry = inflight.pop(0)
                    rt.wait([entry["ref"]], num_returns=1)
                else:
                    # Completion order: wait across the whole in-flight set
                    # (head-of-line FIFO here wedged the stage on one slow
                    # batch even with preserve_order off).
                    ready, _ = rt.wait([e["ref"] for e in inflight],
                                       num_returns=1)
                    rid = ready[0].object_id
                    idx = next(j for j, e in enumerate(inflight)
                               if e["ref"].object_id == rid)
                    entry = inflight.pop(idx)
                if in_submit[0]:
                    backpressure_s += time.perf_counter() - tw
                err = rt.error_of(entry["ref"]) if ft else None
                if err is None or not isinstance(
                        err, (ActorDiedError, WorkerCrashedError,
                              ObjectLostError)):
                    # Healthy block, or a user exception: both flow
                    # downstream unchanged (fail-fast parity for app errors).
                    settle(entry)
                    n += 1
                    note_inflight()
                    progress()
                    return entry["ref"]
                # Typed system death on the in-flight ref.
                preempted = _died_preempted(entry, err)
                if not preempted and entry["attempts"] >= retry_budget:
                    settle(entry)  # budget exhausted: surface the error
                    n += 1
                    return entry["ref"]
                if entry["inc"] == incarnation[entry["slot"]] and \
                        entry["actor"] is actors[entry["slot"]]:
                    replace(entry["slot"], proactive=False)
                settle(entry)
                if preempted:
                    cause = "preempted"
                    _count("preempted_retries")
                elif isinstance(err, ActorDiedError):
                    cause = "actor_died"
                    _count("retries")
                else:
                    cause = "worker_crashed"
                    _count("retries")
                _retries_total.inc(1.0, tags={"cause": cause})
                retries += 1
                submit(entry["input"],
                       attempts=entry["attempts"] + (0 if preempted else 1),
                       at_front=preserve)

        try:
            for ref in inputs:
                while len(inflight) >= per_actor_cap * len(actors):
                    yield drain_one()
                submit(ref)
            in_submit[0] = False
            while inflight:
                yield drain_one()
        finally:
            extra: Dict[str, Any] = {
                "retries": retries,
                "backpressure_s": backpressure_s,
                "upstream_s": upstream_cell[0],
            }
            if self.collect_stats:
                # Fetch each live worker's running meter before the pool
                # is torn down; meters on already-replaced (dead) actors
                # are simply absent from the accounting.
                meter = {"udf_s": 0.0, "rows_in": 0, "rows_out": 0,
                         "bytes_in": 0, "bytes_out": 0, "blocks": 0}
                metered = 0
                for a in actors:
                    try:
                        m = rt.get(a.meter.remote(), timeout=5.0)
                    except Exception:
                        continue
                    metered += 1
                    for k in meter:
                        meter[k] += m.get(k, 0)
                wall = max(1e-9, time.perf_counter() - t0)
                blocks_done = meter.pop("blocks")
                extra.update(meter)
                extra["block_bytes"] = {
                    "count": blocks_done, "sum": meter["bytes_out"],
                    "min": None, "max": 0}
                extra["actor_pool"] = {
                    "actors": len(actors),
                    "metered": metered,
                    # busy fraction: summed UDF seconds over the pool's
                    # aggregate wall-clock capacity.
                    "utilization": round(
                        meter["udf_s"] / (wall * max(1, len(actors))), 4),
                }
            for a in actors:
                try:
                    rt.kill(a)
                except Exception:
                    pass
            for old, _pending in retired.values():
                try:
                    rt.kill(old)
                except Exception:
                    pass
            self._record_stat(label, time.perf_counter() - t0, n, **extra)

    # -- all-to-all -----------------------------------------------------------

    def _counts(self, refs: List[Any]) -> List[int]:
        @rt.remote
        def count(b):
            return BlockAccessor(b).num_rows()

        return rt.get([count.remote(r) for r in refs])

    def _repartition(self, inputs: Iterator[Any], num_blocks: int) -> Iterator[Any]:
        refs = list(inputs)
        counts = self._counts(refs)
        total = sum(counts)
        bounds = [total * i // num_blocks for i in range(num_blocks + 1)]

        @rt.remote
        def build(start, end, *blocks):
            parts = []
            off = 0
            for b, c in zip(blocks, counts):
                lo, hi = max(start - off, 0), min(end - off, c)
                if lo < hi:
                    parts.append(BlockAccessor(b).slice(lo, hi))
                off += c
            return concat_blocks(parts) if parts else rows_to_block([])

        for i in range(num_blocks):
            yield self._derivable(
                lambda i=i: build.remote(bounds[i], bounds[i + 1], *refs))

    def _random_shuffle(self, inputs: Iterator[Any], seed: Optional[int]) -> Iterator[Any]:
        """Two-round push shuffle (reference: planner/exchange push-based
        shuffle): map tasks split each block into P random parts; reduce tasks
        concat + local permute."""
        refs = list(inputs)
        P = self.ctx.shuffle_partitions or max(1, len(refs))
        ft = bool(flags.get("RTPU_DATA_FT"))
        if seed is None and ft:
            # Pin an entropy-sourced seed so a shard lost to node death can
            # be re-derived bit-identically; the permutation is still
            # random across runs.
            seed = int(np.random.SeedSequence().entropy % (2 ** 31))

        def split(block, i):
            rng = np.random.default_rng(None if seed is None else seed + i)
            acc = BlockAccessor(block)
            n = acc.num_rows()
            perm = rng.permutation(n)
            out = [acc.take_rows(part) for part in np.array_split(perm, P)]
            return out if P > 1 else out[0]

        split_remote = rt.remote(split).options(num_returns=P)
        parts: List[List[Any]] = []
        for i, r in enumerate(refs):
            res = split_remote.remote(r, i)
            parts.append([res] if P == 1 else list(res))

        def reduce(j, *shards):
            rng = np.random.default_rng(None if seed is None else seed + 10_000 + j)
            merged = concat_blocks(list(shards))
            acc = BlockAccessor(merged)
            return acc.take_rows(rng.permutation(acc.num_rows()))

        reduce_remote = rt.remote(reduce)

        def split_one(block, i, j):
            out = split(block, i)
            return out[j] if P > 1 else out

        split_one_remote = rt.remote(split_one)

        for j in range(P):
            def rederive(j=j):
                # Recovery path: recompute only shard j of every input
                # (deterministic: the seed is pinned above), never touching
                # the round-1 part refs that may have died with the node.
                return reduce_remote.remote(j, *[
                    split_one_remote.remote(refs[i], i, j)
                    for i in range(len(refs))])

            yield self._register(
                reduce_remote.remote(
                    j, *[parts[i][j] for i in range(len(refs))]),
                rederive)

    def _sort(self, inputs: Iterator[Any], key: str, descending: bool) -> Iterator[Any]:
        """Sample-based range partition sort (reference: exchange/sort)."""
        refs = list(inputs)
        P = max(1, len(refs))

        @rt.remote
        def sample(b):
            cols = BlockAccessor(b).to_numpy()
            v = cols[key]
            if len(v) == 0:
                return v
            idx = np.random.default_rng(0).choice(len(v), min(20, len(v)), replace=False)
            return v[idx]

        samples = np.concatenate([s for s in rt.get([sample.remote(r) for r in refs])
                                  if len(s)]) if refs else np.array([])
        if len(samples) == 0:
            yield from refs
            return
        qs = np.quantile(np.sort(samples), np.linspace(0, 1, P + 1)[1:-1]) if P > 1 else []

        def partition(b):
            acc = BlockAccessor(b)
            v = acc.to_numpy()[key]
            ids = np.searchsorted(qs, v, side="right") if P > 1 else np.zeros(len(v), int)
            out = [acc.take_rows(np.nonzero(ids == p)[0]) for p in range(P)]
            return out if P > 1 else out[0]

        part_remote = rt.remote(partition).options(num_returns=P)
        parts = []
        for r in refs:
            res = part_remote.remote(r)
            parts.append([res] if P == 1 else list(res))

        def merge(*shards):
            merged = concat_blocks(list(shards))
            acc = BlockAccessor(merged)
            order = np.argsort(acc.to_numpy()[key], kind="stable")
            if descending:
                order = order[::-1]
            return acc.take_rows(order)

        merge_remote = rt.remote(merge)

        def part_one(b, j):
            out = partition(b)
            return out[j] if P > 1 else out

        part_one_remote = rt.remote(part_one)

        def make_merge(j):
            def rederive():
                return merge_remote.remote(*[
                    part_one_remote.remote(refs[i], j)
                    for i in range(len(refs))])
            return rederive

        outs = [self._register(
                    merge_remote.remote(
                        *[parts[i][j] for i in range(len(refs))]),
                    make_merge(j))
                for j in range(P)]
        yield from (outs[::-1] if descending else outs)

    def _limit(self, inputs: Iterator[Any], n: int) -> Iterator[Any]:
        taken = 0

        @rt.remote
        def head(b, k):
            return BlockAccessor(b).slice(0, k)

        @rt.remote
        def count(b):
            return BlockAccessor(b).num_rows()

        for ref in inputs:
            if taken >= n:
                break
            c = rt.get(count.remote(ref))
            if taken + c <= n:
                taken += c
                yield ref
            else:
                yield head.remote(ref, n - taken)
                taken = n

    def _union(self, inputs: Iterator[Any], other_plans: List[List[L.LogicalOp]]) -> Iterator[Any]:
        yield from inputs
        for plan in other_plans:
            yield from StreamingExecutor(self.ctx).execute(plan)

    def _zip(self, inputs: Iterator[Any], other_plan: List[L.LogicalOp]) -> Iterator[Any]:
        left = list(inputs)
        right = list(StreamingExecutor(self.ctx).execute(other_plan))
        lcounts = self._counts(left)
        rcounts = self._counts(right)
        if sum(lcounts) != sum(rcounts):
            raise ValueError(
                f"zip requires equal row counts, got {sum(lcounts)} vs {sum(rcounts)}"
            )

        @rt.remote
        def zip_slice(start, end, lblock, *rblocks):
            lcols = BlockAccessor(lblock).to_numpy()
            parts = []
            off = 0
            for rb, c in zip(rblocks, rcounts):
                lo, hi = max(start - off, 0), min(end - off, c)
                if lo < hi:
                    parts.append(BlockAccessor(rb).slice(lo, hi))
                off += c
            rcols = BlockAccessor(concat_blocks(parts)).to_numpy()
            out = dict(lcols)
            for k, v in rcols.items():
                out[k if k not in out else f"{k}_1"] = v
            return out

        off = 0
        for lb, c in zip(left, lcounts):
            yield self._derivable(
                lambda off=off, c=c, lb=lb: zip_slice.remote(
                    off, off + c, lb, *right))
            off += c

    def _aggregate(self, inputs: Iterator[Any], op: L.Aggregate) -> Iterator[Any]:
        """Hash-partition groupby + per-partition pandas aggregate
        (reference: grouped_data.py over sort-based exchange)."""
        refs = list(inputs)
        key = op.key
        aggs = op.aggs
        P = max(1, min(len(refs), 8)) if key is not None else 1

        if key is None:
            @rt.remote
            def global_agg(*blocks):
                import pandas as pd

                df = pd.concat([BlockAccessor(b).to_pandas() for b in blocks])
                row: Dict[str, Any] = {}
                for kind, col, out_name in aggs:
                    if kind == "count":
                        row[out_name] = len(df)
                    else:
                        row[out_name] = getattr(df[col], kind)()
                return rows_to_block([row])

            yield self._derivable(lambda: global_agg.remote(*refs))
            return

        def part_fn(b):
            import zlib

            acc = BlockAccessor(b)
            v = acc.to_numpy()[key]
            # Stable cross-process hash: Python's hash() is salted per process
            # (PYTHONHASHSEED), which would scatter one key across partitions.
            h = np.array([zlib.crc32(repr(x).encode()) % P for x in v.tolist()])
            out = [acc.take_rows(np.nonzero(h == p)[0]) for p in range(P)]
            return out if P > 1 else out[0]

        part_remote = rt.remote(part_fn).options(num_returns=P)
        parts = []
        for r in refs:
            res = part_remote.remote(r)
            parts.append([res] if P == 1 else list(res))

        def agg_fn(*shards):
            import pandas as pd

            df = pd.concat([BlockAccessor(b).to_pandas() for b in shards])
            if df.empty:
                return rows_to_block([])
            g = df.groupby(key, sort=True)
            out = pd.DataFrame(index=g.size().index)
            for kind, col, out_name in aggs:
                if kind == "count":
                    out[out_name] = g.size()
                else:
                    out[out_name] = getattr(g[col], kind)()
            out = out.reset_index()
            return {c: out[c].to_numpy() for c in out.columns}

        agg_remote = rt.remote(agg_fn)

        def part_one(b, j):
            out = part_fn(b)
            return out[j] if P > 1 else out

        part_one_remote = rt.remote(part_one)

        for j in range(P):
            def rederive(j=j):
                return agg_remote.remote(*[
                    part_one_remote.remote(refs[i], j)
                    for i in range(len(refs))])

            yield self._register(
                agg_remote.remote(
                    *[parts[i][j] for i in range(len(refs))]),
                rederive)
