"""Batch iteration, device prefetch, and coordinated streaming splits.

Parity: reference data/iterator.py (DataIterator), block_batching/ (batcher +
local shuffle buffer), and the OutputSplitter operator backing
Dataset.streaming_split (_internal/execution/operators/output_splitter.py).
TPU-first: `device_batch_stream` overlaps `jax.device_put` H2D with consumer
compute via a small prefetch queue — the torch `prefetch_batches`/pin-memory
analog for XLA.

Resumable ingest (RTPU_DATA_FT): `IngestCursor` journals (epoch,
block-offset) through the durable-checkpoint store, `DataIterator` and
`streaming_split(resume_key=...)` ride it so a restarted trainer resumes
mid-epoch without re-reading or double-delivering blocks, and
`SplitCoordinator` journals its handout log so a restarted coordinator
replays assignments instead of orphaning splits.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

import cloudpickle
import numpy as np

import ray_tpu as rt

from .block import BlockAccessor, concat_blocks
from .executor import ft_get


class IngestCursor:
    """Journaled ingest position: (epoch, block_offset, carry_rows).

    Rides the PR 8 durable-checkpoint file store (host-local, atomic
    rename, survives process SIGKILL): each `advance` writes one small
    record under id ``data_cursor_<key>`` and prunes older ones. Meaning
    of a state: blocks ``[0, block_offset)`` of epoch ``epoch`` were
    fully pulled AND delivered as batches, except the last ``carry_rows``
    rows of block ``block_offset - 1``, which had not yet left the
    batcher when the journal was cut. The journal advances as each batch
    is consumed (a pull of batch k+1 proves batch k was delivered), so
    resume re-fetches only one block's tail and batch boundaries — and
    therefore the delivered sample stream — are identical to an
    uninterrupted run.
    """

    def __init__(self, key: str):
        from ray_tpu.core import checkpoint as ckpt

        self._ckpt = ckpt
        self._id = f"data_cursor_{key}"
        self._seq = 0
        self.state: Dict[str, int] = {"epoch": 0, "block_offset": 0,
                                      "carry_rows": 0}
        latest = ckpt.newest_local(self._id)
        if latest is not None:
            self._seq, blob = latest
            try:
                self.state.update(cloudpickle.loads(blob))
            except Exception:
                pass  # unreadable journal == fresh start, never a crash

    def advance(self, epoch: int, block_offset: int,
                carry_rows: int = 0) -> None:
        self.state = {"epoch": epoch, "block_offset": block_offset,
                      "carry_rows": carry_rows}
        self._seq += 1
        self._ckpt.write_local(self._id, self._seq,
                               cloudpickle.dumps(self.state))

    def clear(self) -> None:
        self.state = {"epoch": self.state["epoch"] + 1, "block_offset": 0,
                      "carry_rows": 0}
        self._seq += 1
        self._ckpt.write_local(self._id, self._seq,
                               cloudpickle.dumps(self.state))


def _batch_rows(batch: Any) -> int:
    try:
        if isinstance(batch, dict):
            return len(next(iter(batch.values()))) if batch else 0
        if hasattr(batch, "num_rows"):       # arrow table
            nr = batch.num_rows
            return nr() if callable(nr) else nr
        return len(batch)                    # pandas frame
    except Exception:
        return 0


def batch_stream(refs: Iterator[Any], batch_size: Optional[int], batch_format: str,
                 drop_last: bool, shuffle_buffer: Optional[int],
                 shuffle_seed: Optional[int],
                 cursor: Optional[IngestCursor] = None) -> Iterator[Any]:
    """Re-chunk a stream of block refs into fixed-size batches, metered
    into the operator TSDB families under operator="iter" (the
    driver-side consumption edge of the pipeline) and, with
    RTPU_DATA_PROGRESS, narrated to stderr every RTPU_DATA_PROGRESS_S.
    """
    from ray_tpu import flags

    from .executor import _op_rows_total, _op_seconds_total

    progress_s = float(flags.get("RTPU_DATA_PROGRESS_S")) \
        if flags.get("RTPU_DATA_PROGRESS") else 0.0
    inner = _batch_stream_impl(refs, batch_size, batch_format, drop_last,
                               shuffle_buffer, shuffle_seed, cursor)
    t0 = time.perf_counter()
    last_progress = t0
    batches = 0
    rows = 0
    try:
        for batch in inner:
            batches += 1
            rows += _batch_rows(batch)
            yield batch
            if progress_s:
                now = time.perf_counter()
                if now - last_progress >= progress_s:
                    last_progress = now
                    elapsed = max(1e-9, now - t0)
                    import sys

                    print(f"[data] iter: {batches} batches, {rows} rows "
                          f"({rows / elapsed:.0f} rows/s)", file=sys.stderr)
    finally:
        try:
            _op_seconds_total.inc(time.perf_counter() - t0,
                                  tags={"operator": "iter", "phase": "wall"})
            if rows:
                _op_rows_total.inc(float(rows),
                                   tags={"operator": "iter", "dir": "out"})
        except Exception:
            pass


def _batch_stream_impl(refs: Iterator[Any], batch_size: Optional[int], batch_format: str,
                       drop_last: bool, shuffle_buffer: Optional[int],
                       shuffle_seed: Optional[int],
                       cursor: Optional[IngestCursor] = None) -> Iterator[Any]:
    """Re-chunk a stream of block refs into fixed-size batches.

    With a `cursor`, journal progress at block-pull boundaries and resume
    from the journaled (block_offset, carry_rows) — skipped blocks are
    never fetched (only block_offset-1's tail is re-pulled to rebuild the
    carry). Incompatible with a local shuffle buffer: the buffer makes
    delivery order depend on how much was buffered at the crash, which
    cannot be replayed exactly.
    """
    if cursor is not None and shuffle_buffer:
        raise ValueError(
            "resumable ingest (cursor) cannot be combined with a local "
            "shuffle buffer: buffered rows make exactly-once block "
            "delivery unreplayable; shuffle upstream (random_shuffle / "
            "randomize_block_order) instead")
    rng = np.random.default_rng(shuffle_seed)
    carry = None  # leftover block
    buffer: List[Dict[str, np.ndarray]] = []
    buffered_rows = 0
    skip = cursor.state["block_offset"] if cursor is not None else 0
    resume_carry_rows = cursor.state["carry_rows"] if cursor is not None else 0
    epoch = cursor.state["epoch"] if cursor is not None else 0

    def emit(block) -> Iterator[Any]:
        nonlocal carry
        acc = BlockAccessor(block)
        n = acc.num_rows()
        if batch_size is None:
            if n:
                yield acc.to_batch(batch_format)
            return
        start = 0
        while n - start >= batch_size:
            yield BlockAccessor(acc.slice(start, start + batch_size)).to_batch(batch_format)
            start += batch_size
        carry = acc.slice(start, n) if start < n else None

    def emit_journaled(block, idx) -> Iterator[Any]:
        # Cursor-aware variant of emit: journal each batch as it is handed
        # out, BEFORE the yield — a batch the consumer received is never
        # re-delivered after a restart. (The converse corner — a crash
        # between the journal write and the consumer taking the batch —
        # skips that one batch; trainers that need it exactly pair the
        # cursor's state_dict with their own checkpoint.) The carry is
        # always shorter than one batch, so every batch boundary maps to
        # a unique (block, undelivered-tail) pair and resume realigns
        # exactly.
        nonlocal carry
        carry_len = BlockAccessor(carry).num_rows() if carry is not None \
            else 0
        if carry is not None:
            block = concat_blocks([carry, block])
            carry = None
        acc = BlockAccessor(block)
        n = acc.num_rows()
        fresh = n - carry_len  # rows that belong to block `idx` itself
        if batch_size is None:
            cursor.advance(epoch, idx + 1, 0)
            if n:
                yield acc.to_batch(batch_format)
            return
        start = 0
        while n - start >= batch_size:
            nxt = start + batch_size
            cursor.advance(epoch, idx + 1, fresh - max(0, nxt - carry_len))
            yield BlockAccessor(acc.slice(start, nxt)).to_batch(batch_format)
            start = nxt
        carry = acc.slice(start, n) if start < n else None

    idx = -1
    for ref in refs:
        idx += 1
        if idx < skip:
            if idx == skip - 1 and resume_carry_rows:
                # The one re-fetch on resume: the tail of the last
                # journaled block re-seeds the carry so batch boundaries
                # line up with the uninterrupted run.
                acc = BlockAccessor(ft_get(ref))
                n = acc.num_rows()
                carry = acc.slice(n - resume_carry_rows, n)
            continue
        block = ft_get(ref)
        if shuffle_buffer:
            acc = BlockAccessor(block)
            buffer.append(acc.to_numpy())
            buffered_rows += acc.num_rows()
            if buffered_rows >= shuffle_buffer:
                merged = BlockAccessor(concat_blocks(buffer))
                perm = rng.permutation(merged.num_rows())
                buffer, buffered_rows = [], 0
                block = merged.take_rows(perm)
            else:
                continue
        if cursor is not None:
            yield from emit_journaled(block, idx)
            continue
        if carry is not None:
            block = concat_blocks([carry, block])
            carry = None
        yield from emit(block)
    if shuffle_buffer and buffer:
        merged = BlockAccessor(concat_blocks(buffer))
        block = merged.take_rows(rng.permutation(merged.num_rows()))
        if carry is not None:
            block = concat_blocks([carry, block])
            carry = None
        yield from emit(block)
    if carry is not None and not drop_last:
        acc = BlockAccessor(carry)
        if acc.num_rows():
            yield acc.to_batch(batch_format)
    if cursor is not None:
        cursor.clear()  # epoch complete: roll to (epoch + 1, 0)


def device_batch_stream(batches: Iterator[Dict[str, np.ndarray]], sharding,
                        prefetch: int) -> Iterator[Any]:
    """Move numpy batches onto device ahead of consumption.

    A producer thread runs `jax.device_put` (async dispatch: returns as soon
    as the transfer is enqueued) keeping up to `prefetch` batches in flight,
    so HBM fills while the consumer's previous step computes.
    """
    import jax

    q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, prefetch))
    _DONE = object()
    stop = threading.Event()

    def put(item: Any) -> bool:
        # Bounded put that notices consumer abandonment: without the stop
        # check a dropped generator would block this thread in q.put forever,
        # pinning `prefetch` device batches in HBM for the process lifetime.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            for b in batches:
                if stop.is_set():
                    return
                dev = jax.device_put(b, sharding) if sharding is not None \
                    else jax.device_put(b)
                if not put(dev):
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced on the consumer side
            put(e)
        finally:
            put(_DONE)

    t = threading.Thread(target=produce, name="device-prefetch", daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class SplitCoordinator:
    """Actor feeding n consumers from one executed stream on demand
    (reference: OutputSplitter behind streaming_split, output_splitter.py;
    `equal=False` semantics — first-come first-served block handout).

    Failover (RTPU_DATA_FT): with a `name`, every epoch-0 handout appends
    to an assignment journal persisted through the durable-checkpoint
    store. A restarted coordinator (max_restarts re-runs the constructor)
    re-executes the deterministic stream and replays the journal, so
    every split's already-assigned blocks are re-derivable and a consumer
    asking for position `pos` gets the same block it would have gotten —
    orphaned splits are re-assigned instead of lost.
    """

    def __init__(self, ops, ctx, n: int, name: Optional[str] = None):
        from .executor import StreamingExecutor

        self._stream = StreamingExecutor(ctx).execute(ops)
        self._lock = threading.Lock()
        self.n = n
        self._epoch_refs: List[Any] = []  # replayable for repeated epochs
        self._consumed_all = False
        self._positions: Dict[Any, int] = {}
        # Epoch-0 handout log: per-split refs in handout order, plus the
        # stream-order assignment journal that reconstructs it.
        self._handout: List[List[Any]] = [[] for _ in range(n)]
        self._assignments: List[int] = []
        self._journal_id = f"data_split_{name}" if name else None
        self._journal_seq = 0
        if self._journal_id is not None:
            self._replay_journal()

    def _replay_journal(self) -> None:
        from ray_tpu.core import checkpoint as ckpt

        latest = ckpt.newest_local(self._journal_id)
        if latest is None:
            return
        self._journal_seq, blob = latest
        try:
            assignments = cloudpickle.loads(blob)
        except Exception:
            return
        # Re-derive each previously handed-out block by pulling the
        # re-executed stream in the same order (preserve_order pipelines
        # are deterministic, so position k is the same block as before
        # the crash).
        for split_idx in assignments:
            try:
                ref = next(self._stream)
            except StopIteration:
                self._consumed_all = True
                break
            self._epoch_refs.append(ref)
            self._handout[split_idx].append(ref)
            self._assignments.append(split_idx)

    def _journal(self) -> None:
        if self._journal_id is None:
            return
        from ray_tpu.core import checkpoint as ckpt

        self._journal_seq += 1
        try:
            ckpt.write_local(self._journal_id, self._journal_seq,
                             cloudpickle.dumps(self._assignments))
        except Exception:
            pass  # journal loss degrades failover, never the stream

    def next_block(self, split_idx: int, epoch: int,
                   pos: Optional[int] = None) -> Optional[Any]:
        with self._lock:
            if epoch == 0:
                if pos is not None and pos < len(self._handout[split_idx]):
                    # Re-delivery: a restarted consumer (or one talking to
                    # a restarted coordinator) resumes at its journaled
                    # position and receives the identical assignment.
                    return self._handout[split_idx][pos]
                # First epoch: dynamic first-come-first-served handout straight
                # off the streaming executor (load-balances uneven consumers).
                if self._consumed_all:
                    return None
                try:
                    ref = next(self._stream)
                except StopIteration:
                    self._consumed_all = True
                    return None
                self._epoch_refs.append(ref)
                self._handout[split_idx].append(ref)
                self._assignments.append(split_idx)
                self._journal()
                return ref
            # Later epochs replay the materialized refs round-robin.
            refs = [r for i, r in enumerate(self._epoch_refs)
                    if i % self.n == split_idx]
            if pos is None:
                key = (split_idx, epoch)
                pos = self._positions.get(key, 0)
                self._positions[key] = pos + 1
            if pos >= len(refs):
                return None
            return refs[pos]


class SplitIterator:
    """Per-consumer handle to a SplitCoordinator.

    With a `cursor`, the iterator journals (epoch, block position) after
    each block is consumed and resumes from the journal after a restart;
    paired with the coordinator's handout log this gives exactly-once
    block delivery per split across both consumer and coordinator
    failures (block granularity: batch boundaries realign at the resumed
    block edge).
    """

    def __init__(self, coordinator, split_idx: int,
                 cursor: Optional[IngestCursor] = None):
        self._coord = coordinator
        self._idx = split_idx
        self._cursor = cursor
        self._epoch = cursor.state["epoch"] if cursor is not None else 0
        self._pos = cursor.state["block_offset"] if cursor is not None else 0

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self._epoch, "block_offset": self._pos}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self._epoch = int(state["epoch"])
        self._pos = int(state["block_offset"])

    def _next_block(self) -> Any:
        """One coordinator round-trip, retried across coordinator restarts.

        A call in flight when the coordinator dies surfaces ActorDiedError
        even though max_restarts brings the actor back; with RTPU_DATA_FT
        the journal-replaying restart returns the identical assignment for
        (epoch, pos), so retrying is exact — not at-least-once.
        """
        from ray_tpu import flags

        attempts = 0
        while True:
            try:
                return rt.get(self._coord.next_block.remote(
                    self._idx, self._epoch, self._pos))
            except (rt.ActorDiedError, rt.WorkerCrashedError):
                if not flags.get("RTPU_DATA_FT") or attempts >= 20:
                    raise
                attempts += 1
                time.sleep(0.25)

    def _ref_stream(self) -> Iterator[Any]:
        while True:
            ref = self._next_block()
            if ref is None:
                self._epoch += 1
                self._pos = 0
                if self._cursor is not None:
                    self._cursor.advance(self._epoch, 0)
                return
            yield ref
            self._pos += 1
            if self._cursor is not None:
                # Past the yield: the consumer asked for the next block,
                # so this one was delivered — journal the new position.
                self._cursor.advance(self._epoch, self._pos)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        return batch_stream(self._ref_stream(), batch_size, batch_format,
                            drop_last, local_shuffle_buffer_size, local_shuffle_seed)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._ref_stream():
            yield from BlockAccessor(ft_get(ref)).iter_rows()

    def iter_device_batches(self, *, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        return device_batch_stream(
            self.iter_batches(batch_size=batch_size, batch_format="numpy"),
            sharding, prefetch,
        )


class DataIterator:
    """Resumable iteration handle over a Dataset (reference: DataIterator,
    data/iterator.py). With a `resume_key`, batch iteration journals an
    (epoch, block-offset, carry-rows) cursor through the durable
    checkpoint store: a restarted trainer constructing the iterator with
    the same key resumes mid-epoch — already-delivered blocks are skipped
    without being fetched, and the one partially-batched block tail is
    re-pulled so batch boundaries match an uninterrupted run exactly."""

    def __init__(self, dataset, resume_key: Optional[str] = None):
        self._ds = dataset
        self._cursor = IngestCursor(resume_key) if resume_key else None

    @property
    def cursor(self) -> Optional[IngestCursor]:
        return self._cursor

    def state_dict(self) -> Dict[str, int]:
        return dict(self._cursor.state) if self._cursor is not None else {}

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        return batch_stream(
            self._ds._execute(), batch_size, batch_format, drop_last,
            local_shuffle_buffer_size, local_shuffle_seed,
            cursor=self._cursor,
        )

    def iter_device_batches(self, *, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        return device_batch_stream(
            self.iter_batches(batch_size=batch_size, batch_format="numpy"),
            sharding, prefetch,
        )
