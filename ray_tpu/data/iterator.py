"""Batch iteration, device prefetch, and coordinated streaming splits.

Parity: reference data/iterator.py (DataIterator), block_batching/ (batcher +
local shuffle buffer), and the OutputSplitter operator backing
Dataset.streaming_split (_internal/execution/operators/output_splitter.py).
TPU-first: `device_batch_stream` overlaps `jax.device_put` H2D with consumer
compute via a small prefetch queue — the torch `prefetch_batches`/pin-memory
analog for XLA.
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

import ray_tpu as rt

from .block import BlockAccessor, concat_blocks


def batch_stream(refs: Iterator[Any], batch_size: Optional[int], batch_format: str,
                 drop_last: bool, shuffle_buffer: Optional[int],
                 shuffle_seed: Optional[int]) -> Iterator[Any]:
    """Re-chunk a stream of block refs into fixed-size batches."""
    rng = np.random.default_rng(shuffle_seed)
    carry = None  # leftover block
    buffer: List[Dict[str, np.ndarray]] = []
    buffered_rows = 0

    def emit(block) -> Iterator[Any]:
        nonlocal carry
        acc = BlockAccessor(block)
        n = acc.num_rows()
        if batch_size is None:
            if n:
                yield acc.to_batch(batch_format)
            return
        start = 0
        while n - start >= batch_size:
            yield BlockAccessor(acc.slice(start, start + batch_size)).to_batch(batch_format)
            start += batch_size
        carry = acc.slice(start, n) if start < n else None

    for ref in refs:
        block = rt.get(ref)
        if shuffle_buffer:
            acc = BlockAccessor(block)
            buffer.append(acc.to_numpy())
            buffered_rows += acc.num_rows()
            if buffered_rows >= shuffle_buffer:
                merged = BlockAccessor(concat_blocks(buffer))
                perm = rng.permutation(merged.num_rows())
                buffer, buffered_rows = [], 0
                block = merged.take_rows(perm)
            else:
                continue
        if carry is not None:
            block = concat_blocks([carry, block])
            carry = None
        yield from emit(block)
    if shuffle_buffer and buffer:
        merged = BlockAccessor(concat_blocks(buffer))
        block = merged.take_rows(rng.permutation(merged.num_rows()))
        if carry is not None:
            block = concat_blocks([carry, block])
            carry = None
        yield from emit(block)
    if carry is not None and not drop_last:
        acc = BlockAccessor(carry)
        if acc.num_rows():
            yield acc.to_batch(batch_format)


def device_batch_stream(batches: Iterator[Dict[str, np.ndarray]], sharding,
                        prefetch: int) -> Iterator[Any]:
    """Move numpy batches onto device ahead of consumption.

    A producer thread runs `jax.device_put` (async dispatch: returns as soon
    as the transfer is enqueued) keeping up to `prefetch` batches in flight,
    so HBM fills while the consumer's previous step computes.
    """
    import jax

    q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, prefetch))
    _DONE = object()
    stop = threading.Event()

    def put(item: Any) -> bool:
        # Bounded put that notices consumer abandonment: without the stop
        # check a dropped generator would block this thread in q.put forever,
        # pinning `prefetch` device batches in HBM for the process lifetime.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            for b in batches:
                if stop.is_set():
                    return
                dev = jax.device_put(b, sharding) if sharding is not None \
                    else jax.device_put(b)
                if not put(dev):
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced on the consumer side
            put(e)
        finally:
            put(_DONE)

    t = threading.Thread(target=produce, name="device-prefetch", daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()


class SplitCoordinator:
    """Actor feeding n consumers from one executed stream on demand
    (reference: OutputSplitter behind streaming_split, output_splitter.py;
    `equal=False` semantics — first-come first-served block handout)."""

    def __init__(self, ops, ctx, n: int):
        from .executor import StreamingExecutor

        self._stream = StreamingExecutor(ctx).execute(ops)
        self._lock = threading.Lock()
        self.n = n
        self._epoch_refs: List[Any] = []  # replayable for repeated epochs
        self._consumed_all = False
        self._positions: Dict[Any, int] = {}

    def next_block(self, split_idx: int, epoch: int) -> Optional[Any]:
        with self._lock:
            if epoch == 0:
                # First epoch: dynamic first-come-first-served handout straight
                # off the streaming executor (load-balances uneven consumers).
                if self._consumed_all:
                    return None
                try:
                    ref = next(self._stream)
                    self._epoch_refs.append(ref)
                    return ref
                except StopIteration:
                    self._consumed_all = True
                    return None
            # Later epochs replay the materialized refs round-robin.
            refs = [r for i, r in enumerate(self._epoch_refs)
                    if i % self.n == split_idx]
            key = (split_idx, epoch)
            pos = self._positions.get(key, 0)
            if pos >= len(refs):
                return None
            self._positions[key] = pos + 1
            return refs[pos]


class SplitIterator:
    """Per-consumer handle to a SplitCoordinator."""

    def __init__(self, coordinator, split_idx: int):
        self._coord = coordinator
        self._idx = split_idx
        self._epoch = 0

    def _ref_stream(self) -> Iterator[Any]:
        while True:
            ref = rt.get(self._coord.next_block.remote(self._idx, self._epoch))
            if ref is None:
                self._epoch += 1
                return
            yield ref

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy", drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        return batch_stream(self._ref_stream(), batch_size, batch_format,
                            drop_last, local_shuffle_buffer_size, local_shuffle_seed)

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for ref in self._ref_stream():
            yield from BlockAccessor(rt.get(ref)).iter_rows()

    def iter_device_batches(self, *, batch_size: int = 256, sharding=None,
                            prefetch: int = 2) -> Iterator[Any]:
        return device_batch_stream(
            self.iter_batches(batch_size=batch_size, batch_format="numpy"),
            sharding, prefetch,
        )
