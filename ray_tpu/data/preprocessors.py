"""Dataset preprocessors: fit/transform over numpy batches.

Parity: reference python/ray/data/preprocessors/ (Preprocessor base with
fit/transform/transform_batch, BatchMapper, StandardScaler, Chain,
TorchVisionPreprocessor). The TPU-native shape drops the torch dependency:
every transform is a numpy batch function applied via
``Dataset.map_batches``, so preprocessing fuses into the same streaming
pipeline that feeds the device actor pool (BASELINE.json config 5).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

Batch = Dict[str, np.ndarray]


class Preprocessor:
    """fit() computes statistics over a Dataset; transform() applies the
    batch function lazily via map_batches; transform_batch() applies it to
    one in-memory batch (the serve/inference path)."""

    _fitted = True  # stateless by default

    def fit(self, ds) -> "Preprocessor":
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._fitted:
            raise RuntimeError(f"{type(self).__name__} not fitted")
        return ds.map_batches(self.transform_batch, batch_format="numpy")

    def transform_batch(self, batch: Batch) -> Batch:
        raise NotImplementedError


class BatchMapper(Preprocessor):
    """Wrap a plain numpy-batch function (reference BatchMapper)."""

    def __init__(self, fn: Callable[[Batch], Batch]):
        self.fn = fn

    def transform_batch(self, batch: Batch) -> Batch:
        return self.fn(batch)


class Chain(Preprocessor):
    def __init__(self, *stages: Preprocessor):
        self.stages = stages

    def fit(self, ds) -> "Preprocessor":
        # Each stage fits on the data as transformed by the previous ones
        # (reference Chain semantics).
        for i, st in enumerate(self.stages):
            st.fit(ds)
            if i < len(self.stages) - 1:
                ds = st.transform(ds)
        return self

    def transform_batch(self, batch: Batch) -> Batch:
        for st in self.stages:
            batch = st.transform_batch(batch)
        return batch


class StandardScaler(Preprocessor):
    """Column-wise (x - mean) / std, statistics from fit()."""

    def __init__(self, columns: Sequence[str]):
        self.columns = list(columns)
        self.stats: Dict[str, Tuple[float, float]] = {}
        self._fitted = False

    def fit(self, ds) -> "Preprocessor":
        agg = {c: [0.0, 0.0, 0] for c in self.columns}  # sum, sumsq, n
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in self.columns:
                v = np.asarray(batch[c], np.float64)
                agg[c][0] += float(v.sum())
                agg[c][1] += float((v * v).sum())
                agg[c][2] += v.size
        for c, (s, sq, n) in agg.items():
            mean = s / max(n, 1)
            var = max(sq / max(n, 1) - mean * mean, 0.0)
            self.stats[c] = (mean, float(np.sqrt(var)) or 1.0)
        self._fitted = True
        return self

    def transform_batch(self, batch: Batch) -> Batch:
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats[c]
            out[c] = (np.asarray(batch[c], np.float32) - mean) / (std + 1e-8)
        return out


class ImageNormalizer(Preprocessor):
    """uint8 [B,H,W,C] images -> float32, scaled to [0,1], then per-channel
    (x - mean) / std — the torchvision Normalize recipe without torch
    (reference TorchVisionPreprocessor's common use)."""

    def __init__(self, mean: Sequence[float] = (0.485, 0.456, 0.406),
                 std: Sequence[float] = (0.229, 0.224, 0.225),
                 column: str = "image"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.column = column

    def transform_batch(self, batch: Batch) -> Batch:
        out = dict(batch)
        img = np.asarray(batch[self.column], np.float32) / 255.0
        out[self.column] = (img - self.mean) / self.std
        return out


class LabelEncoder(Preprocessor):
    """String labels -> int codes (reference LabelEncoder)."""

    def __init__(self, column: str):
        self.column = column
        self.classes_: Dict[Any, int] = {}
        self._fitted = False

    def fit(self, ds) -> "Preprocessor":
        seen = set()
        for batch in ds.iter_batches(batch_format="numpy"):
            seen.update(np.asarray(batch[self.column]).tolist())
        self.classes_ = {v: i for i, v in enumerate(sorted(seen))}
        self._fitted = True
        return self

    def transform_batch(self, batch: Batch) -> Batch:
        out = dict(batch)
        out[self.column] = np.asarray(
            [self.classes_[v] for v in
             np.asarray(batch[self.column]).tolist()], np.int64)
        return out
