"""GroupedData: groupby aggregations (reference: data/grouped_data.py)."""
from __future__ import annotations

from typing import List, Optional, Tuple

from . import logical as L


class GroupedData:
    def __init__(self, dataset, key: Optional[str]):
        self._ds = dataset
        self._key = key

    def _agg(self, aggs: List[Tuple[str, str, str]]):
        return self._ds._append(L.Aggregate(self._key, aggs))

    def count(self):
        return self._agg([("count", "", "count()")])

    def sum(self, col: str):
        return self._agg([("sum", col, f"sum({col})")])

    def mean(self, col: str):
        return self._agg([("mean", col, f"mean({col})")])

    def min(self, col: str):
        return self._agg([("min", col, f"min({col})")])

    def max(self, col: str):
        return self._agg([("max", col, f"max({col})")])

    def std(self, col: str):
        return self._agg([("std", col, f"std({col})")])

    def aggregate(self, *aggs: Tuple[str, str]):
        """aggs: (kind, col) pairs, kind in {count,sum,mean,min,max,std}."""
        return self._agg([(k, c, f"{k}({c})") for k, c in aggs])

    def map_groups(self, fn, *, batch_format: str = "numpy"):
        """Run fn(batch)->batch per group (reference: map_groups). Implemented
        as sort-by-key then per-block group apply."""
        key = self._key
        sorted_ds = self._ds.sort(key).repartition(1)

        def apply_groups(batch):
            import numpy as np

            from .block import BlockAccessor, block_from_batch, concat_blocks

            v = batch[key]
            # contiguous runs after sort
            change = np.nonzero(np.concatenate([[True], v[1:] != v[:-1]]))[0]
            bounds = list(change) + [len(v)]
            outs = []
            for s, e in zip(bounds[:-1], bounds[1:]):
                sub = {k: val[s:e] for k, val in batch.items()}
                outs.append(block_from_batch(fn(sub)))
            merged = concat_blocks(outs) if outs else {}
            return BlockAccessor(merged).to_numpy()

        return sorted_ds.map_batches(apply_groups, batch_format="numpy")
