"""ray_tpu.data — streaming Dataset library (SURVEY.md §2.3, §7 step 6)."""
from .block import Block, BlockAccessor
from .context import DataContext
from .dataset import Dataset
from .grouped import GroupedData
from .read_api import (
    from_arrow,
    from_huggingface,
    from_blocks,
    from_items,
    from_numpy,
    from_numpy_refs,
    from_pandas,
    range,  # noqa: A004
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)

__all__ = [
    "Block",
    "BlockAccessor",
    "DataContext",
    "Dataset",
    "GroupedData",
    "from_arrow",
    "from_huggingface",
    "from_blocks",
    "from_items",
    "from_numpy",
    "from_numpy_refs",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
