"""Datasources: read tasks producing blocks.

Parity: reference python/ray/data/datasource/ + read_api.py (read_parquet
:605, read_csv, read_json, read_numpy, read_binary_files, from_items, range).
A Datasource yields ReadTask thunks; each runs remotely and returns one block
(reference: ReadTask → blocks in plasma; here → blocks in the host store).
"""
from __future__ import annotations

import glob as globlib
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .block import Block, rows_to_block


@dataclass
class ReadTask:
    """A zero-arg callable returning one block, plus size metadata."""

    fn: Callable[[], Block]
    num_rows: Optional[int] = None

    def __call__(self) -> Block:
        return self.fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self.n = n
        self.tensor_shape = tensor_shape

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        splits = np.array_split(np.arange(self.n, dtype=np.int64), parallelism)
        shape = self.tensor_shape

        def make(ids: np.ndarray) -> ReadTask:
            def read() -> Block:
                if shape is None:
                    return {"id": ids}
                data = np.broadcast_to(
                    ids.reshape((-1,) + (1,) * len(shape)), (len(ids),) + shape
                ).copy()
                return {"data": data}

            return ReadTask(read, num_rows=len(ids))

        return [make(s) for s in splits if len(s) or parallelism == 1]


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, len(self.items) or 1))
        chunks = np.array_split(np.arange(len(self.items)), parallelism)

        def make(idx: np.ndarray) -> ReadTask:
            part = [self.items[i] for i in idx]

            def read() -> Block:
                rows = [x if isinstance(x, dict) else {"item": x} for x in part]
                return rows_to_block(rows)

            return ReadTask(read, num_rows=len(part))

        return [make(c) for c in chunks if len(c) or parallelism == 1]


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(f for f in globlib.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileDatasource(Datasource):
    """One read task per file group."""

    suffix: Optional[str] = None

    def __init__(self, paths, **kwargs):
        self.paths = _expand_paths(paths, self.suffix)
        self.kwargs = kwargs

    def read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        groups = np.array_split(np.arange(len(self.paths)), max(1, min(parallelism, len(self.paths))))
        tasks = []
        for g in groups:
            if not len(g):
                continue
            files = [self.paths[i] for i in g]

            def read(files=files):
                # Generator: one block per file, so the streaming read task
                # reports each block as it is parsed and downstream stages
                # start before the whole group is read (reference: streaming
                # generator read tasks, data/_internal/planner/plan_read_op.py).
                for f in files:
                    yield self.read_file(f)

            tasks.append(ReadTask(read))
        return tasks


class ParquetDatasource(FileDatasource):
    suffix = ".parquet"

    def read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq

        return pq.read_table(path, **self.kwargs)


class CSVDatasource(FileDatasource):
    suffix = ".csv"

    def read_file(self, path: str) -> Block:
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path, **self.kwargs)


class JSONDatasource(FileDatasource):
    suffix = ".json"

    def read_file(self, path: str) -> Block:
        import pyarrow.json as pajson

        return pajson.read_json(path, **self.kwargs)


class NumpyDatasource(FileDatasource):
    suffix = ".npy"

    def read_file(self, path: str) -> Block:
        return {"data": np.load(path, **self.kwargs)}


class BinaryDatasource(FileDatasource):
    def read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        import pyarrow as pa

        return pa.Table.from_pydict({"bytes": [data], "path": [path]})


class TextDatasource(FileDatasource):
    """One row per line (reference read_api.py read_text): {"text": line},
    trailing newlines stripped, encoding errors replaced."""

    def __init__(self, paths, encoding: str = "utf-8",
                 drop_empty_lines: bool = True, **kwargs):
        super().__init__(paths, **kwargs)
        self.encoding = encoding
        self.drop_empty_lines = drop_empty_lines

    def read_file(self, path: str) -> Block:
        import pyarrow as pa

        with open(path, "rb") as f:
            text = f.read().decode(self.encoding, "replace")
        lines = text.splitlines()
        if self.drop_empty_lines:
            lines = [l for l in lines if l.strip()]
        return pa.Table.from_pydict({"text": lines})


class TFRecordDatasource(FileDatasource):
    """TFRecord shards of tf.train.Example protos -> columnar blocks
    (reference read_api.py read_tfrecords). The record framing
    (len/maskedcrc/payload/maskedcrc) and the Example wire format are
    parsed directly — no tensorflow dependency; CRCs are skipped like the
    reference's fast path."""

    suffix = ".tfrecord"

    def read_file(self, path: str) -> Block:
        import pyarrow as pa

        from .tfrecord_lite import parse_tfrecord_examples

        cols = parse_tfrecord_examples(path)
        return pa.Table.from_pydict(cols)


class ImageDatasource(FileDatasource):
    """Decode images into {"image": ndarray} blocks (reference
    python/ray/data/read_api.py:776 read_images). ``size=(h, w)`` resizes
    at decode time — with a fixed size rows stack into one dense
    [N, H, W, C] array (what the TPU batch-inference path wants); without
    one, rows are ragged and ship as an object-dtype column (the
    reference's variable-shaped tensor case). ``mode`` is a PIL
    conversion mode; single-channel modes keep a trailing channel axis so
    the [H, W, C] contract holds."""

    _EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp",
             ".tif", ".tiff")

    def __init__(self, paths, size=None, mode: str = "RGB", **kwargs):
        super().__init__(paths, **kwargs)
        # Directories commonly hold labels.csv/README next to the images —
        # only decode files with image extensions (reference read_images
        # filters the same way).
        explicit = [paths] if isinstance(paths, str) else list(paths)
        keep = []
        for p in self.paths:
            if p.lower().endswith(self._EXTS) or p in explicit:
                keep.append(p)
        if not keep:
            raise FileNotFoundError(f"no image files matched {paths}")
        self.paths = keep
        self.size = tuple(size) if size else None
        self.mode = mode

    def read_file(self, path: str) -> Block:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert(self.mode)
            if self.size is not None:
                # PIL takes (width, height); size is (height, width) to
                # match the ndarray [H, W, C] the caller sees.
                im = im.resize((self.size[1], self.size[0]),
                               Image.Resampling.BILINEAR)
            arr = np.asarray(im)
        if arr.ndim == 2:  # "L"/"1" modes: keep the channel axis
            arr = arr[..., None]
        if self.size is None:
            # Ragged images cannot stack densely; an object column keeps
            # concat/take working with per-row arrays.
            col = np.empty(1, dtype=object)
            col[0] = arr
        else:
            col = arr[None]
        return {"image": col, "path": np.array([path])}


class SQLDatasource(Datasource):
    """Rows from a DBAPI query (reference python/ray/data/read_api.py
    read_sql: runs `sql` through a zero-arg `connection_factory`).

    The factory — not a connection — is what ships to the read task, so it
    must be picklable (e.g. ``functools.partial(sqlite3.connect, path)``).
    One read task by default, like the reference; `shard_predicates`
    extends it: each predicate string becomes one task reading
    ``SELECT * FROM (sql) WHERE <predicate>`` — dialect-agnostic sharding
    the caller controls (the reference's shard_keys/MOD sharding is
    MySQL-specific)."""

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 shard_predicates: Optional[List[str]] = None):
        self.sql = sql
        self.factory = connection_factory
        self.shard_predicates = shard_predicates

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        queries = [self.sql]
        if self.shard_predicates:
            queries = [
                f"SELECT * FROM ({self.sql}) WHERE {pred}"  # noqa: S608
                for pred in self.shard_predicates
            ]
        factory = self.factory

        def make(q: str) -> ReadTask:
            def read() -> Block:
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(q)
                    names = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                finally:
                    conn.close()
                cols: Dict[str, Any] = {}
                for i, n in enumerate(names):
                    vals = [r[i] for r in rows]
                    if any(isinstance(v, bytes) for v in vals):
                        # np.asarray's fixed-width "S" dtype strips
                        # trailing NULs from blobs; object dtype is exact.
                        col = np.empty(len(vals), dtype=object)
                        for j, v in enumerate(vals):
                            col[j] = v
                        cols[n] = col
                    else:
                        cols[n] = np.asarray(vals)
                return cols

            return ReadTask(read)

        return [make(q) for q in queries]


class WebDatasetDatasource(FileDatasource):
    """Tar shards in WebDataset layout: members sharing a basename-up-to-
    the-first-dot form one sample; the remainder is the field name
    (reference python/ray/data/datasource/webdataset_datasource.py).

    Rows come out as {"__key__": key, "<ext>": value} with stdlib-only
    decoding by extension: txt/text -> str, json -> parsed, cls/index ->
    int, npy -> ndarray, everything else (incl. images) -> raw bytes.
    ``decode_images=True`` additionally decodes jpg/png/... members to
    [H, W, C] uint8 arrays via PIL."""

    suffix = ".tar"
    _IMG_EXTS = ("jpg", "jpeg", "png", "bmp", "webp", "ppm")

    def __init__(self, paths, decode_images: bool = False, **kwargs):
        super().__init__(paths, **kwargs)
        self.decode_images = decode_images

    def _decode(self, ext: str, data: bytes) -> Any:
        e = ext.lower()
        if e in ("txt", "text"):
            return data.decode("utf-8", "replace")
        if e == "json":
            import json

            return json.loads(data)
        if e in ("cls", "index"):
            return int(data.decode("ascii").strip())
        if e == "npy":
            import io

            return np.load(io.BytesIO(data), allow_pickle=False)
        if self.decode_images and e in self._IMG_EXTS:
            import io

            from PIL import Image

            with Image.open(io.BytesIO(data)) as im:
                return np.asarray(im.convert("RGB"))
        return data

    def read_file(self, path: str) -> Block:
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                if not m.isfile():
                    continue
                base = os.path.basename(m.name)
                if "." not in base:
                    key, ext = m.name, "bin"
                else:
                    stem, ext = base.split(".", 1)
                    key = os.path.join(os.path.dirname(m.name), stem)
                data = tf.extractfile(m).read()
                if key not in samples:
                    samples[key] = {"__key__": key}
                    order.append(key)
                # Compound extensions carry a codec suffix the writer added
                # ("meta.json", "x.npy"): decode by the last component and
                # strip it from the field name so write->read round-trips.
                field, last = ext, ext.rsplit(".", 1)[-1]
                if "." in ext and last in ("json", "npy"):
                    field = ext[: -(len(last) + 1)]
                samples[key][field] = self._decode(last, data)
        return rows_to_block([samples[k] for k in order])


# ------------------------------------------------------------------- writers


def write_block(block: Block, path: str, file_format: str, index: int, **kwargs) -> str:
    from .block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    fp = os.path.join(path, f"part-{index:05d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(BlockAccessor(block).to_arrow(), fp, **kwargs)
    elif file_format == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(BlockAccessor(block).to_arrow(), fp, **kwargs)
    elif file_format == "json":
        BlockAccessor(block).to_pandas().to_json(fp, orient="records", lines=True)
    elif file_format == "tfrecord":
        from .tfrecord_lite import write_tfrecord_examples

        cols = BlockAccessor(block).to_batch("numpy")
        write_tfrecord_examples(fp, {k: list(v) for k, v in cols.items()})
    elif file_format == "tar":  # WebDataset shard
        _write_wds_shard(block, fp)
    else:
        raise ValueError(f"unknown format {file_format}")
    return fp


def _write_wds_shard(block: Block, fp: str) -> None:
    """One tar shard in WebDataset layout (reference dataset
    write_webdataset): each row becomes members ``<key>.<field>``; bytes
    pass through, str -> utf-8, int -> ascii (cls convention), dict/list ->
    json, ndarray -> .npy bytes."""
    import io
    import json as jsonlib
    import tarfile

    from .block import BlockAccessor

    def encode(field: str, v: Any) -> tuple:
        if isinstance(v, np.generic):  # numpy scalars: json can't take them
            v = v.item()
        if isinstance(v, bytes):
            return field, v
        if isinstance(v, str):
            return field, v.encode("utf-8")
        if isinstance(v, (bool, int)):
            return field, str(int(v)).encode("ascii")
        if isinstance(v, np.ndarray):
            buf = io.BytesIO()
            np.save(buf, v, allow_pickle=False)
            name = field if field == "npy" or field.endswith(".npy") \
                else field + ".npy"
            return name, buf.getvalue()
        name = field if field == "json" or field.endswith(".json") \
            else field + ".json"
        return name, jsonlib.dumps(v).encode("utf-8")

    with tarfile.open(fp, "w") as tf:
        for i, row in enumerate(BlockAccessor(block).iter_rows()):
            key = row.get("__key__") or f"{i:08d}"
            for field, v in row.items():
                if field == "__key__" or v is None:
                    continue
                name, data = encode(field, v)
                info = tarfile.TarInfo(f"{key}.{name}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
