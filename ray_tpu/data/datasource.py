"""Datasources: read tasks producing blocks.

Parity: reference python/ray/data/datasource/ + read_api.py (read_parquet
:605, read_csv, read_json, read_numpy, read_binary_files, from_items, range).
A Datasource yields ReadTask thunks; each runs remotely and returns one block
(reference: ReadTask → blocks in plasma; here → blocks in the host store).
"""
from __future__ import annotations

import glob as globlib
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .block import Block, rows_to_block


@dataclass
class ReadTask:
    """A zero-arg callable returning one block, plus size metadata."""

    fn: Callable[[], Block]
    num_rows: Optional[int] = None

    def __call__(self) -> Block:
        return self.fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError


class RangeDatasource(Datasource):
    def __init__(self, n: int, tensor_shape: Optional[tuple] = None):
        self.n = n
        self.tensor_shape = tensor_shape

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        splits = np.array_split(np.arange(self.n, dtype=np.int64), parallelism)
        shape = self.tensor_shape

        def make(ids: np.ndarray) -> ReadTask:
            def read() -> Block:
                if shape is None:
                    return {"id": ids}
                data = np.broadcast_to(
                    ids.reshape((-1,) + (1,) * len(shape)), (len(ids),) + shape
                ).copy()
                return {"data": data}

            return ReadTask(read, num_rows=len(ids))

        return [make(s) for s in splits if len(s) or parallelism == 1]


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, len(self.items) or 1))
        chunks = np.array_split(np.arange(len(self.items)), parallelism)

        def make(idx: np.ndarray) -> ReadTask:
            part = [self.items[i] for i in idx]

            def read() -> Block:
                rows = [x if isinstance(x, dict) else {"item": x} for x in part]
                return rows_to_block(rows)

            return ReadTask(read, num_rows=len(part))

        return [make(c) for c in chunks if len(c) or parallelism == 1]


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(f for f in globlib.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileDatasource(Datasource):
    """One read task per file group."""

    suffix: Optional[str] = None

    def __init__(self, paths, **kwargs):
        self.paths = _expand_paths(paths, self.suffix)
        self.kwargs = kwargs

    def read_file(self, path: str) -> Block:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        groups = np.array_split(np.arange(len(self.paths)), max(1, min(parallelism, len(self.paths))))
        tasks = []
        for g in groups:
            if not len(g):
                continue
            files = [self.paths[i] for i in g]

            def read(files=files):
                # Generator: one block per file, so the streaming read task
                # reports each block as it is parsed and downstream stages
                # start before the whole group is read (reference: streaming
                # generator read tasks, data/_internal/planner/plan_read_op.py).
                for f in files:
                    yield self.read_file(f)

            tasks.append(ReadTask(read))
        return tasks


class ParquetDatasource(FileDatasource):
    suffix = ".parquet"

    def read_file(self, path: str) -> Block:
        import pyarrow.parquet as pq

        return pq.read_table(path, **self.kwargs)


class CSVDatasource(FileDatasource):
    suffix = ".csv"

    def read_file(self, path: str) -> Block:
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path, **self.kwargs)


class JSONDatasource(FileDatasource):
    suffix = ".json"

    def read_file(self, path: str) -> Block:
        import pyarrow.json as pajson

        return pajson.read_json(path, **self.kwargs)


class NumpyDatasource(FileDatasource):
    suffix = ".npy"

    def read_file(self, path: str) -> Block:
        return {"data": np.load(path, **self.kwargs)}


class BinaryDatasource(FileDatasource):
    def read_file(self, path: str) -> Block:
        with open(path, "rb") as f:
            data = f.read()
        import pyarrow as pa

        return pa.Table.from_pydict({"bytes": [data], "path": [path]})


class TextDatasource(FileDatasource):
    """One row per line (reference read_api.py read_text): {"text": line},
    trailing newlines stripped, encoding errors replaced."""

    def __init__(self, paths, encoding: str = "utf-8",
                 drop_empty_lines: bool = True, **kwargs):
        super().__init__(paths, **kwargs)
        self.encoding = encoding
        self.drop_empty_lines = drop_empty_lines

    def read_file(self, path: str) -> Block:
        import pyarrow as pa

        with open(path, "rb") as f:
            text = f.read().decode(self.encoding, "replace")
        lines = text.splitlines()
        if self.drop_empty_lines:
            lines = [l for l in lines if l.strip()]
        return pa.Table.from_pydict({"text": lines})


class TFRecordDatasource(FileDatasource):
    """TFRecord shards of tf.train.Example protos -> columnar blocks
    (reference read_api.py read_tfrecords). The record framing
    (len/maskedcrc/payload/maskedcrc) and the Example wire format are
    parsed directly — no tensorflow dependency; CRCs are skipped like the
    reference's fast path."""

    suffix = ".tfrecord"

    def read_file(self, path: str) -> Block:
        import pyarrow as pa

        from .tfrecord_lite import parse_tfrecord_examples

        cols = parse_tfrecord_examples(path)
        return pa.Table.from_pydict(cols)


class ImageDatasource(FileDatasource):
    """Decode images into {"image": ndarray} blocks (reference
    python/ray/data/read_api.py:776 read_images). ``size=(h, w)`` resizes
    at decode time — with a fixed size rows stack into one dense
    [N, H, W, C] array (what the TPU batch-inference path wants); without
    one, rows are ragged and ship as an object-dtype column (the
    reference's variable-shaped tensor case). ``mode`` is a PIL
    conversion mode; single-channel modes keep a trailing channel axis so
    the [H, W, C] contract holds."""

    _EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp",
             ".tif", ".tiff")

    def __init__(self, paths, size=None, mode: str = "RGB", **kwargs):
        super().__init__(paths, **kwargs)
        # Directories commonly hold labels.csv/README next to the images —
        # only decode files with image extensions (reference read_images
        # filters the same way).
        explicit = [paths] if isinstance(paths, str) else list(paths)
        keep = []
        for p in self.paths:
            if p.lower().endswith(self._EXTS) or p in explicit:
                keep.append(p)
        if not keep:
            raise FileNotFoundError(f"no image files matched {paths}")
        self.paths = keep
        self.size = tuple(size) if size else None
        self.mode = mode

    def read_file(self, path: str) -> Block:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert(self.mode)
            if self.size is not None:
                # PIL takes (width, height); size is (height, width) to
                # match the ndarray [H, W, C] the caller sees.
                im = im.resize((self.size[1], self.size[0]),
                               Image.Resampling.BILINEAR)
            arr = np.asarray(im)
        if arr.ndim == 2:  # "L"/"1" modes: keep the channel axis
            arr = arr[..., None]
        if self.size is None:
            # Ragged images cannot stack densely; an object column keeps
            # concat/take working with per-row arrays.
            col = np.empty(1, dtype=object)
            col[0] = arr
        else:
            col = arr[None]
        return {"image": col, "path": np.array([path])}


# ------------------------------------------------------------------- writers


def write_block(block: Block, path: str, file_format: str, index: int, **kwargs) -> str:
    from .block import BlockAccessor

    os.makedirs(path, exist_ok=True)
    fp = os.path.join(path, f"part-{index:05d}.{file_format}")
    table = BlockAccessor(block).to_arrow()
    if file_format == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(table, fp, **kwargs)
    elif file_format == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(table, fp, **kwargs)
    elif file_format == "json":
        BlockAccessor(block).to_pandas().to_json(fp, orient="records", lines=True)
    elif file_format == "tfrecord":
        from .tfrecord_lite import write_tfrecord_examples

        cols = BlockAccessor(block).to_batch("numpy")
        write_tfrecord_examples(fp, {k: list(v) for k, v in cols.items()})
    else:
        raise ValueError(f"unknown format {file_format}")
    return fp
