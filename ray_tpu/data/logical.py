"""Logical plan: operators + the fusion rule.

Parity: reference data/_internal/logical/ (logical operators, optimizers.py
rewrite rules — notably map fusion) and _internal/planner/. The plan is a
chain (Union/Zip reference sibling plans); the optimizer fuses adjacent
row/batch maps with compatible compute so one task does the whole pipeline
stage (the reference's OperatorFusionRule).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .datasource import Datasource


@dataclass
class LogicalOp:
    pass


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1


@dataclass
class InputData(LogicalOp):
    """Pre-materialized block refs (from_blocks / materialized datasets)."""

    refs: List[Any] = field(default_factory=list)


@dataclass
class MapBatches(LogicalOp):
    fn: Any  # callable, or class for actor-pool compute
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    fn_args: Tuple = ()
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)
    fn_constructor_args: Tuple = ()
    fn_constructor_kwargs: Dict[str, Any] = field(default_factory=dict)
    compute: Optional[Any] = None  # None=tasks; ActorPoolStrategy for actors
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    concurrency: Optional[Any] = None

    @property
    def is_actor_compute(self) -> bool:
        return isinstance(self.fn, type)


@dataclass
class MapRows(LogicalOp):
    fn: Callable[[Dict[str, Any]], Dict[str, Any]]


@dataclass
class FlatMap(LogicalOp):
    fn: Callable[[Dict[str, Any]], List[Dict[str, Any]]]


@dataclass
class Filter(LogicalOp):
    fn: Callable[[Dict[str, Any]], bool]


@dataclass
class Repartition(LogicalOp):
    num_blocks: int


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False


@dataclass
class Limit(LogicalOp):
    n: int


@dataclass
class Union(LogicalOp):
    others: List[List[LogicalOp]] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: List[LogicalOp] = field(default_factory=list)


@dataclass
class Aggregate(LogicalOp):
    key: Optional[str]
    aggs: List[Tuple[str, str, str]] = field(default_factory=list)  # (kind, col, out_name)


ROW_OPS = (MapRows, FlatMap, Filter)


# ---------------------------------------------------------------------------
# Rewrite-rule optimizer (reference: data/_internal/logical/optimizers.py —
# an ordered rule list applied to fixpoint before planning; map fusion is
# the planner-side half, fuse_plan below).


class Rule:
    """One rewrite: ops -> ops (pure; return the input to decline)."""

    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        raise NotImplementedError


class MergeLimits(Rule):
    """limit(a).limit(b) == limit(min(a, b))."""

    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        out: List[LogicalOp] = []
        for op in ops:
            if (isinstance(op, Limit) and out
                    and isinstance(out[-1], Limit)):
                out[-1] = Limit(n=min(out[-1].n, op.n))
            else:
                out.append(op)
        return out


class LimitPushdown(Rule):
    """Push Limit below row-count-preserving maps so upstream stages
    produce only what survives (reference LimitPushdownRule). MapRows is
    one-to-one; Filter/FlatMap/MapBatches may change the row count, so the
    limit must stay above them."""

    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        out = list(ops)
        changed = True
        while changed:
            changed = False
            for i in range(1, len(out)):
                if isinstance(out[i], Limit) and isinstance(out[i - 1],
                                                            MapRows):
                    out[i - 1], out[i] = out[i], out[i - 1]
                    changed = True
        return out


class DropRedundantShuffles(Rule):
    """A repartition/shuffle immediately followed by another whole-dataset
    redistribution does dead work: sort and shuffle re-distribute anyway,
    and of consecutive repartitions only the last layout survives."""

    _REDIST = (Repartition, RandomShuffle, Sort)

    def apply(self, ops: List[LogicalOp]) -> List[LogicalOp]:
        out: List[LogicalOp] = []
        for op in ops:
            if (out and isinstance(out[-1], (Repartition, RandomShuffle))
                    and isinstance(op, self._REDIST)
                    # A shuffle feeding a plain repartition still matters
                    # (the randomization is the point); everything else
                    # makes the PREVIOUS redistribution dead.
                    and not (isinstance(out[-1], RandomShuffle)
                             and isinstance(op, Repartition))):
                out[-1] = op
            else:
                out.append(op)
        return out


DEFAULT_RULES: List[Rule] = [MergeLimits(), LimitPushdown(),
                             DropRedundantShuffles(), MergeLimits()]


def optimize(ops: List[LogicalOp],
             rules: Optional[List[Rule]] = None) -> List[LogicalOp]:
    """Apply the rule list to fixpoint (bounded: each rule only ever
    shrinks or reorders, but cap passes defensively)."""
    for _ in range(8):
        before = list(ops)
        for rule in (rules if rules is not None else DEFAULT_RULES):
            ops = rule.apply(ops)
        if ops == before:
            break
    return ops


def is_fusable_map(op: LogicalOp) -> bool:
    if isinstance(op, ROW_OPS):
        return True
    return isinstance(op, MapBatches) and not op.is_actor_compute


def fuse_plan(ops: List[LogicalOp]) -> List[List[LogicalOp]]:
    """Group the chain into stages: runs of fusable maps become one stage
    (executed by a single task per block); everything else stands alone."""
    stages: List[List[LogicalOp]] = []
    run: List[LogicalOp] = []
    for op in ops:
        if is_fusable_map(op):
            run.append(op)
        else:
            if run:
                stages.append(run)
                run = []
            stages.append([op])
    if run:
        stages.append(run)
    return stages
