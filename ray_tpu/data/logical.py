"""Logical plan: operators + the fusion rule.

Parity: reference data/_internal/logical/ (logical operators, optimizers.py
rewrite rules — notably map fusion) and _internal/planner/. The plan is a
chain (Union/Zip reference sibling plans); the optimizer fuses adjacent
row/batch maps with compatible compute so one task does the whole pipeline
stage (the reference's OperatorFusionRule).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .datasource import Datasource


@dataclass
class LogicalOp:
    pass


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1


@dataclass
class InputData(LogicalOp):
    """Pre-materialized block refs (from_blocks / materialized datasets)."""

    refs: List[Any] = field(default_factory=list)


@dataclass
class MapBatches(LogicalOp):
    fn: Any  # callable, or class for actor-pool compute
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    fn_args: Tuple = ()
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)
    fn_constructor_args: Tuple = ()
    fn_constructor_kwargs: Dict[str, Any] = field(default_factory=dict)
    compute: Optional[Any] = None  # None=tasks; ActorPoolStrategy for actors
    num_cpus: Optional[float] = None
    num_tpus: Optional[float] = None
    concurrency: Optional[Any] = None

    @property
    def is_actor_compute(self) -> bool:
        return isinstance(self.fn, type)


@dataclass
class MapRows(LogicalOp):
    fn: Callable[[Dict[str, Any]], Dict[str, Any]]


@dataclass
class FlatMap(LogicalOp):
    fn: Callable[[Dict[str, Any]], List[Dict[str, Any]]]


@dataclass
class Filter(LogicalOp):
    fn: Callable[[Dict[str, Any]], bool]


@dataclass
class Repartition(LogicalOp):
    num_blocks: int


@dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False


@dataclass
class Limit(LogicalOp):
    n: int


@dataclass
class Union(LogicalOp):
    others: List[List[LogicalOp]] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    other: List[LogicalOp] = field(default_factory=list)


@dataclass
class Aggregate(LogicalOp):
    key: Optional[str]
    aggs: List[Tuple[str, str, str]] = field(default_factory=list)  # (kind, col, out_name)


ROW_OPS = (MapRows, FlatMap, Filter)


def is_fusable_map(op: LogicalOp) -> bool:
    if isinstance(op, ROW_OPS):
        return True
    return isinstance(op, MapBatches) and not op.is_actor_compute


def fuse_plan(ops: List[LogicalOp]) -> List[List[LogicalOp]]:
    """Group the chain into stages: runs of fusable maps become one stage
    (executed by a single task per block); everything else stands alone."""
    stages: List[List[LogicalOp]] = []
    run: List[LogicalOp] = []
    for op in ops:
        if is_fusable_map(op):
            run.append(op)
        else:
            if run:
                stages.append(run)
                run = []
            stages.append([op])
    if run:
        stages.append(run)
    return stages
