"""Dataset creation API (reference: python/ray/data/read_api.py —
read_parquet :605, range, from_items, from_pandas, from_numpy, ...)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from . import logical as L
from .context import DataContext
from .dataset import Dataset
from .datasource import (
    BinaryDatasource,
    ImageDatasource,
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
)


def _mk(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset([L.Read(ds, parallelism)])


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return _mk(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return _mk(RangeDatasource(n, tensor_shape=tuple(shape)), parallelism)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return _mk(ItemsDatasource(items), parallelism)


def from_numpy(arr: np.ndarray, *, column: str = "data") -> Dataset:
    import ray_tpu as rt

    ref = rt.put({column: np.asarray(arr)})
    return Dataset([L.InputData(refs=[ref])])


def from_numpy_refs(refs: List[Any]) -> Dataset:
    return Dataset([L.InputData(refs=list(refs))])


def from_blocks(blocks: List[Any]) -> Dataset:
    import ray_tpu as rt

    return Dataset([L.InputData(refs=[rt.put(b) for b in blocks])])


def from_pandas(dfs) -> Dataset:
    import pandas as pd

    import ray_tpu as rt

    if isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    import pyarrow as pa

    refs = [rt.put(pa.Table.from_pandas(df, preserve_index=False)) for df in dfs]
    return Dataset([L.InputData(refs=refs)])


def from_arrow(tables) -> Dataset:
    import pyarrow as pa

    import ray_tpu as rt

    if isinstance(tables, pa.Table):
        tables = [tables]
    return Dataset([L.InputData(refs=[rt.put(t) for t in tables])])


def from_huggingface(hf_dataset, *, blocks_per_shard: int = 4) -> Dataset:
    """Hugging Face ``datasets.Dataset``/``DatasetDict`` -> Dataset
    (reference: python/ray/data/read_api.py:2664 from_huggingface).

    The HF dataset's arrow backing is sliced into blocks zero-copy (no
    row-wise materialization); a ``DatasetDict`` must be indexed to a
    split first, matching the reference's error. ``IterableDataset``
    streams through from_items semantics (materialized — the reference
    converts it to a streamed read; at this scale one pass is fine)."""
    try:
        import datasets as hf
    except ImportError as e:  # pragma: no cover - baked into this image
        raise ImportError(
            "from_huggingface requires the `datasets` package") from e

    if isinstance(hf_dataset, hf.DatasetDict):
        raise ValueError(
            "from_huggingface expects a single split: index the "
            f"DatasetDict first (splits: {list(hf_dataset.keys())})")
    if isinstance(hf_dataset, hf.IterableDataset):
        return from_items([dict(row) for row in hf_dataset])
    table = hf_dataset.data.table if hasattr(hf_dataset.data, "table") \
        else hf_dataset.data
    import builtins

    n = table.num_rows
    shards = max(1, min(blocks_per_shard, n))
    step = (n + shards - 1) // shards
    # builtins.range: this module's range() is the dataset constructor.
    tables = [table.slice(i, min(step, n - i))
              for i in builtins.range(0, n, step)]
    return from_arrow([t.combine_chunks() for t in tables])


def read_parquet(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _mk(ParquetDatasource(paths, **kwargs), parallelism)


def read_csv(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _mk(CSVDatasource(paths, **kwargs), parallelism)


def read_json(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _mk(JSONDatasource(paths, **kwargs), parallelism)


def read_numpy(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    return _mk(NumpyDatasource(paths, **kwargs), parallelism)


def read_text(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """One row per line: {"text": line} (reference read_api read_text)."""
    from .datasource import TextDatasource

    return _mk(TextDatasource(paths, **kwargs), parallelism)


def read_tfrecords(paths, *, parallelism: int = -1, **kwargs) -> Dataset:
    """tf.train.Example TFRecord shards -> columnar rows (reference
    read_api read_tfrecords; dependency-free proto parsing in
    data/tfrecord_lite.py)."""
    from .datasource import TFRecordDatasource

    return _mk(TFRecordDatasource(paths, **kwargs), parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return _mk(BinaryDatasource(paths), parallelism)


def read_sql(sql: str, connection_factory, *, shard_predicates=None,
             parallelism: int = -1) -> Dataset:
    """Rows from a DBAPI query (reference read_api.py read_sql). The
    zero-arg `connection_factory` must be picklable — it runs inside the
    read task. `shard_predicates=["id % 2 = 0", "id % 2 = 1"]` splits the
    read into one task per predicate."""
    from .datasource import SQLDatasource

    return _mk(SQLDatasource(sql, connection_factory,
                             shard_predicates=shard_predicates), parallelism)


def read_webdataset(paths, *, decode_images: bool = False,
                    parallelism: int = -1, **kwargs) -> Dataset:
    """WebDataset tar shards -> {"__key__", "<field>": value} rows
    (reference datasource/webdataset_datasource.py; stdlib tarfile)."""
    from .datasource import WebDatasetDatasource

    return _mk(WebDatasetDatasource(paths, decode_images=decode_images,
                                    **kwargs), parallelism)


def read_images(paths, *, size=None, mode: str = "RGB",
                parallelism: int = -1) -> Dataset:
    """Decode image files into {"image": [H,W,C] uint8, "path"} rows
    (reference python/ray/data/read_api.py:776). ``size=(h, w)`` resizes
    at decode time so the inference batches are uniform."""
    return _mk(ImageDatasource(paths, size=size, mode=mode), parallelism)


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    return _mk(datasource, parallelism)
