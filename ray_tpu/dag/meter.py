"""Out-of-band sampler for the compiled-DAG channel meter (RTPU_DAG_METER).

The channel fabric's hot path (dag/channels.py, dag/resident.py) never
touches a metrics instrument: ring writers/readers bump raw u64 counter
lines inside the SlotRing segment (core/object_store.py) and resident
stage loops accumulate plain-int phase ns on their own mailbox thread.
This module is the cold half: every process hosting channel state
registers its WorkerDAG / driver channel sources here, and a sampler
hooked onto the worker's existing metrics-flush heartbeat
(util/metrics.register_flush_sampler) folds the raw counters into TSDB
families at flush cadence:

- ``rtpu_dag_edge_items_total`` / ``rtpu_dag_edge_bytes_total`` —
  cumulative traffic per edge (counter deltas, epoch-aware);
- ``rtpu_dag_edge_occupancy`` / ``rtpu_dag_edge_lag_seqs`` — in-flight
  depth and worst reader lag, derived from the live cursors at sample
  time (zero hot-path cost);
- ``rtpu_dag_edge_blocked_fraction`` — share of wall time the writer
  spent waiting for ring space (consumer backpressure);
- ``rtpu_dag_stage_busy_fraction{phase=recv|compute|send}`` +
  ``rtpu_dag_stage_steps_total`` — the stage phase accounting.

**Epoch consistency.** A DAG recovery (PR 11) rebuilds affected rings
under a bumped epoch with zeroed counter blocks, and replay writes skip
the counters (`record=False`). The sampler keys its per-edge baseline on
the ring epoch: an epoch bump re-baselines at zero, so rates never go
negative and replayed items are never double-counted.

``attribute_bottleneck`` is the one attribution rule everything renders:
the bottleneck is the stage whose compute+send saturation bounds
steady-state throughput. Starved (recv) time is excluded — a starved
stage is the VICTIM of an upstream bottleneck — and writer-blocked time
is excluded from send — a blocked writer is the victim of a downstream
one. The rule is tested (tests/test_dag_meter.py), not eyeballed.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu.util import metrics as um

_EDGE_ITEMS = um.Counter(
    "rtpu_dag_edge_items_total",
    description="Items published into a compiled-DAG channel edge "
                "(sampled from the shm ring counter block; stream edges "
                "count frames landed at the consumer)",
    tag_keys=("dag", "edge"))
_EDGE_BYTES = um.Counter(
    "rtpu_dag_edge_bytes_total",
    description="Payload bytes published into a compiled-DAG channel "
                "edge (pre-sidecar size for oversize spills)",
    tag_keys=("dag", "edge"))
_EDGE_OCC = um.Gauge(
    "rtpu_dag_edge_occupancy",
    description="In-flight items in a compiled-DAG edge ring "
                "(write_seq - slowest reader cursor; depth bounds it)",
    tag_keys=("dag", "edge"))
_EDGE_LAG = um.Gauge(
    "rtpu_dag_edge_lag_seqs",
    description="Worst consumer lag on a compiled-DAG edge in seqnos "
                "(writer high-water minus the reader's cursor)",
    tag_keys=("dag", "edge"))
_EDGE_BLOCKED = um.Gauge(
    "rtpu_dag_edge_blocked_fraction",
    description="Fraction of wall time the edge's writer spent blocked "
                "on ring space since the last sample (consumer "
                "backpressure; drives the dag_edge_stalled alert)",
    tag_keys=("dag", "edge"))
_STAGE_BUSY = um.Gauge(
    "rtpu_dag_stage_busy_fraction",
    description="Fraction of wall time a resident DAG stage spent in "
                "each phase since the last sample (recv=starved on "
                "inputs, compute=user method, send=publishing minus "
                "backpressure); drives bottleneck attribution and the "
                "dag_stage_starved alert",
    tag_keys=("dag", "stage", "phase"))
_STAGE_STEPS = um.Counter(
    "rtpu_dag_stage_steps_total",
    description="Microbatches a resident DAG stage finished (per-second "
                "rate is the stage's steady-state throughput)",
    tag_keys=("dag", "stage"))

# Registered channel sources: objects exposing ``dag_id`` plus any of
# ``rings`` (eid -> SlotRing), ``stage_ns`` (idx -> phase accumulators),
# ``stream_stats`` (eid -> frame counters). WorkerDAG satisfies all
# three; the driver registers a thin adapter over the rings it creates.
_sources: List[Any] = []
_edge_base: Dict[Any, Dict[str, Any]] = {}
_stage_base: Dict[Any, Dict[str, Any]] = {}
_hooked = False


def register_source(src: Any) -> None:
    global _hooked
    if src not in _sources:
        _sources.append(src)
    if not _hooked:
        _hooked = True
        um.register_flush_sampler(sample_now)


def unregister_source(src: Any) -> None:
    try:
        _sources.remove(src)
    except ValueError:
        pass


def sample_now() -> None:
    """Fold every registered source's raw counters into the instruments.
    Runs on the metrics flusher thread each heartbeat; also callable
    directly from tests for a deterministic sample."""
    now = time.monotonic()
    for src in list(_sources):
        try:
            _sample_source(src, now)
        except Exception:
            pass


def _sample_source(src: Any, now: float) -> None:
    dag = str(src.dag_id)[:12]
    rings = dict(getattr(src, "rings", None) or {})
    for eid, ring in rings.items():
        try:
            c = ring.counters()
        except Exception:
            continue  # ring closed mid-sample
        key = (dag, eid)
        base = _edge_base.get(key)
        if base is None or base["epoch"] != c["epoch"]:
            # Fresh ring incarnation: its counter block starts at zero,
            # so the baseline does too — no negative deltas, and items
            # the old epoch already reported stay reported exactly once.
            base = {"epoch": c["epoch"], "items": 0, "bytes": 0,
                    "blocked_ns": 0, "t": None}
        tags = {"dag": dag, "edge": eid}
        _EDGE_ITEMS.inc(max(0, c["items"] - base["items"]), tags)
        _EDGE_BYTES.inc(max(0, c["bytes"] - base["bytes"]), tags)
        _EDGE_OCC.set(float(c["occupancy"]), tags)
        _EDGE_LAG.set(float(max((r["lag"] for r in c["readers"]),
                                default=0)), tags)
        if base["t"] is not None and now > base["t"]:
            wall_ns = (now - base["t"]) * 1e9
            d_blocked = max(0, c["blocked_ns"] - base["blocked_ns"])
            _EDGE_BLOCKED.set(min(1.0, d_blocked / wall_ns), tags)
        _edge_base[key] = {"epoch": c["epoch"], "items": c["items"],
                           "bytes": c["bytes"],
                           "blocked_ns": c["blocked_ns"], "t": now}
    for eid, st in list((getattr(src, "stream_stats", None) or {}).items()):
        if eid in rings:
            continue  # ring-counted
        key = ("stream", dag, eid)
        base = _edge_base.get(key) or {"items": 0, "bytes": 0}
        tags = {"dag": dag, "edge": eid}
        _EDGE_ITEMS.inc(max(0, st["items"] - base["items"]), tags)
        _EDGE_BYTES.inc(max(0, st["bytes"] - base["bytes"]), tags)
        _EDGE_LAG.set(float(max(0, st.get("wi", 0) - st["items"])), tags)
        _edge_base[key] = {"items": st["items"], "bytes": st["bytes"]}
    for idx, stc in list((getattr(src, "stage_ns", None) or {}).items()):
        snap = dict(stc)
        key = (dag, idx)
        base = _stage_base.get(key)
        stage = f"s{idx}"
        _STAGE_STEPS.inc(
            max(0, snap["steps"] - (base["steps"] if base else 0)),
            {"dag": dag, "stage": stage})
        if base is not None and now > base["t"]:
            wall_ns = (now - base["t"]) * 1e9
            for phase in ("recv", "compute", "send"):
                frac = max(0, snap[phase] - base[phase]) / wall_ns
                _STAGE_BUSY.set(min(1.0, frac),
                                {"dag": dag, "stage": stage,
                                 "phase": phase})
        snap["t"] = now
        _stage_base[key] = snap


def attribute_bottleneck(
        busy: Dict[str, Dict[str, float]]) -> Optional[str]:
    """THE attribution rule: given ``{stage: {phase: busy_fraction}}``,
    name the stage whose compute-or-send saturation bounds steady-state
    throughput. recv (starved) time marks a victim, not a culprit, and
    never scores; ties break toward the earliest stage so the verdict is
    deterministic."""
    best: Optional[str] = None
    best_score = -1.0
    for stage in sorted(busy):
        phases = busy[stage]
        score = (float(phases.get("compute", 0.0))
                 + float(phases.get("send", 0.0)))
        if score > best_score + 1e-12:
            best, best_score = stage, score
    return best


def spans_snapshot(runtime, dag: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
    """Recent per-stage step spans from every DAG this worker hosts, in
    the wire shape ``state.dag_timeline()`` consumes."""
    out: List[Dict[str, Any]] = []
    for dag_id, wd in list((getattr(runtime, "dag_channels", None)
                            or {}).items()):
        if dag and not dag_id.startswith(dag):
            continue
        methods = {st["idx"]: st.get("method", "")
                   for st in wd.plan.get("stages", ())}
        for (idx, seq, end_s, recv, comp, send, blocked) in list(wd.spans):
            out.append({"dag": dag_id[:12], "stage": f"s{idx}",
                        "method": methods.get(idx, ""), "seq": int(seq),
                        "end_s": float(end_s), "recv_ns": int(recv),
                        "compute_ns": int(comp), "send_ns": int(send),
                        "blocked_ns": int(blocked)})
    return out
