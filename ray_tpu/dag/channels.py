"""Mutable channels for compiled DAGs: shm slot rings + raw-tail streams.

Parity: reference python/ray/experimental/channel/ (shared_memory_channel.py
backed by MutableObjectManager in CoreWorker). A compiled DAG allocates one
reusable channel per edge at compile() time; every execute() thereafter is
a header write + one wake, with zero per-call control plane.

Two transports, chosen per edge by locality:

- **Same-host edges** ride a `core.object_store.SlotRing`: a depth-bounded
  ring of fixed-size shm slots with a seqno+len header per slot. The
  producer publishes by bumping the slot seq; consumers copy out and
  advance their read cursor. Values larger than a slot ship via a one-off
  sidecar shm segment named inside the slot (the reference spills oversize
  mutable objects the same way). Wakeups are *doorbells*: tiny unix
  datagram sockets derived from the ring name — a peer rings only when the
  waiter has advertised it is blocking (waiting flags in the ring header),
  so the steady-state fast path is a pure shm poll with no syscalls.
- **Cross-host edges** ride a persistent raw-tail stream (PR 7's
  `encode_raw_prefix` framing): worker→worker legs hold a dedicated
  blocking TCP connection (`transfer.RawStreamSender`) to the consumer's
  direct server; driver↔worker legs reuse the per-DAG install connection
  (`Connection.send_with_raw_threadsafe`), so the driver needs no extra
  listening socket. Receivers land items in a `StreamInbox`.

Both readers expose the same ``recv(timeout) -> (seq, kind, payload)``
surface, so the resident DAG loop (dag/resident.py) is transport-blind.
"""
from __future__ import annotations

import os
import pickle
import socket
import tempfile
import threading
import time
from collections import deque
from multiprocessing import shared_memory
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu import flags
from ray_tpu.core import object_store
from ray_tpu.util import metrics as um

# Value kinds carried in the slot/frame header (SlotRing's `kind` field /
# the dag_channel_item "vk" field).
KIND_DATA = 0      # payload = pickle of the stage result / input value
KIND_ERROR = 1     # payload = pickle of the exception (flows downstream)
KIND_SIDECAR = 2   # payload = pickle of (inner_kind, shm_name, nbytes)

_BYTES = um.Counter(
    "rtpu_dag_channel_bytes_total",
    description="Bytes moved through compiled-DAG channels, by edge "
                "transport (shm slot rings vs persistent raw-tail streams)",
    tag_keys=("edge_kind",),
)


class DAGTeardownError(RuntimeError):
    """The compiled DAG was torn down while this result was outstanding.

    Raised by ``CompiledDAGRef.get()`` for every in-flight ``execute()``
    when a participant dies (worker SIGKILL, node loss, actor restart) or
    the DAG is explicitly torn down. Carries the first underlying cause in
    ``args`` / ``__cause__`` when one is known.
    """


class ChannelClosed(Exception):
    """Internal control-flow signal: the channel's DAG stopped (teardown,
    peer death, or writer drain). Resident loops exit on it; the driver
    translates it into DAGTeardownError for user-visible refs."""


# --------------------------------------------------------------------------
# doorbells


def _bell_dir() -> str:
    return tempfile.gettempdir()


def writer_bell_path(ring_name: str) -> str:
    return os.path.join(_bell_dir(), ring_name + "_w")


def reader_bell_path(ring_name: str, idx: int) -> str:
    return os.path.join(_bell_dir(), f"{ring_name}_r{idx}")


_ring_sock: Optional[socket.socket] = None
_ring_sock_lock = threading.Lock()


def ring_bell(path: str) -> None:
    """Fire-and-forget one-byte wake. Datagram sends are atomic, so one
    shared unbound socket serves every thread in the process; a missing or
    full peer socket is ignored — waits are timeout-bounded precisely so a
    lost wake costs latency, never correctness."""
    global _ring_sock
    s = _ring_sock
    if s is None:
        with _ring_sock_lock:
            s = _ring_sock
            if s is None:
                s = _ring_sock = socket.socket(socket.AF_UNIX,
                                               socket.SOCK_DGRAM)
    try:
        s.sendto(b"\0", path)
    except OSError:
        pass


class Doorbell:
    """The waiting side of a wakeup pair: a bound unix datagram socket.

    The waiter advertises intent via the ring header's waiting flags, then
    blocks in ``wait()``; peers ``ring_bell()`` the deterministic path
    derived from the ring name. Stale paths from a crashed previous run
    are unlinked on bind."""

    def __init__(self, path: str):
        self.path = path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        try:
            os.unlink(path)
        except OSError:
            pass
        self._sock.bind(path)

    def wait(self, timeout: float) -> bool:
        self._sock.settimeout(timeout if timeout > 0 else 0.001)
        try:
            self._sock.recv(16)
        except (socket.timeout, OSError):
            return False
        # Drain queued rings so a burst of publishes costs one wake.
        self._sock.settimeout(0.0)
        try:
            while True:
                self._sock.recv(16)
        except (BlockingIOError, socket.timeout, OSError):
            pass
        return True

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


def _spin_until(cond: Callable[[], bool], spin_us: int) -> bool:
    """Busy-poll ``cond`` for up to ``spin_us`` microseconds. Zero (the
    right setting for 1-core hosts) skips straight to the doorbell."""
    if spin_us <= 0:
        return cond()
    deadline = time.monotonic_ns() + spin_us * 1_000
    while True:
        if cond():
            return True
        if time.monotonic_ns() >= deadline:
            return False


# --------------------------------------------------------------------------
# value encoding


def encode_value(value: Any) -> bytes:
    return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)


def encode_error(exc: BaseException) -> bytes:
    try:
        return pickle.dumps(exc, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        return pickle.dumps(
            RuntimeError(f"{type(exc).__name__}: {exc!r}"))


def decode(payload: bytes) -> Any:
    return pickle.loads(payload)


def apply_selector(value: Any, key: Any) -> Any:
    """InputAttributeNode semantics, applied consumer-side: the full input
    value travels the channel once; each binding selects into it locally
    (same contract as InputAttributeNode._execute_impl)."""
    if isinstance(key, int) and isinstance(value, (list, tuple)):
        return value[key]
    if isinstance(value, dict):
        return value[key]
    return getattr(value, key)


# --------------------------------------------------------------------------
# shm transport


class ShmEdgeWriter:
    """Producer side of a same-host edge: owns the SlotRing segment.

    Single writer (the producing stage's resident loop, or the driver's
    execute thread under its lock). Oversize values spill to a per-seq
    sidecar segment reaped when the slot is provably recycled — space for
    seq implies every reader advanced past seq-depth, so that sidecar can
    be unlinked before the new write."""

    def __init__(self, ring: object_store.SlotRing):
        self.ring = ring
        self._bell = Doorbell(writer_bell_path(ring.name))
        self._spin_us = int(flags.get("RTPU_DAG_SPIN_US"))
        self._meter = bool(flags.get("RTPU_DAG_METER"))
        self._sidecars: Dict[int, str] = {}
        self._closed = False

    def write(self, seq: int, kind: int, payload: bytes,
              stop: Optional[Callable[[], bool]] = None,
              record: bool = True) -> int:
        """Publish one item. Returns the ns spent blocked on ring space
        (0 on the fast path / unmetered). ``record=False`` is the recovery
        replay path: re-delivered items must not re-count."""
        ring = self.ring
        nbytes = len(payload)
        if nbytes > ring.slot_size:
            kind, payload = self._spill(seq, kind, payload)
        blocked = 0
        if not ring.has_space(seq):
            blocked = self._wait_space(seq, stop)
        old = self._sidecars.pop(seq - ring.depth, None)
        if old is not None:
            _unlink_segment(old)
        ring.write(seq, kind, payload)
        if record and self._meter:
            ring.ctr_write(1, nbytes)
        _BYTES.inc(len(payload), {"edge_kind": "shm"})
        for i in range(ring.n_readers):
            if ring.reader_waiting(i):
                # Clear the flag ourselves: the queued datagram already
                # guarantees the reader wakes, so later writes in this
                # burst skip the (expensive) redundant sendto. The reader
                # re-arms the flag every blocking cycle, so no lost wake.
                ring.set_reader_waiting(i, False)
                ring_bell(reader_bell_path(ring.name, i))
        return blocked

    def _wait_space(self, seq: int, stop) -> int:
        """Wait for ring space; returns the ns spent (0 when unmetered).
        Wait time here is backpressure from slow consumers — it accrues
        into the ring's *blocked* counter line, never into the producing
        stage's send cost, so attribution blames the consumer."""
        ring = self.ring
        t0 = time.monotonic_ns() if self._meter else 0
        blocked = 0
        try:
            if not _spin_until(lambda: ring.has_space(seq), self._spin_us):
                while True:
                    if stop is not None and stop():
                        raise ChannelClosed(f"edge ring {ring.name} stopped")
                    ring.set_writer_waiting(True)
                    try:
                        if ring.has_space(seq):
                            break
                        self._bell.wait(0.05)
                    finally:
                        ring.set_writer_waiting(False)
        finally:
            if self._meter:
                blocked = time.monotonic_ns() - t0
                try:
                    ring.ctr_blocked(blocked)
                except Exception:
                    pass
        return blocked

    def _spill(self, seq: int, kind: int, payload: bytes
               ) -> Tuple[int, bytes]:
        name = f"{self.ring.name}s{seq}"
        seg = shared_memory.SharedMemory(name=name, create=True,
                                         size=len(payload))
        object_store._untrack(name)
        seg.buf[: len(payload)] = payload
        object_store.track_channel_segment(name, len(payload))
        seg.close()
        self._sidecars[seq] = name
        return KIND_SIDECAR, pickle.dumps((kind, name, len(payload)))

    def close(self) -> None:
        """Mark the ring drained and release everything this writer owns.
        Readers observe ``closed`` once the ring is empty and raise
        ChannelClosed; sidecars and the ring segment unlink here (creator
        owns the name)."""
        if self._closed:
            return
        self._closed = True
        try:
            self.ring.mark_closed()
        except Exception:
            pass
        for i in range(self.ring.n_readers):
            if self.ring.reader_waiting(i):
                ring_bell(reader_bell_path(self.ring.name, i))
        for name in self._sidecars.values():
            _unlink_segment(name)
        self._sidecars.clear()
        self._bell.close()
        self.ring.unlink()


def _unlink_segment(name: str) -> None:
    object_store.untrack_channel_segment(name)
    try:
        import _posixshmem

        _posixshmem.shm_unlink("/" + name)
    except Exception:
        pass


class ShmEdgeReader:
    """One consumer cursor on a same-host edge's SlotRing."""

    def __init__(self, ring_name: str, idx: int,
                 attach_timeout: float = 10.0,
                 expect_epoch: Optional[int] = None):
        self.idx = idx
        self.ring = _attach_retry(ring_name, attach_timeout,
                                  expect_epoch=expect_epoch)
        self._bell = Doorbell(reader_bell_path(ring_name, idx))
        self._spin_us = int(flags.get("RTPU_DAG_SPIN_US"))
        self._meter = bool(flags.get("RTPU_DAG_METER"))

    def recv(self, timeout: float,
             stop: Optional[Callable[[], bool]] = None
             ) -> Optional[Tuple[int, int, bytes]]:
        ring, idx = self.ring, self.idx
        if not ring.readable(idx):
            # Wait time (spin + doorbell) accrues into this reader's
            # *starved* counter line: nothing to consume means upstream is
            # the slow side of this edge.
            t0 = time.monotonic_ns() if self._meter else 0
            try:
                if not _spin_until(lambda: ring.readable(idx),
                                   self._spin_us):
                    deadline = time.monotonic() + timeout
                    while True:
                        if stop is not None and stop():
                            raise ChannelClosed(
                                f"edge ring {ring.name} stopped")
                        ring.set_reader_waiting(idx, True)
                        try:
                            if ring.readable(idx):
                                break
                            if ring.closed():
                                raise ChannelClosed(
                                    f"edge ring {ring.name} closed by writer")
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                return None
                            self._bell.wait(min(0.05, remaining))
                        finally:
                            ring.set_reader_waiting(idx, False)
            finally:
                if self._meter:
                    try:
                        ring.ctr_starved(idx, time.monotonic_ns() - t0)
                    except Exception:
                        pass
        seq, kind, payload = ring.read(idx)
        if kind == KIND_SIDECAR:
            kind, payload = _read_sidecar(payload)
        ring.advance(idx)
        if self._meter:
            ring.ctr_read(idx, 1, len(payload))
        if ring.writer_waiting():
            # Same elision as the writer side: one queued bell wakes the
            # writer, which re-arms its flag before blocking again.
            ring.set_writer_waiting(False)
            ring_bell(writer_bell_path(ring.name))
        return seq, kind, payload

    def close(self) -> None:
        self._bell.close()
        self.ring.close()


def _attach_retry(name: str, timeout: float,
                  expect_epoch: Optional[int] = None
                  ) -> object_store.SlotRing:
    """Attach to a peer-created ring. The producer creates it during
    dag_install (or a recovery rebuild); install order across workers is
    unordered, so consumers tolerate a startup window. ``expect_epoch``
    rejects a stale incarnation of the ring: a rebuilt reader must never
    have its cursor satisfied by the previous epoch's segment."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            ring = object_store.SlotRing.attach(name)
            # The creator zero-fills then writes the header; an attach
            # landing inside that window sees depth=0 — not ready yet.
            if ring.depth > 0 and ring.n_readers > 0 and (
                    expect_epoch is None or ring.epoch() == expect_epoch):
                return ring
            ring.close()
        except FileNotFoundError:
            pass
        except ValueError:
            # Attach landed between the creator's shm_open and ftruncate:
            # the segment exists but is still zero-sized ("cannot mmap an
            # empty file"). Same not-ready window as depth==0.
            pass
        if time.monotonic() >= deadline:
            raise ChannelClosed(
                f"edge ring {name} never appeared (producer install "
                f"failed or tore down)")
        time.sleep(0.005)


def _read_sidecar(marker: bytes) -> Tuple[int, bytes]:
    kind, name, n = pickle.loads(marker)
    seg = shared_memory.SharedMemory(name=name)
    object_store._untrack(name)  # writer owns the unlink
    try:
        return kind, bytes(seg.buf[:n])
    finally:
        seg.close()


# --------------------------------------------------------------------------
# stream transport (receiver side; senders live in transfer/protocol)


class StreamInbox:
    """Landing queue for one (edge, endpoint) fed by raw-tail frames.

    The direct server / install-conn handler pushes from the io loop; the
    resident loop (or driver pump) blocks in ``recv``. Capacity is bounded
    by the driver's in-flight window, so no backpressure of its own."""

    def __init__(self) -> None:
        self._dq: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def push(self, seq: int, kind: int, payload: bytes) -> None:
        with self._cond:
            self._dq.append((seq, kind, payload))
            self._cond.notify_all()

    def recv(self, timeout: float,
             stop: Optional[Callable[[], bool]] = None
             ) -> Optional[Tuple[int, int, bytes]]:
        deadline = time.monotonic() + timeout
        with self._cond:
            while not self._dq:
                if self._closed or (stop is not None and stop()):
                    raise ChannelClosed("stream inbox closed")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(min(0.05, remaining))
            return self._dq.popleft()

    def poke(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()


class EdgeWriter:
    """Fan-out writer for one DAG edge: at most one shm ring (all same-host
    consumers share it) plus one stream send per cross-host consumer.

    Streams go first — they never block — then the ring write, which may
    wait on the in-flight window.

    ``retain`` keeps the last N (seq, kind, payload) items in a deque
    (appended BEFORE any transport touches them) so DAG recovery can
    replay everything a rebuilt/restarted consumer has not yet applied.
    ``epoch`` rides every stream frame so a consumer that survived a
    rebuild can drop frames from a superseded incarnation of the edge."""

    def __init__(self, dag_id: str, edge_id: str,
                 ring_writer: Optional[ShmEdgeWriter] = None,
                 stream_targets: Optional[
                     List[Tuple[Callable[[Dict[str, Any], bytes], None],
                                str]]] = None,
                 retain: int = 0, epoch: int = 0):
        self.dag_id = dag_id
        self.edge_id = edge_id
        self.ring_writer = ring_writer
        self.stream_targets = list(stream_targets or ())
        self.retained: Optional[deque] = (
            deque(maxlen=retain) if retain > 0 else None)
        self.epoch = epoch
        self.aborted = False  # recovery retired this writer mid-write
        self._meter = bool(flags.get("RTPU_DAG_METER"))
        # Cross-host edges have no shm counter block to sample, so the
        # writer's cumulative (items, bytes) piggyback on every frame and
        # the consumer's worker samples the high-water mark it last saw.
        self.stream_items = 0
        self.stream_bytes = 0

    def write(self, seq: int, kind: int, payload: bytes,
              stop: Optional[Callable[[], bool]] = None) -> int:
        """Returns ns spent blocked on the ring's in-flight window (0 on
        the fast path / unmetered) so the resident loop can subtract
        backpressure from its send-phase accounting."""
        if self.retained is not None:
            # An aborted-then-retried write (quiesce interrupted the ring
            # leg) must not append the same seq twice.
            if not (self.retained and self.retained[-1][0] == seq):
                self.retained.append((seq, kind, payload))
        if self._meter and self.stream_targets:
            self.stream_items += 1
            self.stream_bytes += len(payload)
        for send, endpoint in self.stream_targets:
            try:
                send({"kind": "dag_channel_item", "dag": self.dag_id,
                      "edge": self.edge_id, "to": endpoint, "seq": seq,
                      "vk": kind, "epoch": self.epoch,
                      "wi": self.stream_items, "wb": self.stream_bytes},
                     payload)
            except Exception:
                if self.retained is None:
                    raise  # fail-fast semantics (RTPU_DAG_RECOVERY=0)
                # Dead peer mid-pipeline: the item is retained, recovery
                # replays it once the edge is rebuilt.
                continue
            _BYTES.inc(len(payload), {"edge_kind": "stream"})
        if self.ring_writer is not None:
            return self.ring_writer.write(seq, kind, payload, stop)
        return 0

    def replay(self, needs: Dict[str, int], ring_base: Optional[int],
               stop: Optional[Callable[[], bool]] = None) -> None:
        """Recovery re-delivery: push every retained item each consumer
        still needs. Stream targets filter per-endpoint on ``needs``; the
        rebuilt ring (created with write_seq == ring_base) takes every
        retained item from ring_base up, in order."""
        for seq, kind, payload in list(self.retained or ()):
            for send, endpoint in self.stream_targets:
                if seq >= needs.get(endpoint, seq + 1):
                    try:
                        send({"kind": "dag_channel_item",
                              "dag": self.dag_id, "edge": self.edge_id,
                              "to": endpoint, "seq": seq, "vk": kind,
                              "epoch": self.epoch}, payload)
                    except Exception:
                        continue  # double failure; the stall probe re-runs
                    _BYTES.inc(len(payload), {"edge_kind": "stream"})
            if (self.ring_writer is not None and ring_base is not None
                    and seq >= ring_base):
                # record=False: the rebuilt ring's counter block starts at
                # zero and the sampler re-baselines on the epoch bump, so
                # counting replayed items would double-bill every item the
                # old incarnation already reported.
                self.ring_writer.write(seq, kind, payload, stop,
                                       record=False)

    def close(self) -> None:
        if self.ring_writer is not None:
            self.ring_writer.close()
