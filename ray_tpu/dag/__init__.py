"""Lazy DAG authoring + compiled actor pipelines.

Parity: reference python/ray/dag/ (dag_node.py, function_node.py,
class_node.py, input_node.py, output_node.py, compiled_dag_node.py).
"""
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.dag.channels import DAGTeardownError
from ray_tpu.dag.compiled_dag import (
    ChannelDAGRef,
    CompiledDAG,
    CompiledDAGRef,
    compile_dag,
)

__all__ = [
    "DAGNode",
    "FunctionNode",
    "ClassNode",
    "ClassMethodNode",
    "InputNode",
    "InputAttributeNode",
    "MultiOutputNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "ChannelDAGRef",
    "DAGTeardownError",
    "compile_dag",
]
