"""DAG authoring: lazy task/actor call graphs built with ``.bind()``.

Parity: reference python/ray/dag/dag_node.py + function_node.py /
class_node.py / input_node.py / output_node.py. The authoring surface is
the same shape — ``fn.bind(x)`` returns a node instead of submitting, and
``node.execute(input)`` walks the graph and submits everything — but the
body is independent: nodes are plain Python objects resolved against the
ray_tpu task/actor API, with one shared-subgraph memo per execution so a
diamond dependency runs its common parent once.

Consumers: ``ray_tpu.workflow`` (durable execution, checkpoint per node)
and ``ray_tpu.dag.compiled_dag`` (persistent actor pipelines).
"""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_ANON = itertools.count()


class DAGNode:
    """One lazy call in an authored graph.

    Subclasses define what submitting the call means via ``_execute_impl``.
    ``execute`` resolves upstream nodes first (memoized in ``memo``) and
    passes their *ObjectRefs* downstream — data flows worker→worker through
    the object plane, the driver never materializes intermediates.
    """

    def __init__(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal ---------------------------------------------------------
    def _upstream(self) -> List["DAGNode"]:
        found: List[DAGNode] = []

        def scan(v):
            if isinstance(v, DAGNode):
                found.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for a in self._bound_kwargs.values():
            scan(a)
        return found

    def topological(self) -> List["DAGNode"]:
        """All nodes reachable from (and including) self, deps first."""
        order: List[DAGNode] = []
        seen: set = set()

        def visit(n: DAGNode):
            if id(n) in seen:
                return
            seen.add(id(n))
            for up in n._upstream():
                visit(up)
            order.append(n)

        visit(self)
        return order

    def _resolve_value(self, v: Any, memo: Dict[int, Any]) -> Any:
        if isinstance(v, DAGNode):
            return v._execute_memo(memo)
        if isinstance(v, list):
            return [self._resolve_value(x, memo) for x in v]
        if isinstance(v, tuple):
            return tuple(self._resolve_value(x, memo) for x in v)
        if isinstance(v, dict):
            return {k: self._resolve_value(x, memo) for k, x in v.items()}
        return v

    def _resolved_args(self, memo: Dict[int, Any]) -> Tuple[tuple, dict]:
        args = tuple(self._resolve_value(a, memo) for a in self._bound_args)
        kwargs = {
            k: self._resolve_value(v, memo)
            for k, v in self._bound_kwargs.items()
        }
        return args, kwargs

    # -- execution ---------------------------------------------------------
    def _execute_memo(self, memo: Dict[int, Any]) -> Any:
        if id(self) not in memo:
            memo[id(self)] = self._execute_impl(memo)
        return memo[id(self)]

    def execute(self, *input_args, **input_kwargs) -> Any:
        """Submit the whole graph; returns the ref(s) of this output node."""
        memo: Dict[int, Any] = {"__input__": (input_args, input_kwargs)}
        return self._execute_memo(memo)

    def _execute_impl(self, memo: Dict[int, Any]) -> Any:
        raise NotImplementedError

    # -- naming (stable ids for workflow checkpoints) ----------------------
    def _name_hint(self) -> str:
        return f"node_{next(_ANON)}"


class FunctionNode(DAGNode):
    """``remote_fn.bind(*args)`` — a task submission deferred."""

    def __init__(self, remote_fn, args, kwargs, options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._options = dict(options or {})

    def options(self, **opts) -> "FunctionNode":
        merged = dict(self._options)
        merged.update(opts)
        return FunctionNode(self._remote_fn, self._bound_args,
                            self._bound_kwargs, merged)

    def _execute_impl(self, memo):
        args, kwargs = self._resolved_args(memo)
        fn = self._remote_fn
        if self._options:
            fn = fn.options(**self._options)
        return fn.remote(*args, **kwargs)

    def _name_hint(self) -> str:
        fn = getattr(self._remote_fn, "_fn", None)
        return getattr(fn, "__name__", "task")


class ClassNode(DAGNode):
    """``ActorClass.bind(*args)`` — deferred actor construction.

    Within one ``execute`` (or one workflow run) the actor is created once
    and shared by all method nodes hanging off it.
    """

    def __init__(self, actor_cls, args, kwargs, options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._options = dict(options or {})

    def options(self, **opts) -> "ClassNode":
        merged = dict(self._options)
        merged.update(opts)
        return ClassNode(self._actor_cls, self._bound_args,
                         self._bound_kwargs, merged)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _BoundMethod(self, name)

    def _execute_impl(self, memo):
        args, kwargs = self._resolved_args(memo)
        cls = self._actor_cls
        if self._options:
            cls = cls.options(**self._options)
        return cls.remote(*args, **kwargs)

    def _name_hint(self) -> str:
        cls = getattr(self._actor_cls, "_cls", None)
        return getattr(cls, "__name__", "actor")


class _BoundMethod:
    def __init__(self, owner: ClassNode, method: str):
        self._owner = owner
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._owner, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    """``class_node.method.bind(*args)`` — deferred actor method call."""

    def __init__(self, owner, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._owner = owner  # ClassNode or ActorHandle
        self._method = method

    @property
    def owner(self):
        """The ClassNode (deferred actor) or live ActorHandle this method
        dispatches on — the channel compiler keys stages by it."""
        return self._owner

    @property
    def method_name(self) -> str:
        return self._method

    def _upstream(self) -> List[DAGNode]:
        ups = super()._upstream()
        if isinstance(self._owner, DAGNode):
            ups.append(self._owner)
        return ups

    def _execute_impl(self, memo):
        owner = self._owner
        handle = owner._execute_memo(memo) if isinstance(owner, DAGNode) \
            else owner
        args, kwargs = self._resolved_args(memo)
        return getattr(handle, self._method).remote(*args, **kwargs)

    def _name_hint(self) -> str:
        return self._method


class InputNode(DAGNode):
    """Placeholder for the value passed to ``execute()`` / each workflow run.

    Usable as a context manager for authoring-scope clarity, matching the
    reference's ``with InputNode() as inp:`` idiom (input_node.py).
    ``inp[k]`` / ``inp.attr`` select into a dict/positional input.
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name)

    def _execute_impl(self, memo):
        args, kwargs = memo.get("__input__", ((), {}))
        if kwargs and args:
            # Silently returning only args would make inp['key'] selectors
            # read wrong data; mirror the reference's DAGInputData contract
            # by refusing the ambiguous mix outright.
            raise TypeError(
                "DAG execute() got both positional and keyword inputs; "
                "pass one or the other (use a dict input for named access)")
        if kwargs and not args:
            return kwargs
        if len(args) == 1 and not kwargs:
            return args[0]
        return args

    def _name_hint(self) -> str:
        return "input"


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        super().__init__((), {})
        self._parent = parent
        self._key = key

    @property
    def key(self):
        """The selector applied to the execute() input. Channel mode ships
        the full input once per seq and applies this consumer-side."""
        return self._key

    def _upstream(self) -> List[DAGNode]:
        return [self._parent]

    def _execute_impl(self, memo):
        val = self._parent._execute_memo(memo)
        if isinstance(self._key, int) and isinstance(val, (list, tuple)):
            return val[self._key]
        if isinstance(val, dict):
            return val[self._key]
        return getattr(val, self._key)

    def _name_hint(self) -> str:
        return f"input.{self._key}"


class MultiOutputNode(DAGNode):
    """Bundle several leaves so ``execute`` returns a list of refs."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__((tuple(outputs),), {})
        self._outputs = list(outputs)

    @property
    def outputs(self) -> List[DAGNode]:
        return list(self._outputs)

    def _execute_impl(self, memo):
        return [o._execute_memo(memo) for o in self._outputs]

    def _name_hint(self) -> str:
        return "multi_output"
