"""Compiled DAGs: persistent actor pipelines with channel-based dispatch.

Parity: reference python/ray/dag/compiled_dag_node.py (CompiledDAG,
ExecutableTask) + experimental/channel/shared_memory_channel.py. The
reference compiles an actor-method DAG into reusable mutable-plasma
channels so repeated executions skip per-call RPC setup; GPU-GPU hops ride
NCCL P2P. The TPU-native translation has two halves:

- **Host half (this file + dag/channels.py + dag/resident.py):**
  ``compile()`` turns the graph into a static *channel plan* — one
  reusable mutable channel per DAG edge (shm slot ring for same-host
  consumers, persistent raw-tail stream for cross-host ones, depth =
  ``max_in_flight``) — and installs a resident loop on each participating
  actor's mailbox thread. Steady-state ``execute()`` is one slot write +
  one doorbell: the controller sees compile and teardown only. A dead
  participant tears the whole DAG down with ``DAGTeardownError`` on every
  outstanding ref rather than hanging.
- **Device half:** chip-to-chip movement inside a stage is XLA's job
  (collectives over ICI scheduled by the compiler — see
  ray_tpu/parallel/pipeline.py for the in-graph microbatch pipeline). A
  CompiledDAG stitches *processes*; XLA stitches *chips*. The reference
  needs NCCL channels because torch ops don't compose across processes;
  jitted steps already internalize their collectives.

``RTPU_DAG_CHANNELS=0`` (or a graph shape channels can't express — bare
task nodes, no InputNode, nested-container bindings) falls back to the
original submit path: every ``execute()`` re-submits the stage chain
through normal actor calls, with ``max_in_flight`` bounding pipeline depth
via ``api.wait`` on the oldest outstanding ref. The submit path is the
baseline the dispatch benchmarks compare against.
"""
from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import flags
from ray_tpu.core import api
from ray_tpu.core import context as ctx
from ray_tpu.dag import channels
from ray_tpu.dag.channels import DAGTeardownError
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.util import metrics as um

_m_compiled = um.Gauge(
    "rtpu_dag_compiled",
    description="Compiled DAGs currently live in this process with a "
                "channel execution plan installed on workers")
_m_execute = um.Histogram(
    "rtpu_dag_execute_seconds",
    description="Compiled-DAG end-to-end step latency: input channel "
                "write to final result available at the driver",
    boundaries=(0.0001, 0.001, 0.01, 0.1, 1.0, 10.0))
_m_recoveries = um.Counter(
    "rtpu_dag_recoveries_total",
    description="Compiled-DAG in-place recoveries completed (stage "
                "restarted, affected channels rebuilt, retained items "
                "replayed), by detected cause",
    tag_keys=("cause",))
_m_recovery_s = um.Histogram(
    "rtpu_dag_recovery_seconds",
    description="Compiled-DAG recovery latency: participant death "
                "detected to pipeline resumed with channels rebuilt and "
                "retained items replayed",
    boundaries=(0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0))

_live_lock = threading.Lock()
_live_count = 0


class _DriverMeterSource:
    """Adapter registering the driver's channel state with the channel
    meter (dag/meter.py): the input edge's ring is created driver-side,
    so its counter block is sampled here, on the driver's own metrics
    flush heartbeat. ``rings`` re-reads the live writer every sample, so
    a recovery's ring swap (bumped epoch, zeroed counters) is picked up
    without re-registration."""

    def __init__(self, dag: "CompiledDAG"):
        self._dag = dag
        self.dag_id = dag.dag_id

    @property
    def rings(self):
        iw = self._dag._input_writer
        rw = iw.ring_writer if iw is not None else None
        return {"in": rw.ring} if rw is not None else {}


def _live_delta(d: int) -> None:
    global _live_count
    with _live_lock:
        _live_count = max(0, _live_count + d)
        _m_compiled.set(_live_count)


class _ChannelUnsupported(Exception):
    """This graph shape can't compile to channels; use the submit path."""


class CompiledDAGRef:
    """Future for one compiled execution (reference CompiledDAGRef).

    Submit-path flavor: wraps the ObjectRef(s) of the final stage."""

    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None):
        return api.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class ChannelDAGRef:
    """Future for one channel-mode execution: a (dag, seq) pair. The value
    never has an ObjectRef — it lives in the terminal channel until the
    driver pump stores it."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq

    @property
    def seq(self) -> int:
        return self._seq

    def get(self, timeout: Optional[float] = None):
        return self._dag._get_result(self._seq, timeout)


class CompiledDAG:
    def __init__(self, output_node: DAGNode, *, max_in_flight: int = 16):
        self._output = output_node
        self._nodes = output_node.topological()
        self._max_in_flight = max(1, int(max_in_flight))
        self._inflight: deque = deque()
        self._torn_down = False
        self._teardown_done = threading.Event()
        self._cond = threading.Condition()
        # Validate the whole graph BEFORE creating anything: a rejected
        # graph must not leak half-instantiated actors.
        for n in self._nodes:
            if not isinstance(
                n,
                (ClassNode, ClassMethodNode, FunctionNode, InputNode,
                 InputAttributeNode, MultiOutputNode),
            ):
                raise TypeError(
                    f"cannot compile node type {type(n).__name__}"
                )
            if isinstance(n, ClassNode):
                for up in n.topological():
                    if isinstance(up, (InputNode, InputAttributeNode)):
                        raise TypeError(
                            "compiled DAG: actor constructor args cannot "
                            "reference InputNode — actors are built once at "
                            "compile time, not per execution"
                        )
        # Instantiate every ClassNode once; these handles persist across
        # executions (the defining difference from DAGNode.execute()).
        self._actor_handles: Dict[int, Any] = {}
        boot_memo: Dict[int, Any] = {}
        for n in self._nodes:
            if isinstance(n, ClassNode):
                self._actor_handles[id(n)] = n._execute_memo(boot_memo)
        self._mode = "submit"
        self.dag_id = uuid.uuid4().hex
        if flags.get("RTPU_DAG_CHANNELS"):
            try:
                self._compile_channels()
                self._mode = "channels"
            except _ChannelUnsupported:
                pass  # submit fallback stays fully functional

    # ===================================================== channel compile

    def _compile_channels(self) -> None:
        """Build the channel plan and install it. Raises
        _ChannelUnsupported for graph shapes the plan can't express —
        anything else is a real compile error and propagates."""
        plan = self._analyze()
        wc = ctx.get_worker_context()
        self._wc = wc
        self._plan = plan
        self._place_edges(plan)
        self._conns: Dict[str, Any] = {}
        self._inboxes: Dict[tuple, channels.StreamInbox] = {}
        self._terminal_readers: Dict[str, Any] = {}
        self._input_writer: Optional[channels.EdgeWriter] = None
        self._results: Dict[int, Dict[str, Tuple[int, bytes]]] = {}
        self._finished: set = set()
        self._exec_ts: Dict[int, float] = {}
        self._next_seq = 0
        self._done_contig = 0
        self._error: Optional[BaseException] = None
        self._xlock = threading.Lock()
        self._pump_stop = threading.Event()
        self._recovering = False
        self._recovery_count = 0
        self._terminal_next: Dict[str, int] = {}  # edge -> next unseen seq
        self._meter_src: Optional[_DriverMeterSource] = None
        try:
            self._connect_workers(plan)
            self._install(plan)
            self._open_driver_channels(plan)
        except Exception:
            self._teardown_channels(kill_actors=False)
            raise
        if flags.get("RTPU_DAG_METER"):
            from ray_tpu.dag import meter as dag_meter

            self._meter_src = _DriverMeterSource(self)
            dag_meter.register_source(self._meter_src)
        try:
            wc.client.request(
                {"kind": "dag_compiled", "dag_id": self.dag_id,
                 "stages": [{"idx": s["idx"], "actor_id": s["actor_id"],
                             "method": s["method"]}
                            for s in plan["stages"]],
                 "edges": {eid: ("shm" if e.get("ring") and not e["streams"]
                                 else "stream" if not e.get("ring")
                                 else "mixed")
                           for eid, e in plan["edges"].items()},
                 "depth": plan["depth"]}, timeout=5)
        except Exception:
            pass  # bookkeeping only; the data plane doesn't need it
        self._pump_thread = threading.Thread(
            target=self._pump, name=f"dag-pump-{self.dag_id[:8]}",
            daemon=True)
        self._pump_thread.start()
        _live_delta(+1)

    # -- graph analysis ----------------------------------------------------

    def _analyze(self) -> Dict[str, Any]:
        nodes = self._nodes
        input_node: Optional[InputNode] = None
        stages: List[Dict[str, Any]] = []
        stage_of: Dict[int, int] = {}  # id(ClassMethodNode) -> stage idx
        for n in nodes:
            if isinstance(n, FunctionNode):
                raise _ChannelUnsupported("bare task nodes")
            if isinstance(n, InputNode):
                input_node = n
            if isinstance(n, ClassMethodNode):
                stage_of[id(n)] = len(stages)
                stages.append({"node": n})
        if input_node is None or not stages:
            raise _ChannelUnsupported("no InputNode / no actor stages")
        out = self._output
        if isinstance(out, MultiOutputNode):
            for o in out._outputs:
                if not isinstance(o, ClassMethodNode):
                    raise _ChannelUnsupported("non-stage terminal output")
            terminal_stages = [stage_of[id(o)] for o in out._outputs]
        elif isinstance(out, ClassMethodNode):
            terminal_stages = [stage_of[id(out)]]
        else:
            raise _ChannelUnsupported("output must be an actor stage")

        def classify(v) -> tuple:
            if isinstance(v, InputNode):
                return ("input", None)
            if isinstance(v, InputAttributeNode):
                return ("input", v._key)
            if isinstance(v, ClassMethodNode):
                return ("stage", stage_of[id(v)])
            if isinstance(v, DAGNode):
                raise _ChannelUnsupported(f"binding {type(v).__name__}")
            if isinstance(v, (list, tuple, dict)):
                probe = [v]
                while probe:
                    x = probe.pop()
                    if isinstance(x, DAGNode):
                        raise _ChannelUnsupported(
                            "DAG node nested inside a container arg")
                    if isinstance(x, (list, tuple)):
                        probe.extend(x)
                    elif isinstance(x, dict):
                        probe.extend(x.values())
            return ("const", v)

        for idx, st in enumerate(stages):
            n: ClassMethodNode = st["node"]
            owner = n._owner
            if isinstance(owner, ClassNode):
                handle = self._actor_handles[id(owner)]
            elif isinstance(owner, DAGNode):
                raise _ChannelUnsupported("unsupported method owner")
            else:
                handle = owner  # pre-existing ActorHandle
            args = [classify(a) for a in n._bound_args]
            kwargs = {k: classify(v) for k, v in n._bound_kwargs.items()}
            if not any(b[0] != "const" for b in
                       list(args) + list(kwargs.values())):
                # A stage with only constant bindings would free-run ahead
                # of the per-seq lockstep the channel loop executes in.
                raise _ChannelUnsupported("stage with no data dependency")
            st.update({"idx": idx, "actor_id": handle._actor_id,
                       "handle": handle, "method": n._method,
                       "raw_args": args, "raw_kwargs": kwargs})
        return {
            "dag_id": self.dag_id,
            "depth": self._max_in_flight,
            "slot_bytes": int(flags.get("RTPU_DAG_SLOT_BYTES")),
            "stages": stages,
            "terminal_stages": terminal_stages,
        }

    def _place_edges(self, plan: Dict[str, Any]) -> None:
        """Resolve every stage actor to its worker, then assign each edge
        its transport per consumer: same-node consumers share one slot
        ring on the producer's host; cross-node consumers each get a
        persistent raw-tail stream."""
        wc = self._wc
        endpoints: Dict[str, Dict[str, Any]] = {
            "driver": {"node_id": wc.node_id}}
        for st in plan["stages"]:
            d = wc.client.request(
                {"kind": "resolve_actor", "actor_id": st["actor_id"]},
                timeout=10)
            if d.get("state") != "alive" or not d.get("direct"):
                raise RuntimeError(
                    f"compiled DAG: actor {st['actor_id'][:8]} is not "
                    f"alive / directly reachable (state={d.get('state')})")
            info = dict(d["direct"])
            info["actor_id"] = st["actor_id"]
            endpoints[f"s{st['idx']}"] = info
        plan["endpoints"] = endpoints

        # Edge discovery: one edge per producer ("in" for the driver's
        # input, "e<idx>" per stage), with stage-level consumers — a
        # diamond is ONE ring with two reader cursors, not two copies.
        edges: Dict[str, Dict[str, Any]] = {}

        def consume(eid: str, producer: str, consumer_ep: str) -> None:
            e = edges.setdefault(eid, {"producer": producer,
                                       "consumers": []})
            if consumer_ep not in e["consumers"]:
                e["consumers"].append(consumer_ep)

        for st in plan["stages"]:
            ep = f"s{st['idx']}"

            def bind(b):
                if b[0] == "const":
                    return ("const", b[1])
                if b[0] == "input":
                    consume("in", "driver", ep)
                    return ("chan", "in", b[1])
                prod = plan["stages"][b[1]]
                if prod["actor_id"] == st["actor_id"]:
                    # Same actor: the value never leaves the resident
                    # loop's memory; no channel, no serialization.
                    return ("local", b[1])
                consume(f"e{b[1]}", f"s{b[1]}", ep)
                return ("chan", f"e{b[1]}", None)

            st["args"] = [bind(b) for b in st["raw_args"]]
            st["kwargs"] = {k: bind(b) for k, b in st["raw_kwargs"].items()}
        self._output_edges: List[str] = []
        for tidx in plan["terminal_stages"]:
            consume(f"e{tidx}", f"s{tidx}", "driver")
            self._output_edges.append(f"e{tidx}")
        for st in plan["stages"]:
            eid = f"e{st['idx']}"
            st["out_edge"] = eid if eid in edges else None

        from ray_tpu.core.object_store import SlotRing

        for eid, e in edges.items():
            prod_node = endpoints[e["producer"]]["node_id"]
            ring_eps = [c for c in e["consumers"]
                        if endpoints[c]["node_id"] == prod_node]
            stream_eps = [c for c in e["consumers"]
                          if endpoints[c]["node_id"] != prod_node]
            if len(ring_eps) > SlotRing.MAX_READERS:
                raise _ChannelUnsupported(
                    f"edge {eid}: {len(ring_eps)} same-host consumers "
                    f"exceeds the slot-ring reader table")
            e["streams"] = stream_eps
            e["ring"] = ({"name": f"rtpu_ch_{self.dag_id[:12]}{eid}",
                          "n_readers": len(ring_eps)}
                         if ring_eps else None)
            e["ring_idx"] = {c: i for i, c in enumerate(ring_eps)}
            e["epoch"] = 0
            e.pop("consumers")
        plan["edges"] = edges

    # -- wiring ------------------------------------------------------------

    def _connect_workers(self, plan: Dict[str, Any]) -> None:
        """One dedicated long-lived connection per participating worker:
        dag_install/dag_teardown/dag_status ride it, and so do cross-host
        driver↔worker channel legs (raw-tail frames), so the driver needs
        no extra listening socket."""
        from ray_tpu.core import protocol

        workers: Dict[str, Dict[str, Any]] = {}
        for ep, info in plan["endpoints"].items():
            if ep == "driver":
                continue
            w = workers.setdefault(
                info["worker_id"],
                {"host": info["host"], "port": info["port"]})
            w.setdefault("endpoints", []).append(ep)
        plan["workers"] = workers
        for wid, w in workers.items():
            self._conns[wid] = self._wc.client.io.call(
                protocol.connect(w["host"], w["port"],
                                 handler=self._on_conn_msg,
                                 name=f"dag-{self.dag_id[:8]}"),
                timeout=10)

    async def _on_conn_msg(self, conn, msg):
        if msg.get("kind") != "dag_channel_item":
            return None
        edge = self._plan["edges"].get(msg["edge"])
        if edge is not None and int(msg.get("epoch", 0)) != int(
                edge.get("epoch", 0)):
            return None  # frame from a superseded incarnation of the edge
        inbox = self._inboxes.get((msg["edge"], msg["to"]))
        if inbox is not None:
            inbox.push(msg["seq"], msg["vk"], bytes(msg["data"]))
        return None

    @staticmethod
    def _wire_plan(plan: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "dag_id": plan["dag_id"], "depth": plan["depth"],
            "slot_bytes": plan["slot_bytes"],
            "stages": [{"idx": s["idx"], "actor_id": s["actor_id"],
                        "method": s["method"], "args": s["args"],
                        "kwargs": s["kwargs"], "out_edge": s["out_edge"]}
                       for s in plan["stages"]],
            "edges": plan["edges"],
            "endpoints": plan["endpoints"],
        }

    def _install(self, plan: Dict[str, Any]) -> None:
        wire = self._wire_plan(plan)
        futs = [(wid, conn.request_threadsafe(
            {"kind": "dag_install", "plan": wire}))
            for wid, conn in self._conns.items()]
        for wid, f in futs:
            f.result(15)

    def _retain_depth(self) -> int:
        # +2 covers the cursor positions a paused consumer can report
        # beyond its last applied seq (one consumed-not-applied, one
        # mid-advance), so replay always finds what a reader still needs.
        return (self._max_in_flight + 2
                if flags.get("RTPU_DAG_RECOVERY") else 0)

    def _open_driver_channels(self, plan: Dict[str, Any]) -> None:
        # Input edge: the driver is the producer.
        in_edge = plan["edges"].get("in")
        if in_edge is not None:
            from ray_tpu.core.object_store import SlotRing

            ring_writer = None
            if in_edge["ring"]:
                cfg = in_edge["ring"]
                ring_writer = channels.ShmEdgeWriter(SlotRing.create(
                    plan["depth"], plan["slot_bytes"], cfg["n_readers"],
                    name=cfg["name"],
                    epoch=int(in_edge.get("epoch", 0)),
                    base=int(cfg.get("base", 0)),
                    reader_starts=cfg.get("starts")))
            targets = []
            for dst in in_edge["streams"]:
                conn = self._conns[plan["endpoints"][dst]["worker_id"]]
                targets.append((conn.send_with_raw_threadsafe, dst))
            self._input_writer = channels.EdgeWriter(
                self.dag_id, "in", ring_writer, targets,
                retain=self._retain_depth(),
                epoch=int(in_edge.get("epoch", 0)))
        # Terminal edges: the driver is a consumer.
        for eid in set(self._output_edges):
            e = plan["edges"][eid]
            if "driver" in e["streams"]:
                inbox = channels.StreamInbox()
                self._inboxes[(eid, "driver")] = inbox
                self._terminal_readers[eid] = inbox
            else:
                self._terminal_readers[eid] = channels.ShmEdgeReader(
                    e["ring"]["name"], e["ring_idx"]["driver"],
                    expect_epoch=int(e.get("epoch", 0)))

    # -- driver pump -------------------------------------------------------

    def _pump(self) -> None:
        """Eagerly drains terminal channels into the result map (so unread
        results never clog the window), watches for stalls, and probes
        participant liveness when one appears. Readers are re-read every
        sweep: a recovery may swap an affected terminal edge's reader for
        a fresh one mid-flight."""
        slice_s = 0.05 if len(self._terminal_readers) == 1 else 0.002
        want = len(self._terminal_readers)
        last_progress = time.monotonic()
        stall_s = float(flags.get("RTPU_DAG_STALL_S"))
        while not self._pump_stop.is_set():
            progressed = False
            for eid, r in list(self._terminal_readers.items()):
                try:
                    item = r.recv(slice_s, stop=self._pump_stop.is_set)
                except channels.ChannelClosed:
                    if not self._pump_stop.is_set():
                        self._fail(DAGTeardownError(
                            f"compiled DAG {self.dag_id[:8]}: terminal "
                            f"channel {eid} closed by its producer"))
                    return
                if item is None:
                    continue
                progressed = True
                seq, kind, payload = item
                if seq >= self._terminal_next.get(eid, 0):
                    self._terminal_next[eid] = seq + 1
                t0 = None
                with self._cond:
                    entry = self._results.setdefault(seq, {})
                    entry[eid] = (kind, payload)
                    if len(entry) == want:
                        self._finished.add(seq)
                        while self._done_contig in self._finished:
                            self._done_contig += 1
                        t0 = self._exec_ts.pop(seq, None)
                        self._cond.notify_all()
                if t0 is not None:
                    _m_execute.observe(time.perf_counter() - t0)
            if progressed:
                last_progress = time.monotonic()
                continue
            with self._cond:
                outstanding = self._next_seq - self._done_contig
            if outstanding == 0:
                last_progress = time.monotonic()
            elif time.monotonic() - last_progress > stall_s:
                if not self._probe():
                    return
                last_progress = time.monotonic()

    def _probe(self) -> bool:
        """Zero progress with work outstanding: ask every participant
        directly, then double-check actor liveness with the controller.
        Returns False when the DAG was failed (pump must exit). With
        RTPU_DAG_RECOVERY on, a dead restartable participant triggers an
        in-place recovery instead of teardown."""
        if not flags.get("RTPU_DAG_RECOVERY"):
            return self._probe_failfast()
        return self._probe_recover()

    def _probe_failfast(self) -> bool:
        """PR 10 semantics (RTPU_DAG_RECOVERY=0): any participant anomaly
        tears the whole DAG down with a typed error."""
        plan = self._plan
        for wid, conn in self._conns.items():
            try:
                r = conn.request_threadsafe(
                    {"kind": "dag_status", "dag": self.dag_id}).result(3)
            except Exception as e:
                self._fail(DAGTeardownError(
                    f"compiled DAG {self.dag_id[:8]}: participant worker "
                    f"{wid[:8]} is unreachable ({type(e).__name__}: {e})"))
                return False
            if not r.get("known"):
                self._fail(DAGTeardownError(
                    f"compiled DAG {self.dag_id[:8]}: worker {wid[:8]} "
                    f"lost its execution plan (restarted?)"))
                return False
            if r.get("failed"):
                self._fail(DAGTeardownError(
                    f"compiled DAG {self.dag_id[:8]}: resident loop "
                    f"failed: {r['failed']}"))
                return False
        for ep, info in plan["endpoints"].items():
            if ep == "driver":
                continue
            try:
                d = self._wc.client.request(
                    {"kind": "resolve_actor", "actor_id": info["actor_id"],
                     "wait": 0}, timeout=5)
            except Exception:
                continue  # controller hiccup: not evidence of actor death
            direct = d.get("direct") or {}
            if (d.get("state") != "alive"
                    or direct.get("worker_id") != info["worker_id"]):
                self._fail(DAGTeardownError(
                    f"compiled DAG {self.dag_id[:8]}: stage actor "
                    f"{info['actor_id'][:8]} died or moved "
                    f"(state={d.get('state')}); channels cannot be "
                    f"re-established — recompile the DAG"))
                return False
        return True

    # -- self-healing (RTPU_DAG_RECOVERY) ---------------------------------

    def _probe_recover(self) -> bool:
        """Classify each participant: fine / suspect (unreachable but the
        controller still believes in it — partitions heal without a
        restart) / dead (controller confirms it died, moved, or is
        restarting). Dead restartable participants start a recovery; a
        participant whose restart budget is exhausted still fails the DAG
        with the PR 10 typed error."""
        plan = self._plan
        unreachable: set = set()
        for wid, conn in list(self._conns.items()):
            try:
                r = conn.request_threadsafe(
                    {"kind": "dag_status", "dag": self.dag_id}).result(3)
            except Exception:
                unreachable.add(wid)
                continue
            if not r.get("known"):
                unreachable.add(wid)  # worker lost its plan (restarted)
                continue
            if r.get("failed"):
                self._fail(DAGTeardownError(
                    f"compiled DAG {self.dag_id[:8]}: resident loop "
                    f"failed: {r['failed']}"))
                return False
        dead_eps: Dict[str, str] = {}
        for ep, info in plan["endpoints"].items():
            if ep == "driver":
                continue
            try:
                d = self._wc.client.request(
                    {"kind": "resolve_actor",
                     "actor_id": info["actor_id"], "wait": 0}, timeout=5)
            except Exception:
                continue  # controller hiccup: not evidence of death
            state = d.get("state")
            direct = d.get("direct") or {}
            if state == "dead":
                self._fail(DAGTeardownError(
                    f"compiled DAG {self.dag_id[:8]}: stage actor "
                    f"{info['actor_id'][:8]} is dead and will not restart "
                    f"(max_restarts=0 or restart budget exhausted)"))
                return False
            if state != "alive" or not d.get("direct"):
                dead_eps[ep] = "worker_killed"
            elif direct.get("worker_id") != info["worker_id"]:
                dead_eps[ep] = ("worker_killed"
                                if info["worker_id"] in unreachable
                                else "drain")
            # alive on the recorded worker but the worker is unreachable:
            # suspected partition — stay patient, the next stall re-probes.
        if dead_eps:
            causes = set(dead_eps.values())
            cause = "drain" if causes == {"drain"} else "worker_killed"
            return self._recover(dead_eps, cause)
        return True

    def _notify_recovery(self, phase: str, **extra) -> None:
        try:
            self._wc.client.send_nowait(
                {"kind": "dag_recovery", "dag_id": self.dag_id,
                 "phase": phase, **extra})
        except Exception:
            pass

    def _recover(self, dead_eps: Dict[str, str], cause: str) -> bool:
        """Heal in place: quiesce survivors, wait out the controller's
        actor restart, rebuild only the affected edges under a bumped
        epoch, replay retained items, resume. Runs on the pump thread."""
        t0 = time.monotonic()
        plan = self._plan
        dead_aids = sorted({plan["endpoints"][ep]["actor_id"]
                            for ep in dead_eps})
        self._recovering = True
        self._recovery_count += 1
        self._notify_recovery("died", cause=cause, actors=dead_aids)
        try:
            self._recover_inner(dead_eps, cause)
        except Exception as e:
            self._recovering = False
            with self._cond:
                self._cond.notify_all()
            self._notify_recovery("failed", cause=cause, actors=dead_aids)
            self._fail(DAGTeardownError(
                f"compiled DAG {self.dag_id[:8]}: recovery failed "
                f"({type(e).__name__}: {e})"))
            return False
        self._recovering = False
        with self._cond:
            self._cond.notify_all()
        dt = time.monotonic() - t0
        _m_recoveries.inc(1, {"cause": cause})
        _m_recovery_s.observe(dt)
        self._notify_recovery("recovered", cause=cause, actors=dead_aids,
                              duration_s=dt)
        return True

    def _recover_inner(self, dead_eps: Dict[str, str], cause: str) -> None:
        plan = self._plan
        dead_ep_set = set(dead_eps)
        dead_actor_eps: Dict[str, List[str]] = {}
        for ep in sorted(dead_eps):
            dead_actor_eps.setdefault(
                plan["endpoints"][ep]["actor_id"], []).append(ep)
        self._notify_recovery("recovering", cause=cause,
                              actors=sorted(dead_actor_eps))

        # 1. Quiesce the survivors. A conn whose worker hosted only dead
        # endpoints is expectedly unreachable; anything else failing
        # mid-pause is a double fault and aborts the recovery.
        eps_of_wid: Dict[str, List[str]] = {}
        for ep, info in plan["endpoints"].items():
            if ep != "driver":
                eps_of_wid.setdefault(info["worker_id"], []).append(ep)
        survivors: Dict[str, Any] = {}
        for wid, conn in list(self._conns.items()):
            try:
                conn.request_threadsafe(
                    {"kind": "dag_pause", "dag": self.dag_id}).result(5)
                survivors[wid] = conn
            except Exception:
                if all(ep in dead_ep_set
                       for ep in eps_of_wid.get(wid, [])):
                    self._conns.pop(wid, None)
                    try:
                        self._wc.client.io.call_nowait(conn.close())
                    except Exception:
                        pass
                else:
                    raise RuntimeError(
                        f"worker {wid[:8]} unreachable during quiesce")

        # 2. Barrier: every surviving loop parks and reports its exact
        # position (next seq + which inputs it already consumed for it).
        positions: Dict[int, Dict[str, Any]] = {}
        deadline = time.monotonic() + 20.0
        pending = dict(survivors)
        while pending:
            if time.monotonic() > deadline:
                raise RuntimeError("pipeline did not quiesce within 20s")
            for wid, conn in list(pending.items()):
                r = conn.request_threadsafe(
                    {"kind": "dag_positions",
                     "dag": self.dag_id}).result(5)
                if r.get("failed"):
                    raise RuntimeError(
                        f"resident loop failed during quiesce: "
                        f"{r['failed']}")
                if r.get("known") and r.get("parked"):
                    positions.update(
                        {int(k): v
                         for k, v in (r.get("positions") or {}).items()})
                    pending.pop(wid)
            if pending:
                time.sleep(0.05)

        # 3. Wait for the controller's restart path to bring every dead
        # actor back (checkpoint restore happens inside actor re-create).
        timeout_s = float(flags.get("RTPU_DAG_RECOVERY_TIMEOUT_S"))
        deadline = time.monotonic() + timeout_s
        for aid, eps in dead_actor_eps.items():
            while True:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"stage actor {aid[:8]} did not come back within "
                        f"{timeout_s:.0f}s")
                try:
                    d = self._wc.client.request(
                        {"kind": "resolve_actor", "actor_id": aid,
                         "wait": 0}, timeout=5)
                except Exception:
                    time.sleep(0.25)
                    continue
                if d.get("state") == "dead":
                    raise RuntimeError(
                        f"stage actor {aid[:8]} is dead (max_restarts=0 "
                        f"or restart budget exhausted)")
                direct = d.get("direct") or {}
                if (d.get("state") == "alive" and direct
                        and direct.get("worker_id")
                        not in {plan["endpoints"][ep]["worker_id"]
                                for ep in eps}):
                    info = dict(direct)
                    info["actor_id"] = aid
                    for ep in eps:
                        plan["endpoints"][ep] = dict(info)
                    break
                time.sleep(0.25)

        # 4. Dial connections for workers joining the DAG.
        from ray_tpu.core import protocol

        workers: Dict[str, Dict[str, Any]] = {}
        for ep, info in plan["endpoints"].items():
            if ep == "driver":
                continue
            w = workers.setdefault(
                info["worker_id"],
                {"host": info["host"], "port": info["port"]})
            w.setdefault("endpoints", []).append(ep)
        plan["workers"] = workers
        for wid, w in workers.items():
            if wid not in self._conns:
                self._conns[wid] = self._wc.client.io.call(
                    protocol.connect(w["host"], w["port"],
                                     handler=self._on_conn_msg,
                                     name=f"dag-{self.dag_id[:8]}"),
                    timeout=10)

        # 5. Replay positions for restarted stages, from the journal each
        # actor's restored checkpoint carries (exactly-once resume); a
        # stage with no journal restarts from the oldest seq any consumer
        # could still need.
        journals: Dict[str, Dict[int, int]] = {}
        by_wid: Dict[str, List[str]] = {}
        for aid, eps in dead_actor_eps.items():
            wid = plan["endpoints"][eps[0]]["worker_id"]
            by_wid.setdefault(wid, []).append(aid)
        for wid, aids in by_wid.items():
            try:
                r = self._conns[wid].request_threadsafe(
                    {"kind": "dag_resume_info", "dag": self.dag_id,
                     "actors": aids}).result(5)
                journals.update(r.get("journals") or {})
            except Exception:
                pass
        resume: Dict[int, int] = {}
        for aid, eps in dead_actor_eps.items():
            j = journals.get(aid) or {}
            for ep in eps:
                idx = int(ep[1:])
                resume[idx] = (int(j[idx]) + 1 if idx in j
                               else self._done_contig)

        # 6. Rewrite only the affected edges: bumped epoch, fresh ring
        # name, per-reader start cursors, transport split recomputed for
        # the new placement. Surviving edges keep rings and cursors.
        from ray_tpu.core.object_store import SlotRing

        affected: set = set()
        for eid, e in plan["edges"].items():
            consumers = list(e["ring_idx"].keys()) + list(e["streams"])
            if (e["producer"] in dead_ep_set
                    or any(c in dead_ep_set for c in consumers)):
                affected.add(eid)

        def consumer_need(eid: str, c: str) -> int:
            if c == "driver":
                return int(self._terminal_next.get(eid, 0))
            idx = int(c[1:])
            if c in dead_ep_set:
                return int(resume[idx])
            pos = positions.get(idx)
            if pos is None:
                return int(self._done_contig)
            need = int(pos["next"])
            if eid in (pos.get("have") or ()):
                need += 1
            return need

        starts_msg: Dict[str, Dict[str, int]] = {}
        for eid in sorted(affected):
            e = plan["edges"][eid]
            consumers = list(e["ring_idx"].keys()) + list(e["streams"])
            e["epoch"] = int(e.get("epoch", 0)) + 1
            needs = {c: consumer_need(eid, c) for c in consumers}
            starts_msg[eid] = needs
            prod = e["producer"]
            prod_node = plan["endpoints"][prod]["node_id"]
            ring_eps = [c for c in consumers
                        if plan["endpoints"][c]["node_id"] == prod_node]
            stream_eps = [c for c in consumers
                          if plan["endpoints"][c]["node_id"] != prod_node]
            if len(ring_eps) > SlotRing.MAX_READERS:
                raise RuntimeError(
                    f"edge {eid}: rebuilt placement has {len(ring_eps)} "
                    f"same-host consumers, exceeding the reader table")
            if prod == "driver":
                prod_first = self._next_seq
            elif prod in dead_ep_set:
                prod_first = resume[int(prod[1:])]
            else:
                ppos = positions.get(int(prod[1:]))
                prod_first = (int(ppos["next"]) if ppos
                              else int(self._done_contig))
            e["streams"] = stream_eps
            e["ring"] = (
                {"name": (f"rtpu_ch_{self.dag_id[:12]}{eid}"
                          f"p{e['epoch']}"),
                 "n_readers": len(ring_eps),
                 "base": min([needs[c] for c in ring_eps]
                             + [int(prod_first)]),
                 "starts": [needs[c] for c in ring_eps]}
                if ring_eps else None)
            e["ring_idx"] = {c: i for i, c in enumerate(ring_eps)}

        # 7. Driver-local rebuild. Stream inboxes swap BEFORE broadcast
        # (replayed frames can land immediately); ring readers re-attach
        # AFTER it (the producer creates the fresh segment on rebuild).
        in_edge = plan["edges"].get("in")
        if in_edge is not None and "in" in affected:
            old_writer = self._input_writer
            retained = old_writer.retained if old_writer else None
            if old_writer is not None:
                old_writer.aborted = True
                try:
                    old_writer.close()
                except Exception:
                    pass
            ring_writer = None
            if in_edge["ring"]:
                cfg = in_edge["ring"]
                ring_writer = channels.ShmEdgeWriter(SlotRing.create(
                    plan["depth"], plan["slot_bytes"], cfg["n_readers"],
                    name=cfg["name"], epoch=in_edge["epoch"],
                    base=cfg["base"], reader_starts=cfg["starts"]))
            targets = []
            for dst in in_edge["streams"]:
                conn = self._conns[plan["endpoints"][dst]["worker_id"]]
                targets.append((conn.send_with_raw_threadsafe, dst))
            new_writer = channels.EdgeWriter(
                self.dag_id, "in", ring_writer, targets,
                retain=self._retain_depth(), epoch=in_edge["epoch"])
            if retained and new_writer.retained is not None:
                new_writer.retained.extend(retained)
            self._input_writer = new_writer
        ring_reattach: List[str] = []
        for eid in set(self._output_edges):
            if eid not in affected:
                continue
            e = plan["edges"][eid]
            old = self._terminal_readers.get(eid)
            if "driver" in e["streams"]:
                inbox = channels.StreamInbox()
                self._inboxes[(eid, "driver")] = inbox
                self._terminal_readers[eid] = inbox
            else:
                ring_reattach.append(eid)
            if isinstance(old, channels.ShmEdgeReader):
                try:
                    old.close()
                except Exception:
                    pass
            elif (isinstance(old, channels.StreamInbox)
                    and old is not self._terminal_readers.get(eid)):
                old.close()

        # 8. Broadcast the rebuild. Every participant — including a
        # worker whose stages all moved away — applies it; parked loops
        # wake, swap affected IO, replay, and resume (or exit).
        wire = self._wire_plan(plan)
        futs = [(wid, conn.request_threadsafe(
            {"kind": "dag_rebuild", "plan": wire, "starts": starts_msg,
             "resume": resume, "affected": sorted(affected)}))
            for wid, conn in self._conns.items()]
        for wid, f in futs:
            f.result(20)

        for eid in ring_reattach:
            e = plan["edges"][eid]
            stale = self._inboxes.pop((eid, "driver"), None)
            if stale is not None:
                stale.close()
            self._terminal_readers[eid] = channels.ShmEdgeReader(
                e["ring"]["name"], e["ring_idx"]["driver"],
                expect_epoch=int(e["epoch"]))

        # 9. Driver-side replay: the input edge re-delivers retained
        # items the rebuilt consumers still need; when the input edge
        # survived untouched, re-deliver only the tail an aborted
        # mid-recovery execute left unwritten.
        iw = self._input_writer
        if iw is not None:
            if "in" in affected:
                base = (in_edge.get("ring") or {}).get("base")
                iw.replay(starts_msg.get("in", {}), base,
                          stop=lambda: self._torn_down)
            elif iw.ring_writer is not None and iw.retained:
                ws = iw.ring_writer.ring.write_seq()
                for seq, kind, payload in list(iw.retained):
                    if seq >= ws:
                        iw.write(seq, kind, payload,
                                 stop=lambda: self._torn_down)
        iw = None

        # 10. Drop connections to workers the DAG no longer touches.
        for wid in list(self._conns):
            if wid not in workers:
                conn = self._conns.pop(wid)
                try:
                    self._wc.client.io.call_nowait(conn.close())
                except Exception:
                    pass

    def _fail(self, err: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = err
            self._cond.notify_all()
        # Full teardown: drain the window, free channels, release actors'
        # mailbox threads. Every outstanding ref resolves with the error.
        self.teardown(kill_actors=False, _already_failed=True)

    # ===================================================== public surface

    def execute(self, *args, **kwargs):
        if self._torn_down:
            if self._mode == "channels" and self._error is not None:
                raise DAGTeardownError(str(self._error)) from self._error
            raise DAGTeardownError("CompiledDAG has been torn down")
        if self._mode != "channels":
            return self._execute_submit(args, kwargs)
        # InputNode contract, evaluated eagerly so a bad call fails before
        # a seq is allocated.
        if args and kwargs:
            raise TypeError(
                "DAG execute() got both positional and keyword inputs; "
                "pass one or the other (use a dict input for named access)")
        if kwargs:
            value: Any = kwargs
        elif len(args) == 1:
            value = args[0]
        else:
            value = args
        payload = channels.encode_value(value)
        with self._xlock:
            with self._cond:
                while (self._error is None and not self._torn_down
                       and (self._recovering
                            or self._next_seq - self._done_contig
                            >= self._max_in_flight)):
                    self._cond.wait(0.05)
                if self._error is not None:
                    raise DAGTeardownError(
                        str(self._error)) from self._error
                if self._torn_down:
                    raise RuntimeError("CompiledDAG has been torn down")
                seq = self._next_seq
                self._next_seq += 1
                self._exec_ts[seq] = time.perf_counter()
            if self._input_writer is not None:
                try:
                    self._input_writer.write(
                        seq, channels.KIND_DATA, payload,
                        stop=lambda: self._torn_down or self._recovering)
                except channels.ChannelClosed:
                    # A recovery interrupted the write mid-flight. The
                    # payload is already in the retained window (appended
                    # before any transport leg), so the rebuild replays it;
                    # just wait the recovery out and hand back the ref.
                    with self._cond:
                        while (self._recovering and self._error is None
                               and not self._torn_down):
                            self._cond.wait(0.05)
                        clean = (self._error is None
                                 and not self._torn_down)
                    if not clean:
                        err = self._error
                        raise DAGTeardownError(
                            "CompiledDAG was torn down mid-execute"
                            + (f": {err}" if err else "")) from err
        return ChannelDAGRef(self, seq)

    def _execute_submit(self, args, kwargs) -> CompiledDAGRef:
        while len(self._inflight) >= self._max_in_flight:
            oldest = self._inflight.popleft()
            refs = oldest.ref if isinstance(oldest.ref, list) else [oldest.ref]
            api.wait(refs, num_returns=len(refs))
        memo: Dict[int, Any] = {"__input__": (args, kwargs)}
        memo.update(self._actor_handles)  # reuse persistent actors
        out = CompiledDAGRef(self._output._execute_memo(memo))
        self._inflight.append(out)
        return out

    def _get_result(self, seq: int, timeout: Optional[float]):
        from ray_tpu.core.controller import GetTimeoutError

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while seq not in self._finished:
                if self._error is not None:
                    raise DAGTeardownError(
                        str(self._error)) from self._error
                if self._torn_down:
                    raise DAGTeardownError(
                        "CompiledDAG was torn down with this execution "
                        "outstanding")
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(
                        f"compiled DAG result seq={seq} not ready within "
                        f"{timeout}s")
                self._cond.wait(0.05 if remaining is None
                                else min(0.05, remaining))
            entry = self._results[seq]
        values = []
        for eid in self._output_edges:
            kind, payload = entry[eid]
            if kind == channels.KIND_ERROR:
                raise channels.decode(payload)
            values.append(channels.decode(payload))
        if not isinstance(self._output, MultiOutputNode):
            return values[0]
        return values

    def teardown(self, *, kill_actors: bool = True,
                 _already_failed: bool = False) -> None:
        with self._cond:
            already = self._torn_down
            self._torn_down = True
        if already:
            # Another thread (typically the pump, via _fail) owns the
            # teardown; block until it finishes so resources are really
            # released when this call returns.
            self._teardown_done.wait(timeout=10)
            return
        self._inflight.clear()
        try:
            if self._mode == "channels":
                self._teardown_channels(kill_actors=kill_actors,
                                        notify=True,
                                        _already_failed=_already_failed)
            if kill_actors:
                for h in self._actor_handles.values():
                    try:
                        api.kill(h)
                    except Exception:
                        pass
            self._actor_handles.clear()
        finally:
            self._teardown_done.set()

    def _teardown_channels(self, *, kill_actors: bool = False,
                           notify: bool = False,
                           _already_failed: bool = False) -> None:
        if getattr(self, "_meter_src", None) is not None:
            from ray_tpu.dag import meter as dag_meter

            dag_meter.unregister_source(self._meter_src)
            self._meter_src = None
        self._pump_stop.set()
        with self._cond:
            self._cond.notify_all()
        # Tell every participant to stop its resident loops and release
        # its rings; a dead worker simply errors, its host's segments die
        # with the process tree / the force-unlink sweep.
        futs = []
        for wid, conn in self._conns.items():
            try:
                futs.append(conn.request_threadsafe(
                    {"kind": "dag_teardown", "dag": self.dag_id}))
            except Exception:
                pass
        for f in futs:
            try:
                f.result(3)
            except Exception:
                pass
        pump = getattr(self, "_pump_thread", None)
        if pump is not None and pump is not threading.current_thread():
            pump.join(timeout=3)
        if self._input_writer is not None:
            try:
                self._input_writer.close()
            except Exception:
                pass
            self._input_writer = None
        for r in self._terminal_readers.values():
            if isinstance(r, channels.ShmEdgeReader):
                try:
                    r.close()
                except Exception:
                    pass
        self._terminal_readers.clear()
        for inbox in self._inboxes.values():
            inbox.close()
        for conn in self._conns.values():
            try:
                self._wc.client.io.call_nowait(conn.close())
            except Exception:
                pass
        self._conns.clear()
        self._sweep_channel_names()
        # A resident loop that observed ChannelClosed in the teardown window
        # can re-bind its ring AFTER the sweep above and then be SIGKILLed
        # before its own 5s force-unlink fires. Idempotent second pass while
        # the driver is still alive; daemon so interpreter exit never waits.
        resweep = threading.Timer(2.0, self._sweep_channel_names)
        resweep.daemon = True
        resweep.start()
        if notify:
            try:
                self._wc.client.send_nowait(
                    {"kind": "dag_torndown", "dag_id": self.dag_id})
            except Exception:
                pass
            _live_delta(-1)
        if not _already_failed:
            with self._cond:
                self._cond.notify_all()

    def _sweep_channel_names(self) -> None:
        """Defensive last pass: unlink every shm segment and doorbell path
        the DAG could have created on THIS host — all edges, all recovery
        epochs, all per-seq sidecars. Surviving workers clean their own; a
        SIGKILLed producer leaves its ring, sidecars, and bell sockets
        behind, and only the driver knows the name prefix."""
        import glob
        import tempfile

        prefix = f"rtpu_ch_{self.dag_id[:12]}"
        named = set()
        for edge in self._plan.get("edges", {}).values():
            ring = edge.get("ring")
            if ring:
                named.add(ring["name"])
        for path in glob.glob(f"/dev/shm/{prefix}*"):
            channels._unlink_segment(os.path.basename(path))
        for name in named:
            channels._unlink_segment(name)  # non-Linux: no /dev/shm to glob
        for bell in glob.glob(
                os.path.join(tempfile.gettempdir(), f"{prefix}*")):
            try:
                os.unlink(bell)
            except OSError:
                pass

    def __enter__(self) -> "CompiledDAG":
        return self

    def __exit__(self, *exc):
        self.teardown()
        return False


def compile_dag(output_node: DAGNode, *, max_in_flight: int = 16) -> CompiledDAG:
    """Entry point mirroring ``dag.experimental_compile()``."""
    return CompiledDAG(output_node, max_in_flight=max_in_flight)


def _experimental_compile(self: DAGNode, *, max_in_flight: int = 16,
                          **_ignored) -> CompiledDAG:
    return CompiledDAG(self, max_in_flight=max_in_flight)


DAGNode.experimental_compile = _experimental_compile
