"""Compiled DAGs: persistent actor pipelines with pipelined dispatch.

Parity: reference python/ray/dag/compiled_dag_node.py (CompiledDAG,
ExecutableTask) + experimental/channel/shared_memory_channel.py. The
reference compiles an actor-method DAG into reusable mutable-plasma
channels so repeated executions skip per-call RPC setup; GPU-GPU hops ride
NCCL P2P. The TPU-native translation has two halves:

- **Host half (this file):** actors are instantiated once at compile time
  and every ``execute()`` submits the whole stage chain up front, wiring
  stage N's ObjectRef straight into stage N+1's arg list. Intermediates
  flow worker→worker through the shared-memory arena (ray_tpu's channel
  equivalent); the driver touches only the final ref. Because per-actor
  mailboxes are ordered, ``execute()`` calls issued back-to-back overlap
  across stages — item *i+1* is in stage 0 while item *i* is in stage 1 —
  which is the aDAG pipelining win without a bespoke channel type.
- **Device half:** chip-to-chip movement inside a stage is XLA's job
  (collectives over ICI scheduled by the compiler — see
  ray_tpu/parallel/pipeline.py for the in-graph microbatch pipeline). A
  CompiledDAG stitches *processes*; XLA stitches *chips*. The reference
  needs NCCL channels because torch ops don't compose across processes;
  jitted steps already internalize their collectives.

``max_in_flight`` bounds pipeline depth the way the reference's
``_max_buffered_results`` does: executing past the window blocks on the
oldest outstanding result.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.core import api
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)


class CompiledDAGRef:
    """Future for one compiled execution (reference CompiledDAGRef)."""

    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None):
        return api.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class CompiledDAG:
    def __init__(self, output_node: DAGNode, *, max_in_flight: int = 16):
        self._output = output_node
        self._nodes = output_node.topological()
        self._max_in_flight = max(1, int(max_in_flight))
        self._inflight: deque = deque()
        self._torn_down = False
        # Validate the whole graph BEFORE creating anything: a rejected
        # graph must not leak half-instantiated actors.
        for n in self._nodes:
            if not isinstance(
                n,
                (ClassNode, ClassMethodNode, FunctionNode, InputNode,
                 InputAttributeNode, MultiOutputNode),
            ):
                raise TypeError(
                    f"cannot compile node type {type(n).__name__}"
                )
            if isinstance(n, ClassNode):
                for up in n.topological():
                    if isinstance(up, (InputNode, InputAttributeNode)):
                        raise TypeError(
                            "compiled DAG: actor constructor args cannot "
                            "reference InputNode — actors are built once at "
                            "compile time, not per execution"
                        )
        # Instantiate every ClassNode once; these handles persist across
        # executions (the defining difference from DAGNode.execute()).
        self._actor_handles: Dict[int, Any] = {}
        boot_memo: Dict[int, Any] = {}
        for n in self._nodes:
            if isinstance(n, ClassNode):
                self._actor_handles[id(n)] = n._execute_memo(boot_memo)

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("CompiledDAG has been torn down")
        while len(self._inflight) >= self._max_in_flight:
            oldest = self._inflight.popleft()
            refs = oldest.ref if isinstance(oldest.ref, list) else [oldest.ref]
            api.wait(refs, num_returns=len(refs))
        memo: Dict[int, Any] = {"__input__": (args, kwargs)}
        memo.update(self._actor_handles)  # reuse persistent actors
        out = CompiledDAGRef(self._output._execute_memo(memo))
        self._inflight.append(out)
        return out

    def teardown(self, *, kill_actors: bool = True) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        self._inflight.clear()
        if kill_actors:
            for h in self._actor_handles.values():
                try:
                    api.kill(h)
                except Exception:
                    pass
        self._actor_handles.clear()

    def __enter__(self) -> "CompiledDAG":
        return self

    def __exit__(self, *exc):
        self.teardown()
        return False


def compile_dag(output_node: DAGNode, *, max_in_flight: int = 16) -> CompiledDAG:
    """Entry point mirroring ``dag.experimental_compile()``."""
    return CompiledDAG(output_node, max_in_flight=max_in_flight)


def _experimental_compile(self: DAGNode, *, max_in_flight: int = 16,
                          **_ignored) -> CompiledDAG:
    return CompiledDAG(self, max_in_flight=max_in_flight)


DAGNode.experimental_compile = _experimental_compile
