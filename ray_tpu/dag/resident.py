"""Worker-side half of compiled-DAG channel execution.

``dag_install`` (pushed over the driver's per-DAG direct connection)
creates this worker's producer rings, registers stream inboxes, and parks
one *resident loop* on each participating actor's mailbox thread via the
``__create__`` closure lane — the same lane actor construction rides, so
the loop starts strictly after the actor exists and occupies the mailbox
until teardown (ordinary queued calls wait behind it, preserving the
actor's single-threaded execution contract).

The loop is transport-blind: it blocks on its input channels (shm ring or
stream inbox, both exposing ``recv``), runs the bound method, writes the
result into its output edge, and advances to the next global seq. Errors
are *values*: a raised exception is encoded as a KIND_ERROR item and flows
downstream edge-by-edge until it reaches the driver, which surfaces it on
that seq's ref — the pipeline itself keeps running for later seqs.

Infra failures (torn ring, dead peer, closed driver conn) stop the loop
and record ``wd.fail``; the driver's stall probe reads it via
``dag_status`` and tears the whole DAG down with a typed error.

**Recovery (RTPU_DAG_RECOVERY).** When a participant dies, the driver
quiesces the survivors (``dag_pause`` → every loop parks between
microbatches and reports its exact position: the next seq it will apply
plus which input edges it already consumed for it), waits for the
controller's restart path to bring the dead stage back (restoring its
durable checkpoint when one is configured), then pushes ``dag_rebuild``:
an updated plan in which only the affected edges carry a bumped epoch, a
fresh ring name, and per-reader start cursors. Parked loops swap the
affected halves of their channel IO in place, producers replay their
retained unacked items, and the pipeline resumes with every microbatch
delivered exactly once. The loop journals its last-applied seq (plus a
window of encoded outputs) per stage under the ``__dag__<dag_id>`` key of
the actor's PR 8 exactly-once journal, inside the same durable checkpoint
record — a restarted stage resumes from there instead of seq 0 and
re-emits journaled outputs without re-executing them. ``drain_node``
rides the same machinery: the worker intercepts the migration snapshot,
runs it at a seq-consistent point, parks the loop, and the stall probe
turns the migrated stage into an ordinary recovery with zero failed refs.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ray_tpu import flags
from ray_tpu.core import object_store
from ray_tpu.dag import channels


def _dags(runtime) -> Dict[str, "WorkerDAG"]:
    d = getattr(runtime, "dag_channels", None)
    if d is None:
        d = runtime.dag_channels = {}
    return d


def handle_direct_message(runtime, conn, msg):
    """Dispatch dag_* kinds arriving on the worker's direct server."""
    kind = msg["kind"]
    if kind == "dag_install":
        return handle_install(runtime, conn, msg)
    if kind == "dag_teardown":
        return handle_teardown(runtime, msg)
    if kind == "dag_status":
        return handle_status(runtime, msg)
    if kind == "dag_channel_item":
        return handle_item(runtime, msg)
    if kind == "dag_pause":
        return handle_pause(runtime, msg)
    if kind == "dag_positions":
        return handle_positions(runtime, msg)
    if kind == "dag_rebuild":
        return handle_rebuild(runtime, conn, msg)
    if kind == "dag_resume_info":
        return handle_resume_info(runtime, msg)
    raise ValueError(f"direct server: unknown kind {kind!r}")


def handle_install(runtime, conn, msg):
    plan = msg["plan"]
    wd = WorkerDAG(runtime, conn, plan)
    _dags(runtime)[plan["dag_id"]] = wd
    wd.setup()
    return {"ok": True, "worker_id": runtime.worker_id}


def handle_teardown(runtime, msg):
    wd = _dags(runtime).pop(msg["dag"], None)
    if wd is not None:
        wd.stop()
    return {"ok": True}


def handle_status(runtime, msg):
    wd = _dags(runtime).get(msg["dag"])
    if wd is None:
        return {"ok": True, "known": False}
    return {"ok": True, "known": True,
            "failed": repr(wd.fail) if wd.fail is not None else None,
            "progress": dict(wd.progress)}


def handle_item(runtime, msg):
    """A raw-tail stream frame landed: route into the (edge, endpoint)
    inbox. Fire-and-forget (no rid) — a frame for an unknown DAG (already
    torn down) or from a superseded edge epoch (a writer incarnation a
    rebuild replaced) is dropped, matching the mutable-channel contract
    that stale items are superseded, never queued."""
    wd = _dags(runtime).get(msg["dag"])
    if wd is None:
        return None
    edge = wd.plan["edges"].get(msg["edge"])
    if edge is not None and int(msg.get("epoch", 0)) != int(
            edge.get("epoch", 0)):
        return None
    inbox = wd.inboxes.get((msg["edge"], msg["to"]))
    if inbox is not None:
        data = bytes(msg["data"])
        if wd.meter:
            # Stream edges have no shm counter block: account frames as
            # they land, and keep the writer's piggybacked cumulative
            # high-water ("wi"/"wb") so the sampler can report the
            # producer's view even when this consumer lags.
            st = wd.stream_stats.get(msg["edge"])
            if st is None:
                st = wd.stream_stats[msg["edge"]] = {
                    "items": 0, "bytes": 0, "wi": 0, "wb": 0}
            st["items"] += 1
            st["bytes"] += len(data)
            if "wi" in msg:
                st["wi"] = max(st["wi"], int(msg["wi"]))
                st["wb"] = max(st["wb"], int(msg["wb"]))
        inbox.push(msg["seq"], msg["vk"], data)
    return None


def handle_pause(runtime, msg):
    """Quiesce request: flip the pause flag and poke every blocking wait.
    Returns immediately — the driver polls ``dag_positions`` for the
    actual barrier so the worker io loop never blocks behind a stage."""
    wd = _dags(runtime).get(msg["dag"])
    if wd is None:
        return {"ok": True, "known": False}
    wd.pause()
    return {"ok": True, "known": True}


def handle_positions(runtime, msg):
    wd = _dags(runtime).get(msg["dag"])
    if wd is None:
        return {"ok": True, "known": False}
    return {"ok": True, "known": True, "parked": wd.all_parked(),
            "positions": wd.positions_snapshot(),
            "failed": repr(wd.fail) if wd.fail is not None else None}


def handle_rebuild(runtime, conn, msg):
    dag_id = msg["plan"]["dag_id"]
    wd = _dags(runtime).get(dag_id)
    if wd is None:
        # This worker joins the DAG mid-life: it hosts a restarted stage.
        wd = WorkerDAG(runtime, conn, msg["plan"])
        wd.recover = {"resume": msg["resume"], "starts": msg["starts"],
                      "affected": set(msg["affected"])}
        _dags(runtime)[dag_id] = wd
        wd.setup()
    else:
        wd.apply_rebuild(conn, msg["plan"], msg["starts"], msg["resume"],
                         set(msg["affected"]))
    return {"ok": True, "worker_id": runtime.worker_id}


def handle_resume_info(runtime, msg):
    """Report the last seq each requested (restarted) actor's DAG journal
    recorded per stage — the driver derives replay positions from it."""
    journals: Dict[str, Dict[int, int]] = {}
    key = "__dag__" + msg["dag"]
    for aid in msg["actors"]:
        mb = runtime.actors.get(aid)
        if mb is None:
            continue
        with mb._seq_lock:
            ent = mb.journal.get(key) or {}
            journals[aid] = {int(idx): int(rec["seq"])
                             for idx, rec in ent.items()}
    return {"ok": True, "journals": journals}


class _Err:
    """Local-edge error marker: a same-actor stage→stage binding whose
    producer raised carries the encoded payload forward unchanged."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes):
        self.payload = payload


class _Paused(Exception):
    """Control flow: a quiesce / snapshot request interrupted a stage;
    unwind to the loop top (partial per-seq progress survives in the
    cache) and handle it there."""


def _sweep_ring(ring) -> None:
    """Unlink a superseded ring incarnation plus any per-seq sidecar
    segments it spilled (named ``<ring>s<seq>``)."""
    import glob
    import os

    for path in glob.glob(f"/dev/shm/{ring.name}s*"):
        channels._unlink_segment(os.path.basename(path))
    try:
        ring.unlink()
    except Exception:
        pass


class WorkerDAG:
    """Everything this worker holds for one compiled DAG."""

    def __init__(self, runtime, conn, plan: Dict[str, Any]):
        self.runtime = runtime
        self.driver_conn = conn
        self.plan = plan
        self.dag_id = plan["dag_id"]
        self.stopped = threading.Event()
        self.fail: Optional[BaseException] = None
        self.progress: Dict[int, int] = {}  # stage idx -> last finished seq
        self.rings: Dict[str, object_store.SlotRing] = {}  # edges I produce
        self.ring_bases: Dict[str, int] = {}
        self.inboxes: Dict[tuple, channels.StreamInbox] = {}
        self._senders: Dict[tuple, Any] = {}  # (host, port) -> RawStreamSender
        self._lock = threading.Lock()
        self._cleaned: set = set()
        # -- recovery state --
        self.recover: Optional[Dict[str, Any]] = None  # set for mid-life join
        self.pause_req = threading.Event()
        self.resume_gen = 0
        self._resume_cond = threading.Condition()
        self._parked: set = set()       # actor ids currently at the barrier
        self._loop_actors: set = set()  # actor ids with a live loop
        self._suspended: set = set()    # drain-snapshotted, awaiting rebuild
        self._snap_reqs: Dict[str, Any] = {}  # actor id -> snapshot closure
        self._pos: Dict[int, int] = {}  # stage idx -> next seq to apply
        self._cache: Dict[int, Dict[str, Any]] = {}  # partial per-seq state
        self._affected: set = set()
        self._starts: Dict[str, Dict[str, int]] = {}
        self._retain = (int(plan["depth"]) + 2
                        if flags.get("RTPU_DAG_RECOVERY") else 0)
        # -- channel meter state (RTPU_DAG_METER) --
        # Plain-int phase accumulators written only by the stage's own
        # mailbox thread; the flush sampler (same process) reads them —
        # GIL-atomic int loads, no locks on the hot path.
        self.meter = bool(flags.get("RTPU_DAG_METER"))
        self.stage_ns: Dict[int, Dict[str, int]] = {}
        self.stream_stats: Dict[str, Dict[str, int]] = {}
        # Recent per-stage step spans for state.dag_timeline():
        # (idx, seq, wall_end_s, recv_ns, compute_ns, send_ns, blocked_ns).
        self.spans: deque = deque(maxlen=512)

    # -- install -----------------------------------------------------------

    def _my_endpoints(self) -> List[str]:
        wid = self.runtime.worker_id
        return [ep for ep, info in self.plan["endpoints"].items()
                if info.get("worker_id") == wid]

    def _create_ring(self, eid: str, edge: Dict[str, Any]) -> None:
        old = self.rings.get(eid)
        if old is not None:
            _sweep_ring(old)  # superseded epoch; loop-side close is a no-op
        cfg = edge["ring"]
        base = int(cfg.get("base", 0))
        self.rings[eid] = object_store.SlotRing.create(
            self.plan["depth"], self.plan["slot_bytes"], cfg["n_readers"],
            name=cfg["name"], epoch=int(edge.get("epoch", 0)),
            base=base, reader_starts=cfg.get("starts"))
        self.ring_bases[eid] = base

    def setup(self) -> None:
        plan = self.plan
        mine = set(self._my_endpoints())
        if self.recover is not None:
            self._starts = dict(self.recover.get("starts") or {})
            self._affected = set(self.recover.get("affected") or ())
        # Producer rings first: same-host consumers (possibly on other
        # workers) attach by name with a bounded retry window.
        for eid, edge in plan["edges"].items():
            if edge["producer"] in mine and edge.get("ring"):
                self._create_ring(eid, edge)
        # Stream inboxes for every cross-host edge that lands here.
        by_actor: Dict[str, List[Dict[str, Any]]] = {}
        for stage in plan["stages"]:
            ep = f"s{stage['idx']}"
            if ep not in mine:
                continue
            for b in list(stage["args"]) + list(stage["kwargs"].values()):
                if b[0] == "chan" and ep in plan["edges"][b[1]]["streams"]:
                    self.inboxes.setdefault(
                        (b[1], ep), channels.StreamInbox())
            by_actor.setdefault(stage["actor_id"], []).append(stage)
        from ray_tpu.core.controller import ActorNotHostedError

        resume = (self.recover or {}).get("resume") or {}
        for aid, stages in by_actor.items():
            mb = self.runtime.actors.get(aid)
            if mb is None:
                raise ActorNotHostedError(
                    f"dag_install: actor {aid[:8]} is not hosted here")
            stages = sorted(stages, key=lambda s: s["idx"])
            for st in stages:
                self._pos[st["idx"]] = int(resume.get(st["idx"], 0))
            rec = self.recover
            mb.q.put({"__create__":
                      (lambda mb=mb, st=stages, rec=rec:
                       self._actor_loop(mb, st, recover=rec))})
        if self.meter:
            from ray_tpu.dag import meter

            meter.register_source(self)

    def sender(self, host: str, port: int):
        """One persistent raw-tail stream per downstream worker, shared by
        every edge and stage on this worker that targets it."""
        key = (host, port)
        with self._lock:
            s = self._senders.get(key)
            if s is None:
                from ray_tpu.core.transfer import RawStreamSender

                s = self._senders[key] = RawStreamSender(host, port)
            return s

    # -- quiesce / rebuild (driver-orchestrated recovery) ------------------

    def pause(self) -> None:
        self.pause_req.set()
        for inbox in self.inboxes.values():
            inbox.poke()

    def all_parked(self) -> bool:
        with self._resume_cond:
            return self._loop_actors <= self._parked

    def positions_snapshot(self) -> Dict[int, Dict[str, Any]]:
        out: Dict[int, Dict[str, Any]] = {}
        for idx, nxt in list(self._pos.items()):
            cache = self._cache.get(idx)
            have: List[str] = []
            if cache is not None and cache.get("seq") == nxt:
                have = list(cache["vals"].keys())
            out[idx] = {"next": int(nxt), "have": have}
        return out

    def apply_rebuild(self, conn, plan, starts, resume, affected) -> None:
        """Adopt the driver's post-recovery plan (runs on the io-loop
        thread): fresh rings for affected edges I produce, fresh inboxes
        for affected stream edges I consume, loops for stages newly hosted
        here, then wake every parked loop to swap its affected IO in place
        and replay."""
        self.driver_conn = conn
        self.plan = plan
        self._starts = {eid: dict(d) for eid, d in (starts or {}).items()}
        self._affected = set(affected)
        mine = set(self._my_endpoints())
        for eid in self._affected:
            edge = plan["edges"].get(eid)
            if edge and edge["producer"] in mine and edge.get("ring"):
                self._create_ring(eid, edge)
            elif eid in self.rings and (
                    edge is None or edge["producer"] not in mine):
                # The producer moved off this worker (drain): the old
                # incarnation's ring is ours to reap, nobody else's.
                _sweep_ring(self.rings.pop(eid))
                self.ring_bases.pop(eid, None)
        # Fresh inboxes for affected stream edges landing here (the old
        # deque may hold frames from the superseded epoch).
        adopted: Dict[str, List[Dict[str, Any]]] = {}
        for stage in plan["stages"]:
            ep = f"s{stage['idx']}"
            if ep not in mine:
                continue
            for b in list(stage["args"]) + list(stage["kwargs"].values()):
                if b[0] == "chan" and ep in plan["edges"][b[1]]["streams"]:
                    key = (b[1], ep)
                    if b[1] in self._affected or key not in self.inboxes:
                        old = self.inboxes.get(key)
                        self.inboxes[key] = channels.StreamInbox()
                        if old is not None:
                            old.close()
            if stage["idx"] not in self._pos:
                adopted.setdefault(stage["actor_id"], []).append(stage)
        for aid, stages in adopted.items():
            if aid in self._loop_actors:
                continue
            mb = self.runtime.actors.get(aid)
            if mb is None:
                continue  # restart still materializing; driver re-probes
            stages = sorted(stages, key=lambda s: s["idx"])
            for st in stages:
                self._pos[st["idx"]] = int(resume.get(st["idx"], 0))
            rec = {"resume": resume, "starts": self._starts,
                   "affected": self._affected}
            mb.q.put({"__create__":
                      (lambda mb=mb, st=stages, rec=rec:
                       self._actor_loop(mb, st, recover=rec))})
        with self._resume_cond:
            self.resume_gen += 1
            self.pause_req.clear()
            self._resume_cond.notify_all()

    def request_snapshot(self, actor_id: str, fn) -> bool:
        """Drain migration support: a resident loop owns the mailbox, so
        the ordinary snapshot closure lane would time out behind it. Hand
        the closure to the loop instead — it runs it between microbatches
        (a seq-consistent point) and then parks until the driver rebuilds
        the pipeline around the migrated stage."""
        if actor_id not in self._loop_actors:
            return False
        self._snap_reqs[actor_id] = fn
        for inbox in self.inboxes.values():
            inbox.poke()
        return True

    # -- the resident loop -------------------------------------------------

    def _stop_requested(self) -> bool:
        return self.stopped.is_set() or self.driver_conn.closed.is_set()

    def _make_reader(self, stage, eid: str):
        edge = self.plan["edges"][eid]
        ep = f"s{stage['idx']}"
        if ep in edge["streams"]:
            return self.inboxes[(eid, ep)]
        return channels.ShmEdgeReader(
            edge["ring"]["name"], edge["ring_idx"][ep],
            expect_epoch=int(edge.get("epoch", 0)))

    def _build_stage_io(self, stage):
        """Readers for each channel edge this stage consumes, writer for
        the edge it produces (None when only same-actor locals consume).
        Returned as a mutable [readers, writer] pair so a rebuild can swap
        the affected halves in place."""
        readers: Dict[str, Any] = {}
        for b in list(stage["args"]) + list(stage["kwargs"].values()):
            if b[0] != "chan" or b[1] in readers:
                continue
            readers[b[1]] = self._make_reader(stage, b[1])
        return [readers, self._build_stage_writer(stage)]

    def _build_stage_writer(self, stage):
        plan = self.plan
        eid = stage.get("out_edge")
        if eid is None:
            return None
        edge = plan["edges"][eid]
        ring_writer = None
        if eid in self.rings:
            ring_writer = channels.ShmEdgeWriter(self.rings[eid])
        targets = []
        for dst in edge["streams"]:
            if dst == "driver":
                targets.append(
                    (self.driver_conn.send_with_raw_threadsafe, dst))
            else:
                info = plan["endpoints"][dst]
                s = self.sender(info["host"], info["port"])
                targets.append((s.send, dst))
        return channels.EdgeWriter(self.dag_id, eid, ring_writer, targets,
                                   retain=self._retain,
                                   epoch=int(edge.get("epoch", 0)))

    def _journal_apply(self, mb, idx: int, seq: int, kind: int,
                       payload: bytes) -> None:
        """Record one applied stage output in the actor's exactly-once
        journal (PR 8 record format, caller key ``__dag__<dag_id>``). Runs
        strictly BEFORE the edge write, so an output a crash or pause
        interrupted mid-write is still replayable from the journal."""
        if self._retain == 0:
            return
        key = "__dag__" + self.dag_id
        with mb._seq_lock:
            ent = mb.journal.setdefault(key, {}).get(idx)
            if ent is None:
                ent = mb.journal[key][idx] = {
                    "seq": -1, "outs": deque(maxlen=self._retain)}
            ent["outs"].append((seq, kind, payload))
            ent["seq"] = seq

    def _seed_writer(self, mb, stage, writer) -> None:
        """Restart path: refill a fresh writer's retention window from the
        journaled outputs the previous incarnation checkpointed."""
        if writer is None or writer.retained is None or writer.retained:
            return
        key = "__dag__" + self.dag_id
        with mb._seq_lock:
            ent = (mb.journal.get(key) or {}).get(stage["idx"])
            outs = list(ent["outs"]) if ent else []
        writer.retained.extend(outs)

    def _maybe_checkpoint(self, mb) -> None:
        """Durable-checkpoint cadence for a mailbox this loop occupies:
        ``request_checkpoint`` would park behind us forever, so run the
        checkpoint inline — we ARE the mailbox thread."""
        if not getattr(mb, "ckpt_enabled", False):
            return
        mb.calls_since_ckpt += 1
        due = (mb.ckpt_every_n and mb.calls_since_ckpt >= mb.ckpt_every_n)
        if due or mb.ckpt_due():
            try:
                mb.do_checkpoint()
            except Exception:
                pass

    def _actor_loop(self, mb, stages: List[Dict[str, Any]],
                    recover: Optional[Dict[str, Any]] = None) -> None:
        """Runs ON the actor's mailbox thread until teardown."""
        from ray_tpu.core import context as ctx

        ctx.task_local.actor_id = mb.actor_id
        aid = mb.actor_id

        def interrupted() -> bool:
            return (self.pause_req.is_set() or aid in self._suspended
                    or aid in self._snap_reqs)

        with self._resume_cond:
            self._loop_actors.add(aid)
        io: List[list] = []
        try:
            try:
                for stage in stages:
                    io.append(self._build_stage_io(stage))
            except Exception as e:
                self.fail = self.fail or e
                return
            nexts = {st["idx"]: int(self._pos.get(st["idx"], 0))
                     for st in stages}
            if recover is not None:
                self._replay_writers(mb, stages, io,
                                     set(recover.get("affected") or ()))
            local_vals: Dict[int, Any] = {}
            seq = min(nexts.values()) if nexts else 0
            while True:
                if self._stop_requested():
                    raise channels.ChannelClosed("teardown")
                if self.pause_req.is_set() or aid in self._suspended:
                    if self._park(mb, stages, io) == "exit":
                        return
                    continue
                snap = self._snap_reqs.pop(aid, None)
                if snap is not None:
                    # Drain snapshot at a seq-consistent point; then park
                    # until the driver rebuilds around the migrated stage.
                    # No post-snapshot seq may run here, or its side
                    # effects would repeat on the restored copy.
                    try:
                        snap()
                    finally:
                        self._suspended.add(aid)
                    continue
                try:
                    for stage, sio in zip(stages, io):
                        idx = stage["idx"]
                        if seq < nexts[idx]:
                            self._skip_stage(mb, stage, seq, local_vals)
                            continue
                        self._run_stage(mb, stage, sio, seq, local_vals,
                                        interrupted)
                        nexts[idx] = self._pos[idx] = seq + 1
                        self.progress[idx] = seq
                    self._maybe_checkpoint(mb)
                except _Paused:
                    continue
                seq += 1
        except channels.ChannelClosed:
            pass  # upstream tore down first; the driver handles fallout
        except BaseException as e:
            self.fail = self.fail or e
        finally:
            self._cleanup(io)
            with self._resume_cond:
                self._loop_actors.discard(aid)
                self._suspended.discard(aid)
                self._parked.discard(aid)
                self._resume_cond.notify_all()

    def _park(self, mb, stages, io) -> str:
        """Quiesce barrier: advertise this loop as parked, wait for the
        driver's rebuild (or teardown), then swap the affected channel IO
        in place and replay retained items. Returns "exit" when the
        post-rebuild plan no longer hosts this actor's stages here
        (migrated away)."""
        aid = mb.actor_id
        with self._resume_cond:
            gen = self.resume_gen
            self._parked.add(aid)
            self._resume_cond.notify_all()
            try:
                while (self.resume_gen == gen
                       and not self._stop_requested()):
                    self._resume_cond.wait(0.1)
            finally:
                self._parked.discard(aid)
        if self._stop_requested():
            raise channels.ChannelClosed("teardown")
        self._suspended.discard(aid)
        mine = set(self._my_endpoints())
        if any(f"s{st['idx']}" not in mine for st in stages):
            for st in stages:
                self._pos.pop(st["idx"], None)
                self._cache.pop(st["idx"], None)
            return "exit"
        affected = set(self._affected)
        for stage, sio in zip(stages, io):
            readers = sio[0]
            for eid in list(readers.keys()):
                if eid not in affected:
                    continue
                old = readers.pop(eid)
                if isinstance(old, channels.ShmEdgeReader):
                    try:
                        old.close()
                    except Exception:
                        pass
                readers[eid] = self._make_reader(stage, eid)
                # A consumed-but-unapplied cached value from the old
                # incarnation stays valid: positions reported it, so
                # upstream replay starts after it.
            eid = stage.get("out_edge")
            if eid is not None and eid in affected and sio[1] is not None:
                old_writer = sio[1]
                old_writer.aborted = True
                retained = old_writer.retained
                try:
                    old_writer.close()  # unlinks the superseded ring
                except Exception:
                    pass
                new_writer = self._build_stage_writer(stage)
                if retained and new_writer.retained is not None:
                    new_writer.retained.extend(retained)
                sio[1] = new_writer
        self._replay_writers(mb, stages, io, affected)
        return "resume"

    def _replay_writers(self, mb, stages, io, affected) -> None:
        """Re-deliver retained items on every affected edge this actor
        produces: the rebuilt ring takes everything from its base up, and
        stream consumers are filtered by their reported need."""
        for stage, sio in zip(stages, io):
            eid = stage.get("out_edge")
            writer = sio[1]
            if writer is None or eid is None or eid not in affected:
                continue
            self._seed_writer(mb, stage, writer)
            writer.replay(self._starts.get(eid, {}),
                          self.ring_bases.get(eid),
                          stop=self._stop_requested)

    def _skip_stage(self, mb, stage, seq, local_vals) -> None:
        """This stage already applied ``seq`` in a previous incarnation:
        re-expose its journaled output for same-actor consumers without
        re-executing (exactly-once side effects)."""
        idx = stage["idx"]
        key = "__dag__" + self.dag_id
        with mb._seq_lock:
            ent = (mb.journal.get(key) or {}).get(idx)
            hit = None
            if ent is not None:
                for s, kind, payload in ent["outs"]:
                    if s == seq:
                        hit = (kind, payload)
                        break
        if hit is not None:
            kind, payload = hit
            local_vals[idx] = (_Err(payload)
                               if kind == channels.KIND_ERROR
                               else channels.decode(payload))

    def _recv_input(self, reader, eid: str, seq: int,
                    interrupted: Callable[[], bool]):
        """Blocking recv with quiesce awareness and stale-skip: a replayed
        duplicate (seq below what this stage needs) is dropped — recovery
        re-delivery is at-least-once per transport, exactly-once at the
        consumer."""
        while True:
            item = reader.recv(0.1, stop=self._stop_requested)
            if item is None:
                if self._stop_requested():
                    raise channels.ChannelClosed("teardown")
                if interrupted():
                    raise _Paused()
                continue
            got_seq = item[0]
            if got_seq < seq:
                continue  # superseded replay duplicate
            if got_seq > seq:
                raise RuntimeError(
                    f"dag {self.dag_id[:8]} edge {eid}: expected seq "
                    f"{seq}, got {got_seq} (torn channel)")
            return item

    def _run_stage(self, mb, stage, sio, seq, local_vals,
                   interrupted) -> None:
        idx = stage["idx"]
        readers, writer = sio[0], sio[1]
        # Phase accounting (RTPU_DAG_METER): four amortized monotonic
        # reads bracket recv / compute / send; ring backpressure inside
        # the write is subtracted out (it is the CONSUMER'S cost).
        mt = self.meter
        t0 = time.monotonic_ns() if mt else 0
        t1 = t2 = t0
        cache = self._cache.get(idx)
        if cache is None or cache.get("seq") != seq:
            cache = self._cache[idx] = {"seq": seq, "vals": {}, "out": None}
        if cache["out"] is None:
            for eid, reader in readers.items():
                if eid in cache["vals"]:
                    continue  # consumed before a pause interrupted us
                got_seq, kind, payload = self._recv_input(
                    reader, eid, seq, interrupted)
                cache["vals"][eid] = (kind, payload)
            if mt:
                t1 = time.monotonic_ns()
            err_payload: Optional[bytes] = None
            chan_vals: Dict[str, Any] = {}
            for eid in readers:
                kind, payload = cache["vals"][eid]
                if kind == channels.KIND_ERROR:
                    if err_payload is None:
                        err_payload = payload
                else:
                    chan_vals[eid] = channels.decode(payload)

            def resolve(b):
                nonlocal err_payload
                if b[0] == "const":
                    return b[1]
                if b[0] == "local":
                    v = local_vals.get(b[1])
                    if isinstance(v, _Err):
                        err_payload = err_payload or v.payload
                        return None
                    return v
                v = chan_vals.get(b[1])
                if b[1] not in chan_vals:
                    return None  # an upstream error consumed this value
                if b[2] is not None:
                    return channels.apply_selector(v, b[2])
                return v

            args = [resolve(b) for b in stage["args"]]
            kwargs = {k: resolve(b) for k, b in stage["kwargs"].items()}
            if err_payload is not None:
                out_kind, out_payload = channels.KIND_ERROR, err_payload
                local_vals[idx] = _Err(err_payload)
            else:
                try:
                    result = getattr(mb.instance, stage["method"])(
                        *args, **kwargs)
                    out_kind = channels.KIND_DATA
                    out_payload = channels.encode_value(result)
                    local_vals[idx] = result
                except BaseException as e:
                    out_kind = channels.KIND_ERROR
                    out_payload = channels.encode_error(e)
                    local_vals[idx] = _Err(out_payload)
            cache["out"] = (out_kind, out_payload)
            self._journal_apply(mb, idx, seq, out_kind, out_payload)
        if mt:
            t2 = time.monotonic_ns()
        blocked = 0
        if writer is not None:
            out_kind, out_payload = cache["out"]
            try:
                blocked = writer.write(
                    seq, out_kind, out_payload,
                    stop=lambda: self._stop_requested() or interrupted())
            except channels.ChannelClosed:
                if interrupted() and not self._stop_requested():
                    # Applied + journaled; the post-rebuild replay (or a
                    # plain retry after an unaffected-edge resume, which
                    # the retention dedup makes idempotent) delivers it.
                    raise _Paused()
                raise
        self._cache.pop(idx, None)
        if mt:
            t3 = time.monotonic_ns()
            recv = max(0, t1 - t0)
            comp = max(0, t2 - t1)
            send = max(0, t3 - t2 - (blocked or 0))
            st = self.stage_ns.get(idx)
            if st is None:
                st = self.stage_ns[idx] = {
                    "recv": 0, "compute": 0, "send": 0,
                    "blocked": 0, "steps": 0}
            st["recv"] += recv
            st["compute"] += comp
            st["send"] += send
            st["blocked"] += blocked or 0
            st["steps"] += 1
            self.spans.append(
                (idx, seq, time.time(), recv, comp, send, blocked or 0))

    # -- teardown ----------------------------------------------------------

    def stop(self) -> None:
        """Called from the io loop (dag_teardown) or failure paths: flips
        the stop flag and pokes every blocking wait. Resident loops exit
        within one wait slice and release their channels; persistent
        cross-host senders close here too (a loop mid-send surfaces an
        OSError and exits), and a timer sweeps anything a never-started
        loop would have owned."""
        self.stopped.set()
        from ray_tpu.dag import meter

        meter.unregister_source(self)
        for inbox in self.inboxes.values():
            inbox.close()
        with self._resume_cond:
            self._resume_cond.notify_all()
        self._close_senders()
        threading.Timer(5.0, self._force_unlink).start()

    def _close_senders(self) -> None:
        with self._lock:
            senders, self._senders = dict(self._senders), {}
        for s in senders.values():
            try:
                s.close()
            except Exception:
                pass

    def _cleanup(self, io) -> None:
        for sio in io:
            readers, writer = sio[0], sio[1]
            for r in readers.values():
                if isinstance(r, channels.ShmEdgeReader):
                    try:
                        r.close()
                    except Exception:
                        pass
            if writer is not None:
                try:
                    writer.close()  # marks closed + unlinks the ring
                except Exception:
                    pass
                if writer.ring_writer is not None:
                    with self._lock:
                        self._cleaned.add(writer.edge_id)
        self._close_senders()

    def _force_unlink(self) -> None:
        """Defensive sweep: unlink producer rings whose loop never ran
        (actor died before the closure executed) or died uncleanly — and
        any per-seq sidecar segments those rings spilled, which a
        SIGKILLed peer's teardown would otherwise leak."""
        with self._lock:
            leftovers = {eid: ring for eid, ring in self.rings.items()
                         if eid not in self._cleaned}
            self._cleaned.update(leftovers)
        for ring in leftovers.values():
            _sweep_ring(ring)
        self._close_senders()
