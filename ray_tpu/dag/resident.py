"""Worker-side half of compiled-DAG channel execution.

``dag_install`` (pushed over the driver's per-DAG direct connection)
creates this worker's producer rings, registers stream inboxes, and parks
one *resident loop* on each participating actor's mailbox thread via the
``__create__`` closure lane — the same lane actor construction rides, so
the loop starts strictly after the actor exists and occupies the mailbox
until teardown (ordinary queued calls wait behind it, preserving the
actor's single-threaded execution contract).

The loop is transport-blind: it blocks on its input channels (shm ring or
stream inbox, both exposing ``recv``), runs the bound method, writes the
result into its output edge, and advances to the next global seq. Errors
are *values*: a raised exception is encoded as a KIND_ERROR item and flows
downstream edge-by-edge until it reaches the driver, which surfaces it on
that seq's ref — the pipeline itself keeps running for later seqs.

Infra failures (torn ring, dead peer, closed driver conn) stop the loop
and record ``wd.fail``; the driver's stall probe reads it via
``dag_status`` and tears the whole DAG down with a typed error.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import object_store
from ray_tpu.dag import channels


def _dags(runtime) -> Dict[str, "WorkerDAG"]:
    d = getattr(runtime, "dag_channels", None)
    if d is None:
        d = runtime.dag_channels = {}
    return d


def handle_direct_message(runtime, conn, msg):
    """Dispatch dag_* kinds arriving on the worker's direct server."""
    kind = msg["kind"]
    if kind == "dag_install":
        return handle_install(runtime, conn, msg)
    if kind == "dag_teardown":
        return handle_teardown(runtime, msg)
    if kind == "dag_status":
        return handle_status(runtime, msg)
    if kind == "dag_channel_item":
        return handle_item(runtime, msg)
    raise ValueError(f"direct server: unknown kind {kind!r}")


def handle_install(runtime, conn, msg):
    plan = msg["plan"]
    wd = WorkerDAG(runtime, conn, plan)
    _dags(runtime)[plan["dag_id"]] = wd
    wd.setup()
    return {"ok": True, "worker_id": runtime.worker_id}


def handle_teardown(runtime, msg):
    wd = _dags(runtime).pop(msg["dag"], None)
    if wd is not None:
        wd.stop()
    return {"ok": True}


def handle_status(runtime, msg):
    wd = _dags(runtime).get(msg["dag"])
    if wd is None:
        return {"ok": True, "known": False}
    return {"ok": True, "known": True,
            "failed": repr(wd.fail) if wd.fail is not None else None,
            "progress": dict(wd.progress)}


def handle_item(runtime, msg):
    """A raw-tail stream frame landed: route into the (edge, endpoint)
    inbox. Fire-and-forget (no rid) — a frame for an unknown DAG (already
    torn down) is dropped, matching the mutable-channel contract that
    stale items are superseded, never queued."""
    wd = _dags(runtime).get(msg["dag"])
    if wd is None:
        return None
    inbox = wd.inboxes.get((msg["edge"], msg["to"]))
    if inbox is not None:
        inbox.push(msg["seq"], msg["vk"], bytes(msg["data"]))
    return None


class _Err:
    """Local-edge error marker: a same-actor stage→stage binding whose
    producer raised carries the encoded payload forward unchanged."""

    __slots__ = ("payload",)

    def __init__(self, payload: bytes):
        self.payload = payload


class WorkerDAG:
    """Everything this worker holds for one compiled DAG."""

    def __init__(self, runtime, conn, plan: Dict[str, Any]):
        self.runtime = runtime
        self.driver_conn = conn
        self.plan = plan
        self.dag_id = plan["dag_id"]
        self.stopped = threading.Event()
        self.fail: Optional[BaseException] = None
        self.progress: Dict[int, int] = {}  # stage idx -> last finished seq
        self.rings: Dict[str, object_store.SlotRing] = {}  # edges I produce
        self.inboxes: Dict[tuple, channels.StreamInbox] = {}
        self._senders: Dict[tuple, Any] = {}  # (host, port) -> RawStreamSender
        self._lock = threading.Lock()
        self._cleaned: set = set()

    # -- install -----------------------------------------------------------

    def _my_endpoints(self) -> List[str]:
        wid = self.runtime.worker_id
        return [ep for ep, info in self.plan["endpoints"].items()
                if info.get("worker_id") == wid]

    def setup(self) -> None:
        plan = self.plan
        mine = set(self._my_endpoints())
        # Producer rings first: same-host consumers (possibly on other
        # workers) attach by name with a bounded retry window.
        for eid, edge in plan["edges"].items():
            if edge["producer"] in mine and edge.get("ring"):
                self.rings[eid] = object_store.SlotRing.create(
                    plan["depth"], plan["slot_bytes"],
                    edge["ring"]["n_readers"], name=edge["ring"]["name"])
        # Stream inboxes for every cross-host edge that lands here.
        by_actor: Dict[str, List[Dict[str, Any]]] = {}
        for stage in plan["stages"]:
            ep = f"s{stage['idx']}"
            if ep not in mine:
                continue
            for b in list(stage["args"]) + list(stage["kwargs"].values()):
                if b[0] == "chan" and ep in plan["edges"][b[1]]["streams"]:
                    self.inboxes.setdefault(
                        (b[1], ep), channels.StreamInbox())
            by_actor.setdefault(stage["actor_id"], []).append(stage)
        from ray_tpu.core.controller import ActorNotHostedError

        for aid, stages in by_actor.items():
            mb = self.runtime.actors.get(aid)
            if mb is None:
                raise ActorNotHostedError(
                    f"dag_install: actor {aid[:8]} is not hosted here")
            stages = sorted(stages, key=lambda s: s["idx"])
            mb.q.put({"__create__":
                      (lambda mb=mb, st=stages: self._actor_loop(mb, st))})

    def sender(self, host: str, port: int):
        """One persistent raw-tail stream per downstream worker, shared by
        every edge and stage on this worker that targets it."""
        key = (host, port)
        with self._lock:
            s = self._senders.get(key)
            if s is None:
                from ray_tpu.core.transfer import RawStreamSender

                s = self._senders[key] = RawStreamSender(host, port)
            return s

    # -- the resident loop -------------------------------------------------

    def _stop_requested(self) -> bool:
        return self.stopped.is_set() or self.driver_conn.closed.is_set()

    def _build_stage_io(self, stage):
        """Readers for each channel edge this stage consumes, writer for
        the edge it produces (None when only same-actor locals consume)."""
        plan = self.plan
        ep = f"s{stage['idx']}"
        readers: Dict[str, Any] = {}
        for b in list(stage["args"]) + list(stage["kwargs"].values()):
            if b[0] != "chan" or b[1] in readers:
                continue
            eid = b[1]
            edge = plan["edges"][eid]
            if ep in edge["streams"]:
                readers[eid] = self.inboxes[(eid, ep)]
            else:
                readers[eid] = channels.ShmEdgeReader(
                    edge["ring"]["name"], edge["ring_idx"][ep])
        writer = None
        eid = stage.get("out_edge")
        if eid is not None:
            edge = plan["edges"][eid]
            ring_writer = None
            if eid in self.rings:
                ring_writer = channels.ShmEdgeWriter(self.rings[eid])
            targets = []
            for dst in edge["streams"]:
                if dst == "driver":
                    targets.append(
                        (self.driver_conn.send_with_raw_threadsafe, dst))
                else:
                    info = plan["endpoints"][dst]
                    s = self.sender(info["host"], info["port"])
                    targets.append((s.send, dst))
            writer = channels.EdgeWriter(self.dag_id, eid,
                                         ring_writer, targets)
        return readers, writer

    def _actor_loop(self, mb, stages: List[Dict[str, Any]]) -> None:
        """Runs ON the actor's mailbox thread until teardown."""
        from ray_tpu.core import context as ctx

        ctx.task_local.actor_id = mb.actor_id
        io = []
        try:
            for stage in stages:
                io.append(self._build_stage_io(stage))
        except Exception as e:
            self.fail = self.fail or e
            self._cleanup(io)
            return
        local_vals: Dict[int, Any] = {}
        seq = 0
        try:
            while not self._stop_requested():
                for stage, (readers, writer) in zip(stages, io):
                    if not self._run_stage(mb, stage, readers, writer,
                                           seq, local_vals):
                        return
                    self.progress[stage["idx"]] = seq
                seq += 1
        except channels.ChannelClosed:
            pass  # upstream tore down first; the driver handles fallout
        except BaseException as e:
            self.fail = self.fail or e
        finally:
            self._cleanup(io)

    def _run_stage(self, mb, stage, readers, writer, seq,
                   local_vals) -> bool:
        err_payload: Optional[bytes] = None
        chan_vals: Dict[str, Any] = {}
        for eid, reader in readers.items():
            while True:
                item = reader.recv(0.1, stop=self._stop_requested)
                if item is not None:
                    break
                if self._stop_requested():
                    raise channels.ChannelClosed("teardown")
            got_seq, kind, payload = item
            if got_seq != seq:
                raise RuntimeError(
                    f"dag {self.dag_id[:8]} edge {eid}: expected seq "
                    f"{seq}, got {got_seq} (torn channel)")
            if kind == channels.KIND_ERROR:
                if err_payload is None:
                    err_payload = payload
            else:
                chan_vals[eid] = channels.decode(payload)

        def resolve(b):
            nonlocal err_payload
            if b[0] == "const":
                return b[1]
            if b[0] == "local":
                v = local_vals.get(b[1])
                if isinstance(v, _Err):
                    err_payload = err_payload or v.payload
                    return None
                return v
            v = chan_vals.get(b[1])
            if b[1] not in chan_vals:
                return None  # an upstream error consumed this edge's value
            if b[2] is not None:
                return channels.apply_selector(v, b[2])
            return v

        args = [resolve(b) for b in stage["args"]]
        kwargs = {k: resolve(b) for k, b in stage["kwargs"].items()}
        if err_payload is not None:
            out_kind, out_payload = channels.KIND_ERROR, err_payload
            local_vals[stage["idx"]] = _Err(err_payload)
        else:
            try:
                result = getattr(mb.instance, stage["method"])(
                    *args, **kwargs)
                out_kind = channels.KIND_DATA
                out_payload = channels.encode_value(result)
                local_vals[stage["idx"]] = result
            except BaseException as e:
                out_kind = channels.KIND_ERROR
                out_payload = channels.encode_error(e)
                local_vals[stage["idx"]] = _Err(out_payload)
        if writer is not None:
            writer.write(seq, out_kind, out_payload,
                         stop=self._stop_requested)
        return True

    # -- teardown ----------------------------------------------------------

    def stop(self) -> None:
        """Called from the io loop (dag_teardown) or failure paths: flips
        the stop flag and pokes every blocking wait. Resident loops exit
        within one wait slice and release their channels; a timer sweeps
        anything a never-started loop would have owned."""
        self.stopped.set()
        for inbox in self.inboxes.values():
            inbox.close()
        threading.Timer(5.0, self._force_unlink).start()

    def _cleanup(self, io) -> None:
        for readers, writer in io:
            for r in readers.values():
                if isinstance(r, channels.ShmEdgeReader):
                    try:
                        r.close()
                    except Exception:
                        pass
            if writer is not None:
                try:
                    writer.close()  # marks closed + unlinks the ring
                except Exception:
                    pass
                if writer.ring_writer is not None:
                    with self._lock:
                        self._cleaned.add(writer.edge_id)
        with self._lock:
            senders, self._senders = dict(self._senders), {}
        for s in senders.values():
            try:
                s.close()
            except Exception:
                pass

    def _force_unlink(self) -> None:
        """Defensive sweep: unlink producer rings whose loop never ran
        (actor died before the closure executed) or died uncleanly."""
        with self._lock:
            leftovers = {eid: ring for eid, ring in self.rings.items()
                         if eid not in self._cleaned}
            self._cleaned.update(leftovers)
        for ring in leftovers.values():
            try:
                ring.unlink()
            except Exception:
                pass
