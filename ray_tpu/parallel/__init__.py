"""Parallelism layer: device meshes, sharding rules, collectives.

The keystone the reference lacks (SURVEY.md §7 step 4): DP/FSDP/TP/PP/SP/EP
expressed as jax.sharding over a named Mesh, with host-level collectives for
processes outside the mesh.
"""
from .mesh import (
    AXIS_ORDER,
    MeshBootstrap,
    MeshSpec,
    best_effort_spec,
    make_mesh,
    single_device_mesh,
)
from .pipeline import pipeline_apply, pipeline_loss_fn
from .sharding import (
    DEFAULT_RULES,
    RULES_DP,
    RULES_FSDP,
    RULES_TP,
    constrain,
    logical_to_mesh_spec,
    named_sharding,
    replicated,
    shard_batch,
    tree_shardings,
)

__all__ = [
    "pipeline_apply",
    "pipeline_loss_fn",
    "AXIS_ORDER",
    "MeshSpec",
    "MeshBootstrap",
    "make_mesh",
    "single_device_mesh",
    "best_effort_spec",
    "DEFAULT_RULES",
    "RULES_DP",
    "RULES_FSDP",
    "RULES_TP",
    "named_sharding",
    "logical_to_mesh_spec",
    "tree_shardings",
    "constrain",
    "shard_batch",
    "replicated",
]
