"""Parallelism layer: device meshes, sharding rules, collectives.

The keystone the reference lacks (SURVEY.md §7 step 4): DP/FSDP/TP/PP/SP/EP
expressed as jax.sharding over a named Mesh, with host-level collectives for
processes outside the mesh.
"""
from .mesh import (
    AXIS_ORDER,
    MeshBootstrap,
    MeshSpec,
    best_effort_spec,
    make_mesh,
    single_device_mesh,
)
from .pipeline import pipeline_apply, pipeline_loss_fn


def __getattr__(name):
    # mpmd imports ray_tpu (actor API) — lazy so `import ray_tpu.parallel`
    # from inside a worker stays cheap and cycle-free.
    if name in ("MPMDPipeline", "StageFactory"):
        from ray_tpu.parallel import mpmd

        return getattr(mpmd, name)
    raise AttributeError(name)
from .sharding import (
    DEFAULT_RULES,
    RULES_DP,
    RULES_FSDP,
    RULES_TP,
    constrain,
    logical_to_mesh_spec,
    named_sharding,
    replicated,
    shard_batch,
    tree_shardings,
)

__all__ = [
    "MPMDPipeline",
    "StageFactory",
    "pipeline_apply",
    "pipeline_loss_fn",
    "AXIS_ORDER",
    "MeshSpec",
    "MeshBootstrap",
    "make_mesh",
    "single_device_mesh",
    "best_effort_spec",
    "DEFAULT_RULES",
    "RULES_DP",
    "RULES_FSDP",
    "RULES_TP",
    "named_sharding",
    "logical_to_mesh_spec",
    "tree_shardings",
    "constrain",
    "shard_batch",
    "replicated",
]
