"""Pipeline parallelism over the `pipe` mesh axis: a microbatched GPipe
schedule inside ONE jitted step, expressed entirely in GSPMD auto mode.

SURVEY.md §5.7 names pipeline parallelism a first-class requirement; the
reference has no in-graph pipeline engine at all (its compiled-DAG pipelines
actors at the task layer, dag/compiled_dag_node.py:291 — a different
altitude). The TPU-native design (the MaxText/praxis idiom) runs the whole
schedule inside XLA with NO manual collectives:

- The layer stack [L, ...] reshapes to [P, L/P, ...] with the leading stage
  dim sharded over `pipe` — each device holds its stage's contiguous layer
  block, zero repartitioning.
- A state buffer [P, mb, S, d], also pipe-sharded on the stage dim, holds
  the microbatch each stage is processing. Every tick vmaps the stage body
  (a lax.scan over that stage's layers) across the stage dim — perfectly
  SPMD — then hands activations to the next stage with jnp.roll along the
  stage dim, which XLA lowers to a CollectivePermute over `pipe`.
- Because everything is ordinary sharded computation, tensor/fsdp/expert
  sharding INSIDE a stage needs nothing special: the same rule table that
  shards the unpipelined model shards each stage's params and activations,
  and GSPMD inserts the per-stage collectives. pipe x fsdp, pipe x tensor
  and MoE-under-pipe compose by construction; autodiff is the standard
  transpose (the roll transposes to the reverse roll — the backward
  pipeline for free).
- The schedule is GPipe: with M microbatches and P stages it runs M+P-1
  ticks; bubble ticks compute garbage that output masking discards, and
  the MoE aux-loss contribution of bubbles is masked out the same way.

Embedding and the LM head run outside the scan in ordinary GSPMD land, so
vocab/fsdp sharding of those params keeps working unchanged.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import sharding as shd


def _stage_spec(rules: shd.Rules, mesh: Mesh, logical: Tuple) -> P:
    """PartitionSpec for an array with a leading stage dim: ('pipe', then
    the usual logical mapping for the remaining dims)."""
    inner = shd.logical_to_mesh_spec(logical, rules, mesh)
    return P("pipe", *tuple(inner))


def pipeline_apply(
    cfg,
    layers: Dict[str, jax.Array],
    x: jax.Array,  # [M, mb, S, d] microbatched activations
    mesh: Mesh,
    rules: Optional[Dict] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Run the layer stack as a P-stage GPipe pipeline.

    Returns (activations [M, mb, S, d], summed MoE aux loss — zero for
    dense stacks)."""
    from ray_tpu.models.transformer import layer_scan_body

    rules = rules or shd.DEFAULT_RULES
    num_stages = mesh.shape["pipe"]
    M, mb, S, d = x.shape
    num_ticks = M + num_stages - 1

    # [L, ...] -> [P, L/P, ...], stage dim pinned to `pipe`; remaining dims
    # keep their logical sharding (fsdp/tensor/expert) from the rule table.
    from ray_tpu.models.transformer import param_logical_specs

    lspecs = param_logical_specs(cfg)["layers"]

    def stage_fold(a, spec):
        L = a.shape[0]
        if L % num_stages:
            raise ValueError(
                f"n_layers {L} not divisible by pipe={num_stages}")
        staged = a.reshape(num_stages, L // num_stages, *a.shape[1:])
        # [P, L/P, *param_dims]: pipe on the stage dim, None for the L/P
        # dim, then the per-param logical mapping — off-by-one here would
        # silently shard heads/mlp dims onto the wrong mesh axes.
        inner = shd.logical_to_mesh_spec(tuple(spec)[1:], rules, mesh)
        return jax.lax.with_sharding_constraint(
            staged, NamedSharding(mesh, P("pipe", None, *tuple(inner))))

    layers_staged = jax.tree.map(
        stage_fold, layers, lspecs,
        is_leaf=lambda v: not isinstance(v, dict))

    act_logical = ("batch", "seq_act", "embed")
    state_sharding = NamedSharding(mesh, _stage_spec(rules, mesh, act_logical))

    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None],
                                 (mb, S))
    scan_body = layer_scan_body(cfg, positions)
    # Ring attention is a shard_map over `seq` and cannot nest inside the
    # vmapped stage body; dropping the seq_act routing makes attention()
    # use the dense per-stage kernel (context parallelism composes with
    # pipe at the batch level instead).
    inner_rules = {k: v for k, v in rules.items() if k != "seq_act"}

    def stage_apply(stage_layers, h):
        with shd.sharding_ctx(mesh, inner_rules):
            out, auxs = lax.scan(scan_body, h, stage_layers)
        return out, auxs.sum()

    vapply = jax.vmap(stage_apply)

    state0 = jnp.zeros((num_stages, mb, S, d), x.dtype)
    outputs0 = jnp.zeros_like(x)
    stage_ids = jnp.arange(num_stages)

    def tick(carry, t):
        state, outputs, aux_acc = carry
        # Stage 0 picks up microbatch t (bubble ticks recirculate garbage
        # that the masks below ignore).
        inject = x[jnp.minimum(t, M - 1)]
        state = state.at[0].set(jnp.where(t < M, inject, state[0]))
        state = jax.lax.with_sharding_constraint(state, state_sharding)
        out, aux = vapply(layers_staged, state)  # [P, mb, S, d], [P]
        # Stage s processes microbatch (t - s) this tick; outside [0, M)
        # it's a bubble — mask its aux contribution.
        mb_idx = t - stage_ids
        valid = (mb_idx >= 0) & (mb_idx < M)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0).sum()
        # The last stage emits microbatch t-(P-1) once real work reaches it.
        out_idx = t - (num_stages - 1)
        idx = jnp.clip(out_idx, 0, M - 1)
        outputs = outputs.at[idx].set(
            jnp.where(out_idx >= 0, out[num_stages - 1], outputs[idx]))
        # Hand activations to the next stage: a roll on the pipe-sharded
        # stage dim = CollectivePermute over ICI. Slot 0's content is
        # overwritten by the next injection.
        state = jnp.roll(out, 1, axis=0)
        state = jax.lax.with_sharding_constraint(state, state_sharding)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = lax.scan(
        tick, (state0, outputs0, jnp.zeros((), jnp.float32)),
        jnp.arange(num_ticks))
    # The per-layer aux loss is a token-MEAN (ops/moe.py); every microbatch
    # contributes one mean per layer, so the accumulated sum is M x the
    # full-batch value — normalize to match the unpipelined loss exactly
    # (equal-size microbatches make mean-of-means = full mean).
    return outputs, aux_acc / M


def pipeline_loss_fn(cfg, mesh: Mesh, *, rules=None, num_microbatches: int = 4,
                     shift_inputs: bool = False):
    """Build loss_fn(params, batch) running the decoder as a GPipe pipeline.

    Drop-in for models.transformer.loss_fn wherever the mesh has pipe>1;
    wire into ShardedTrainStep via train.step.transformer_train_step(...,
    pipeline_microbatches=M). ``shift_inputs`` selects the [B,S+1]-tokens
    convention (models.transformer.loss_fn docstring). MoE stacks thread
    their load-balancing aux loss through the schedule (bubble ticks
    masked out).
    """
    from ray_tpu.models import transformer as tfm

    rules = rules or shd.DEFAULT_RULES
    M = num_microbatches

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs = tokens[:, :-1] if shift_inputs else tokens
        B, S = inputs.shape
        if B % M != 0:
            raise ValueError(
                f"batch {B} not divisible by num_microbatches {M}")
        x = tfm.embed_tokens(params, inputs, cfg)  # [B, S, d]
        x = x.reshape(M, B // M, S, -1)
        y, aux = pipeline_apply(cfg, params["layers"], x, mesh, rules)
        y = y.reshape(B, S, -1)
        y = shd.maybe_constrain(y, ("batch", "seq_act", "embed"))
        logits = tfm.lm_head(params, y, cfg)
        if shift_inputs:
            targets, valid = tfm.shift_targets_valid(
                tokens, batch.get("mask"))
            loss = tfm.token_cross_entropy(logits, targets, valid)
        else:
            loss = tfm.next_token_loss(logits, batch)
        if cfg.moe_num_experts:
            loss = loss + cfg.moe_aux_coef * aux
        return loss

    return loss_fn
