"""Pipeline parallelism over the `pipe` mesh axis: a microbatched GPipe
schedule inside ONE jitted step.

SURVEY.md §5.7 names pipeline parallelism a first-class requirement; the
reference has no in-graph pipeline engine at all (its compiled-DAG pipelines
actors at the task layer, dag/compiled_dag_node.py:291 — a different altitude).
The TPU-native design runs the whole schedule inside XLA:

- The layer stack [L, ...] is sharded over `pipe` (logical axis "layers"),
  so each stage owns a contiguous block of L/P layers — zero repartitioning.
- shard_map makes the mesh manual; each device runs `lax.scan` over its
  local layers, and `lax.ppermute` hands activations to the next stage.
- The schedule is GPipe: with M microbatches and P stages it runs M+P-1
  ticks; bubbles compute garbage that output masking discards. Backward is
  plain autodiff through the scan — ppermute transposes to the reverse
  permutation, giving the symmetric backward pipeline for free.

Embedding and the LM head run OUTSIDE the shard_map in ordinary GSPMD land,
so vocab/fsdp sharding of those params keeps working unchanged.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel import sharding as shd


def _check_layer_specs_pipe_only(cfg, mesh: Mesh, rules) -> None:
    """The stage body runs _layer_body in plain (non-collective) form, so
    layer params may be sharded over `pipe` ONLY. Megatron-style manual TP
    inside the pipeline (psum after row-parallel matmuls) is not implemented
    — composing pipe with tensor/fsdp ON PARAMS must fail loudly, not
    silently all-gather and replicate compute."""
    from ray_tpu.models.transformer import param_logical_specs

    for spec in jax.tree.leaves(
        param_logical_specs(cfg)["layers"],
        is_leaf=lambda x: isinstance(x, tuple),
    ):
        mesh_spec = shd.logical_to_mesh_spec(spec, rules, mesh)
        extra = [a for a in jax.tree.leaves(tuple(mesh_spec)) if a != "pipe"]
        if extra:
            raise NotImplementedError(
                f"pipeline parallelism composes with data-parallel batch "
                f"sharding only; layer param spec {spec} maps onto mesh "
                f"axes {extra} (tensor/fsdp on params inside the pipeline "
                f"is not supported — use a mesh with those axes = 1)"
            )


def pipeline_apply(
    cfg,
    layers: Dict[str, jax.Array],
    x: jax.Array,  # [M, mb, S, d] microbatched activations
    mesh: Mesh,
    rules: Optional[Dict] = None,
) -> jax.Array:
    """Run the layer stack as a P-stage pipeline; returns [M, mb, S, d]."""
    from ray_tpu.models.transformer import layer_scan_body

    rules = rules or shd.DEFAULT_RULES
    num_stages = mesh.shape["pipe"]
    M, mb, S, d = x.shape
    num_ticks = M + num_stages - 1
    _check_layer_specs_pipe_only(cfg, mesh, rules)
    # Same mapping shard_batch/maybe_constrain use for the batch dim.
    mb_spec = shd.logical_to_mesh_spec(("batch",), rules, mesh)[0]

    layer_specs = jax.tree.map(lambda a: P("pipe"), layers)
    x_spec = P(None, mb_spec, None, None)
    out_spec = P("pipe", None, mb_spec, None, None)

    def body(layers_local, x_local):
        # x_local: [M, mb_local, S, d]; layers_local leaves: [L/P, ...]
        stage = lax.axis_index("pipe")
        positions = jnp.broadcast_to(
            jnp.arange(S, dtype=jnp.int32)[None], (x_local.shape[1], S))
        scan_body = layer_scan_body(cfg, positions)

        def run_local(h):
            with shd.no_sharding_ctx():
                out, _ = lax.scan(scan_body, h, layers_local)
            return out

        state0 = jnp.zeros(x_local.shape[1:], x_local.dtype)
        outputs0 = jnp.zeros_like(x_local)

        def tick(carry, t):
            state, outputs = carry
            inject = x_local[jnp.minimum(t, M - 1)]
            cur = jnp.where(stage == 0, inject, state)
            cur = run_local(cur)
            out_idx = t - (num_stages - 1)
            valid = (stage == num_stages - 1) & (out_idx >= 0)
            idx = jnp.clip(out_idx, 0, M - 1)
            outputs = outputs.at[idx].set(
                jnp.where(valid, cur, outputs[idx]))
            nxt = lax.ppermute(
                cur, "pipe",
                [(i, (i + 1) % num_stages) for i in range(num_stages)])
            return (nxt, outputs), None

        (_, outputs), _ = lax.scan(
            tick, (state0, outputs0), jnp.arange(num_ticks))
        # Stack per-stage buffers along a new leading axis; only the last
        # stage's buffer is real — the caller slices it out (pure data
        # movement, no collective).
        return outputs[None]

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_specs, x_spec),
        out_specs=out_spec,
        check_vma=False,
    )
    return fn(layers, x)[-1]


def pipeline_loss_fn(cfg, mesh: Mesh, *, rules=None, num_microbatches: int = 4,
                     shift_inputs: bool = False):
    """Build loss_fn(params, batch) running the decoder as a GPipe pipeline.

    Drop-in for models.transformer.loss_fn wherever the mesh has pipe>1;
    wire into ShardedTrainStep via train.step.transformer_train_step(...,
    pipeline_microbatches=M). ``shift_inputs`` selects the [B,S+1]-tokens
    convention (models.transformer.loss_fn docstring).
    """
    from ray_tpu.models import transformer as tfm

    rules = rules or shd.DEFAULT_RULES
    M = num_microbatches
    if getattr(cfg, "moe_num_experts", 0):
        raise NotImplementedError(
            "MoE under pipeline parallelism is not supported yet: the "
            "load-balancing aux loss would be silently dropped by the "
            "stage scan. Use expert parallelism (mesh expert axis) without "
            "pipe, or a dense config with pipe.")

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        inputs = tokens[:, :-1] if shift_inputs else tokens
        B, S = inputs.shape
        if B % M != 0:
            raise ValueError(
                f"batch {B} not divisible by num_microbatches {M}")
        x = tfm.embed_tokens(params, inputs, cfg)  # [B, S, d]
        x = x.reshape(M, B // M, S, -1)
        y = pipeline_apply(cfg, params["layers"], x, mesh, rules)
        y = y.reshape(B, S, -1)
        y = shd.maybe_constrain(y, ("batch", "seq_act", "embed"))
        logits = tfm.lm_head(params, y, cfg)
        if shift_inputs:
            targets, valid = tfm.shift_targets_valid(
                tokens, batch.get("mask"))
            return tfm.token_cross_entropy(logits, targets, valid)
        return tfm.next_token_loss(logits, batch)

    return loss_fn
