"""MPMD pipeline parallelism: stage actors owning disjoint meshes, wired
by compiled-DAG channels.

This is the actor-altitude counterpart of ``parallel.pipeline`` (which runs
a GPipe schedule INSIDE one XLA program over the `pipe` mesh axis). Here
each stage is a separate program — its own process, its own jax world, its
own (optional) device mesh — and microbatches flow stage-to-stage through
the mutable shared-memory / raw-stream channels that
``ray_tpu.dag.compiled_dag`` allocates at compile time. That buys what the
in-graph engine cannot express:

- Heterogeneous stages (different model code, different frameworks, or a
  CPU tokenizer feeding TPU decoders) — MPMD, not SPMD.
- Stages on disjoint device sets: each actor initializes its mesh from the
  chips the scheduler granted IT, so stage 0's collectives never contend
  with stage 2's.
- µs-scale steady-state dispatch: the driver writes one header per
  microbatch; the controller is out of the loop entirely, so the per-
  microbatch gap is bounded by stage compute + channel copy, not RPC.

Overlap comes from the compiled DAG's ``max_in_flight`` window: with W
in-flight microbatches, stage k runs microbatch i while stage k+1 runs
i-1 — the 1F1B-style steady state where every stage is busy once the
pipeline fills. ``run()`` records the completion gap per microbatch so
benchmarks can show the overlap directly (gap ≈ slowest-stage time, not
sum-of-stages).

Dry-runs on CPU: pass ``mesh_spec=None`` (the default) and stage factories
that ignore the mesh argument; nothing here imports jax unless a spec asks
for a mesh.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import ray_tpu
from ray_tpu.dag import InputNode

# A stage factory: called ONCE inside the stage actor at construction,
# returns the per-microbatch callable. Signature:
#     factory(stage_idx, num_stages, mesh) -> (x -> y)
StageFactory = Callable[[int, int, Any], Callable[[Any], Any]]


@ray_tpu.remote
class _StageActor:
    """One pipeline stage: builds its mesh (if any) and its step callable
    once, then serves microbatches through the compiled-DAG channel loop."""

    def __init__(self, factory: StageFactory, stage_idx: int,
                 num_stages: int, mesh_spec: Any = None):
        self._idx = stage_idx
        self._n = num_stages
        self._mesh = None
        if mesh_spec is not None:
            # Deferred import: CPU dry-runs must not require jax devices.
            from ray_tpu.parallel import mesh as mesh_mod

            self._mesh = mesh_mod.make_mesh(mesh_spec)
        self._fn = factory(stage_idx, num_stages, self._mesh)

    def step(self, x):
        return self._fn(x)

    def describe(self) -> Dict[str, Any]:
        return {
            "stage": self._idx,
            "num_stages": self._n,
            "mesh": None if self._mesh is None else dict(self._mesh.shape),
        }


class MPMDPipeline:
    """N-stage actor pipeline compiled onto reusable channels.

    ``stage_factories[k]`` builds stage k's step callable (see
    ``StageFactory``). ``mesh_specs``/``stage_options`` are optional
    per-stage lists: a ``MeshSpec`` gives that stage its own device mesh,
    options dicts pass through to ``.options()`` (resources, chips, …) so
    stages land on disjoint hardware.

    ``recovery=True`` (the default) makes every stage restartable with
    periodic durable checkpoints, so a SIGKILLed stage worker or a drained
    node heals in place: the compiled DAG pauses, the controller restarts
    the stage from its checkpoint, only the affected channels are rebuilt,
    and retained microbatches replay exactly once. Explicit per-stage
    ``stage_options`` win over these defaults; pass ``recovery=False`` for
    PR-10-style fail-fast teardown semantics.
    """

    #: Per-stage defaults installed by ``recovery=True``: enough restart
    #: budget for repeated chaos, and a checkpoint cadence that bounds how
    #: much stage state a restart can lose.
    RECOVERY_STAGE_OPTIONS = {
        "max_restarts": 4,
        "max_task_retries": 1,
        "checkpoint_interval_s": 2.0,
    }

    def __init__(
        self,
        stage_factories: Sequence[StageFactory],
        *,
        max_in_flight: int = 8,
        mesh_specs: Optional[Sequence[Any]] = None,
        stage_options: Optional[Sequence[Optional[dict]]] = None,
        recovery: bool = True,
    ):
        if not stage_factories:
            raise ValueError("MPMDPipeline needs at least one stage")
        n = len(stage_factories)
        if mesh_specs is not None and len(mesh_specs) != n:
            raise ValueError("mesh_specs must match stage count")
        if stage_options is not None and len(stage_options) != n:
            raise ValueError("stage_options must match stage count")
        self.num_stages = n
        self.max_in_flight = max_in_flight
        self.recovery = bool(recovery)
        handles = []
        for i, factory in enumerate(stage_factories):
            cls = _StageActor
            opts = dict(self.RECOVERY_STAGE_OPTIONS) if recovery else {}
            opts.update((stage_options[i] if stage_options else None) or {})
            if opts:
                cls = cls.options(**opts)
            spec = mesh_specs[i] if mesh_specs else None
            handles.append(cls.remote(factory, i, n, spec))
        self._handles = handles
        # Query the stages BEFORE compiling: installing the channel plan
        # parks each actor's mailbox thread in the resident DAG loop, so
        # ordinary method calls would queue behind it until teardown.
        self.stage_info: List[Dict[str, Any]] = ray_tpu.get(
            [h.describe.remote() for h in handles], timeout=60)
        with InputNode() as inp:
            node = handles[0].step.bind(inp)
            for h in handles[1:]:
                node = h.step.bind(node)
        self._compiled = node.experimental_compile(
            max_in_flight=max_in_flight)
        #: "channels" when every edge got a shm ring / raw stream;
        #: "submit" when the flag is off or the graph fell back.
        self.mode = self._compiled._mode
        self.last_gaps_s: List[float] = []

    # -- execution ---------------------------------------------------------
    def submit(self, microbatch) -> Any:
        """Feed one microbatch; returns a ref. Blocks only when
        ``max_in_flight`` microbatches are already in the pipe."""
        return self._compiled.execute(microbatch)

    def run(self, microbatches: Sequence[Any], *,
            timeout: Optional[float] = 120.0) -> List[Any]:
        """Stream ``microbatches`` through the pipeline with the full
        in-flight window; returns outputs in order. Records the wall-clock
        gap between consecutive microbatch completions in
        ``self.last_gaps_s`` — in steady state the gap is the slowest
        stage's per-microbatch time, not the sum over stages."""
        refs = [self._compiled.execute(mb) for mb in microbatches]
        outs: List[Any] = []
        stamps: List[float] = []
        for r in refs:
            outs.append(r.get(timeout=timeout))
            stamps.append(time.perf_counter())
        self.last_gaps_s = [
            stamps[i] - stamps[i - 1] for i in range(1, len(stamps))]
        return outs

    @property
    def recoveries(self) -> int:
        """In-place recoveries the compiled plan has completed so far."""
        return getattr(self._compiled, "_recovery_count", 0)

    def gap_stats(self) -> Dict[str, Any]:
        """Summary of the last run's per-microbatch completion gaps.
        Steady-state gaps exclude the pipeline-fill ramp: the first
        ``num_stages - 1`` completions arrive while the pipe is filling.

        Re-based on the channel meter (RTPU_DAG_METER): the driver-side
        gap percentiles now ship alongside the cluster-side attribution —
        ``bottleneck`` names the stage whose compute+send saturation
        explains the steady-state gap, so the summary answers "WHY is the
        gap what it is", not just "what is it"."""
        gaps = self.last_gaps_s
        steady = gaps[self.num_stages - 1:] or gaps
        if not steady:
            return {"n": 0}
        s = sorted(steady)
        out: Dict[str, Any] = {
            "n": len(steady),
            "mean_us": sum(steady) / len(steady) * 1e6,
            "p50_us": s[len(s) // 2] * 1e6,
            "max_us": s[-1] * 1e6,
        }
        out.update(self.meter_stats())
        return out

    def meter_stats(self) -> Dict[str, Any]:
        """This pipeline's channel-meter rollup from the controller
        registry (state.list_compiled_dags): per-stage busy fractions,
        per-edge ring stats, steps/s, and the bottleneck verdict. Empty
        dict in submit mode, with RTPU_DAG_METER=0, or before the first
        out-of-band sample lands."""
        if self.mode != "channels":
            return {}
        try:
            from ray_tpu.util import state as state_api

            row = next((d for d in state_api.list_compiled_dags()
                        if d.get("dag_id") == self._compiled.dag_id), None)
        except Exception:
            row = None
        if not row:
            return {}
        out: Dict[str, Any] = {}
        for key in ("stage_busy", "edge_stats", "steps_per_s",
                    "bottleneck"):
            v = row.get(key)
            if v:
                out[key] = v
        bn = out.get("bottleneck")
        if bn:
            try:
                idx = int(bn[1:])
                out["bottleneck_stage"] = idx
            except (ValueError, IndexError):
                pass
        return out

    def describe(self) -> List[Dict[str, Any]]:
        """One dict per stage (stage idx, mesh shape), captured at
        construction — the live actors can't be queried while the compiled
        plan owns their mailbox threads."""
        return list(self.stage_info)

    def teardown(self, *, kill_actors: bool = True) -> None:
        # The pipeline created its stage actors itself (live handles, not
        # ClassNodes), so the compiled DAG doesn't own them — kill here.
        self._compiled.teardown(kill_actors=False)
        if kill_actors:
            for h in self._handles:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
