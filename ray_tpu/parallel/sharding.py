"""Logical-axis sharding rules: how params/activations map onto the mesh.

The reference delegates intra-model parallelism entirely to torch-ecosystem
libraries (SURVEY.md §5.7 — FSDP/DeepSpeed via Lightning strategies); here it
is a first-class library: every model tags its arrays with *logical* axis
names ("embed", "mlp", "heads", "batch", "seq", ...) and a rule table maps
logical axes → mesh axes per parallelism strategy. Changing strategy =
changing the rule table, never the model. This is the t5x/flax partitioning
idiom, which is the idiomatic TPU design (not a torch translation).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# A logical spec is a tuple of logical axis names (or None), one per dim.
LogicalSpec = Tuple[Optional[str], ...]
Rules = Dict[str, Union[str, Tuple[str, ...], None]]

# Rule tables per strategy. Values name mesh axes (see mesh.AXIS_ORDER).
# "batch" always shards over (data, fsdp) — fsdp acts as extra DP for
# activations, the standard ZeRO-3 trick.
_BATCH = ("data", "fsdp")

RULES_DP: Rules = {"batch": _BATCH}

RULES_FSDP: Rules = {
    "batch": _BATCH,
    # Params: shard the largest dim over fsdp (all-gathered per layer under
    # jit; XLA overlaps the gather with compute).
    "embed": "fsdp",
    "vocab": "tensor",
    "mlp": "tensor",
    "heads": "tensor",
    "kv_heads": "tensor",
}

RULES_TP: Rules = {
    "batch": _BATCH,
    "layers": "pipe",  # layer stack split across pipeline stages
    "vocab": "tensor",
    "mlp": "tensor",
    "heads": "tensor",
    "kv_heads": "tensor",
    "embed": "fsdp",
    "seq_act": "seq",  # activation sequence dim under context parallelism
    "expert": "expert",
}

DEFAULT_RULES = RULES_TP  # superset table; unused mesh axes are size-1


def logical_to_mesh_spec(logical: LogicalSpec, rules: Rules, mesh: Mesh) -> P:
    """Map a logical spec to a PartitionSpec, dropping axes the mesh doesn't
    have (or that have size 1 — avoids useless resharding)."""
    out = []
    used = set()
    for name in logical:
        if name is None:
            out.append(None)
            continue
        mapped = rules.get(name)
        if mapped is None:
            out.append(None)
            continue
        axes = (mapped,) if isinstance(mapped, str) else tuple(mapped)
        axes = tuple(
            a for a in axes if a in mesh.axis_names and mesh.shape[a] > 1 and a not in used
        )
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    # Trailing Nones can be dropped; keep them for clarity.
    return P(*out)


def named_sharding(mesh: Mesh, logical: LogicalSpec, rules: Optional[Rules] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh_spec(logical, rules or DEFAULT_RULES, mesh))


def tree_shardings(mesh: Mesh, logical_tree: Any, rules: Optional[Rules] = None) -> Any:
    """Map a pytree of LogicalSpecs to a pytree of NamedShardings."""
    rules = rules or DEFAULT_RULES
    return jax.tree.map(
        lambda spec: named_sharding(mesh, spec, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x),
    )


def constrain(x: jax.Array, mesh: Mesh, logical: LogicalSpec, rules: Optional[Rules] = None):
    """with_sharding_constraint by logical names (t5x's logical constraint)."""
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, logical, rules)
    )


def shard_batch(mesh: Mesh, batch: Any) -> Any:
    """Place a host batch onto the mesh, sharded over the batch axes."""
    def put(x):
        x = np.asarray(x)
        if x.ndim == 0:
            return jax.device_put(x, replicated(mesh))
        spec: LogicalSpec = ("batch",) + (None,) * (x.ndim - 1)
        return jax.device_put(x, named_sharding(mesh, spec))

    return jax.tree.map(put, batch)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# --------------------------------------------------------- sharding context
# Models call maybe_constrain() on activations; it is a no-op unless a trainer
# established a (mesh, rules) context around tracing. This keeps model code
# mesh-agnostic (same function runs single-chip and on a v5p-64 FSDP mesh).

import contextlib
import threading

_ctx = threading.local()


@contextlib.contextmanager
def sharding_ctx(mesh: Mesh, rules: Optional[Rules] = None):
    prev = getattr(_ctx, "val", None)
    _ctx.val = (mesh, rules or DEFAULT_RULES)
    try:
        yield
    finally:
        _ctx.val = prev


def current_sharding_ctx() -> Optional[Tuple[Mesh, Rules]]:
    return getattr(_ctx, "val", None)


@contextlib.contextmanager
def no_sharding_ctx():
    """Suspend the context (inside shard_map bodies, where the mesh is fully
    manual and with_sharding_constraint would be ill-formed)."""
    prev = getattr(_ctx, "val", None)
    _ctx.val = None
    try:
        yield
    finally:
        _ctx.val = prev


def maybe_constrain(x: jax.Array, logical: LogicalSpec) -> jax.Array:
    ctx = current_sharding_ctx()
    if ctx is None:
        return x
    mesh, rules = ctx
    return constrain(x, mesh, logical, rules)
