"""Device-mesh formation: the TPU-native replacement for process groups.

Where the reference bootstraps NCCL process groups per library (torch
dist.init_process_group in ray Train's _TorchBackend, torch/config.py:65-199;
cupy-NCCL groups in ray.util.collective nccl_collective_group.py:128), the
TPU-native design has ONE primitive: a `jax.sharding.Mesh` over the slice's
devices, with named axes for every parallelism dimension. XLA emits the
collectives; ICI carries them. This module owns mesh axis conventions and
construction, including multi-host formation parameters (the analog of
MASTER_ADDR handoff) and virtual CPU meshes for tests.

Axis conventions (orders chosen so the innermost/fastest axes map to ICI
neighbors; see the scaling-book recipe: mesh → shardings → XLA collectives):

    data  — pure data parallelism (gradient all-reduce)
    fsdp  — ZeRO-style parameter/optimizer sharding (all-gather + reduce-scatter)
    tensor— megatron-style intra-layer model parallelism
    seq   — sequence/context parallelism (ring attention neighbors)
    expert— MoE expert parallelism (all-to-all)
    pipe  — pipeline stages (ppermute microbatch handoff)
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER: Tuple[str, ...] = ("pipe", "data", "fsdp", "seq", "expert", "tensor")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout. -1 on at most one axis means "fill with
    remaining devices" (like torch DeviceMesh / t5x partitioning)."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def sizes(self) -> Dict[str, int]:
        return {
            "pipe": self.pipe,
            "data": self.data,
            "fsdp": self.fsdp,
            "seq": self.seq,
            "expert": self.expert,
            "tensor": self.tensor,
        }

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wildcards = [k for k, v in sizes.items() if v == -1]
        if len(wildcards) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wildcards:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh spec {sizes} needs {fixed} devices, have {n_devices}")
        return MeshSpec(**{k: sizes[k] for k in ("data", "fsdp", "tensor", "pipe", "seq", "expert")})

    @property
    def num_devices(self) -> int:
        return math.prod(self.sizes().values())


def make_mesh(
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with the canonical axis order.

    Device order matters for ICI locality: jax.devices() on TPU enumerates in
    physical torus order, so adjacent mesh coordinates along the trailing
    axes land on ICI neighbors. We keep that order (no shuffling) and put
    `tensor`/`expert`/`seq` innermost where the highest-bandwidth traffic is.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    spec = spec.resolve(len(devs))
    sizes = spec.sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.asarray(devs, dtype=object).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def single_device_mesh() -> Mesh:
    return make_mesh(MeshSpec(), devices=jax.devices()[:1])


def best_effort_spec(
    n_devices: int,
    *,
    want_fsdp: bool = False,
    want_tensor: int = 1,
) -> MeshSpec:
    """A sane default layout: tensor innermost, remainder to fsdp or data."""
    if n_devices % want_tensor != 0:
        raise ValueError(f"{n_devices} devices not divisible by tensor={want_tensor}")
    rest = n_devices // want_tensor
    if want_fsdp:
        return MeshSpec(fsdp=rest, tensor=want_tensor)
    return MeshSpec(data=rest, tensor=want_tensor)


@dataclasses.dataclass
class MeshBootstrap:
    """Parameters a multi-host world needs to form one mesh — the analog of
    the reference handing MASTER_ADDR/RANK to every torch worker
    (backend_executor.py:436 + torch/config.py:153-199). The Train layer puts
    one of these in each worker's env; workers call `initialize()` before any
    jax computation touches devices."""

    coordinator_address: str  # "host:port" of process 0
    num_processes: int
    process_id: int

    def initialize(self) -> None:
        if self.num_processes > 1:
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id,
            )
