"""Cross-process collectives (`ray.util.collective` parity).

Reference: python/ray/util/collective/collective.py (init_collective_group
:120, allreduce :258, GroupManager :40) with NCCL-via-cupy / pygloo backends
and a named-actor Rendezvous (nccl_collective_group.py:29).

TPU-native split, mirroring SURVEY.md §5.8's three planes:
- **In-mesh collectives** (the hot path) are NOT here: they are XLA psum /
  all_gather / reduce_scatter / all-to-all emitted from pjit/shard_map over
  the Mesh — see ray_tpu.parallel.mesh. Nothing in Python touches per-step
  bytes.
- **Host-level collectives** (this module) synchronize *processes* that are
  not in one XLA program: CPU train workers (DP gradient all-reduce in the
  MNIST smoke config), cross-slice barriers, weight broadcast to env-runners.
  Backend: a named rendezvous actor + the shared-memory object store — the
  structural analog of the reference's gloo path.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda xs: _tree_reduce(xs, np.add),
    "mean": lambda xs: _tree_scale(_tree_reduce(xs, np.add), 1.0 / len(xs)),
    "max": lambda xs: _tree_reduce(xs, np.maximum),
    "min": lambda xs: _tree_reduce(xs, np.minimum),
}


def _tree_reduce(trees: List[Any], op) -> Any:
    import jax

    out = trees[0]
    for t in trees[1:]:
        out = jax.tree.map(lambda a, b: op(np.asarray(a), np.asarray(b)), out, t)
    return out


def _tree_scale(tree: Any, s: float) -> Any:
    import jax

    return jax.tree.map(lambda a: np.asarray(a) * s, tree)


class _RoundError:
    """Picklable sentinel carrying a failed round's error to all ranks."""

    def __init__(self, msg: str):
        self.msg = msg


@ray_tpu.remote
class _RendezvousActor:
    """Barrier/reduce hub for one collective group. Methods run with
    max_concurrency == world_size so all ranks can block in one round
    together (threaded-actor pattern, reference: Rendezvous actor)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.rounds: Dict[Any, Dict[int, Any]] = {}
        self.results: Dict[Any, Any] = {}
        self.done_counts: Dict[Any, int] = {}

    def collect(self, key, rank: int, value, op: Optional[str]):
        """All-gather `value` from every rank; if `op` is set, reduce instead.

        A failure while producing the round's result is published to every
        waiting rank (as an exception sentinel) — otherwise ranks already
        parked in cv.wait() would hang forever.
        """
        with self.cv:
            slot = self.rounds.setdefault(key, {})
            if rank in slot:
                raise RuntimeError(f"rank {rank} contributed twice to round {key}")
            slot[rank] = value
            if len(slot) == self.world_size:
                ordered = [slot[r] for r in range(self.world_size)]
                try:
                    self.results[key] = _REDUCE_OPS[op](ordered) if op else ordered
                except Exception as e:  # noqa: BLE001 — publish to all ranks
                    self.results[key] = _RoundError(repr(e))
                self.done_counts[key] = 0
                self.cv.notify_all()
            else:
                while key not in self.results:
                    self.cv.wait()
            result = self.results[key]
            self.done_counts[key] += 1
            if self.done_counts[key] == self.world_size:
                del self.rounds[key], self.results[key], self.done_counts[key]
            if isinstance(result, _RoundError):
                raise RuntimeError(f"collective round {key} failed: {result.msg}")
            return result

    def ping(self):
        return True


class CollectiveGroup:
    def __init__(self, name: str, world_size: int, rank: int, actor):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._actor = actor
        self._round = 0

    def _next_key(self, tag: str) -> str:
        self._round += 1
        return f"{tag}:{self._round}"

    def allreduce(self, value, op: str = "sum"):
        """Reduce a numpy array (or pytree of arrays) across the group."""
        if op not in _REDUCE_OPS:
            raise ValueError(f"op must be one of {sorted(_REDUCE_OPS)}, got {op!r}")
        key = self._next_key("ar")
        return ray_tpu.get(self._actor.collect.remote(key, self.rank, value, op))

    def allgather(self, value) -> List[Any]:
        key = self._next_key("ag")
        return ray_tpu.get(self._actor.collect.remote(key, self.rank, value, None))

    def broadcast(self, value, src_rank: int = 0):
        key = self._next_key("bc")
        got = ray_tpu.get(
            self._actor.collect.remote(key, self.rank, value if self.rank == src_rank else None, None)
        )
        return got[src_rank]

    def reducescatter(self, value, op: str = "sum"):
        """Reduce then return this rank's equal slice along axis 0."""
        reduced = self.allreduce(value, op)
        arr = np.asarray(reduced)
        chunks = np.array_split(arr, self.world_size, axis=0)
        return chunks[self.rank]

    def barrier(self) -> None:
        key = self._next_key("bar")
        ray_tpu.get(self._actor.collect.remote(key, self.rank, None, None))


_groups: Dict[str, CollectiveGroup] = {}


def init_collective_group(
    world_size: int,
    rank: int,
    group_name: str = "default",
    backend: str = "shm",
) -> CollectiveGroup:
    """Join (rank 0: create) a collective group. Reference API:
    util/collective/collective.py:120."""
    actor_name = f"__rtpu_collective__{group_name}"
    if rank == 0:
        actor = _RendezvousActor.options(
            name=actor_name, max_concurrency=world_size + 1
        ).remote(world_size)
        ray_tpu.get(actor.ping.remote())
    else:
        actor = _wait_for_actor(actor_name)
    group = CollectiveGroup(group_name, world_size, rank, actor)
    _groups[group_name] = group
    return group


def _wait_for_actor(name: str, timeout: float = 60.0):
    import time

    deadline = time.monotonic() + timeout
    while True:
        try:
            return ray_tpu.get_actor(name)
        except Exception:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.1)


def get_group(group_name: str = "default") -> CollectiveGroup:
    return _groups[group_name]


def allreduce(value, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(value, op)


def allgather(value, group_name: str = "default"):
    return get_group(group_name).allgather(value)


def broadcast(value, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(value, src_rank)


def barrier(group_name: str = "default") -> None:
    get_group(group_name).barrier()


def destroy_collective_group(group_name: str = "default") -> None:
    group = _groups.pop(group_name, None)
    if group is not None and group.rank == 0:
        try:
            ray_tpu.kill(group._actor)
        except Exception:
            pass
