"""Callbacks + built-in loggers.

Parity: reference tune/callback.py (Callback hooks) and tune/logger/
(CSVLoggerCallback, JsonLoggerCallback) — per-trial progress.csv,
result.json (jsonl) and params.json files in the trial dir, the layout
analysis tools expect.
"""
from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Optional, TextIO


class Callback:
    def on_experiment_start(self, controller) -> None:
        pass

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def on_experiment_end(self, controller) -> None:
        pass


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


class JsonLoggerCallback(Callback):
    """Appends each result as a JSON line to <trial_dir>/result.json and
    writes params.json once."""

    def __init__(self):
        self._files: Dict[str, TextIO] = {}

    def _ensure(self, trial) -> Optional[TextIO]:
        if not trial.local_dir:
            return None
        f = self._files.get(trial.trial_id)
        if f is None:
            with open(os.path.join(trial.local_dir, "params.json"), "w") as pf:
                json.dump(trial.config, pf, default=str)
            f = open(os.path.join(trial.local_dir, "result.json"), "a")
            self._files[trial.trial_id] = f
        return f

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        f = self._ensure(trial)
        if f:
            f.write(json.dumps(result, default=str) + "\n")
            f.flush()

    def on_experiment_end(self, controller) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()


class CSVLoggerCallback(Callback):
    """Appends flattened results to <trial_dir>/progress.csv.

    Buffers rows in memory and rewrites the file whenever a new metric key
    first appears, so late-appearing columns aren't dropped; appends to an
    existing file (experiment restore) only when its header still matches.
    """

    def __init__(self):
        # trial_id -> {"path", "fields": [..], "rows": [...], "file": f|None}
        self._state: Dict[str, Dict[str, Any]] = {}

    def _rewrite(self, st: Dict[str, Any]) -> None:
        if st["file"] is not None:
            st["file"].close()
        f = open(st["path"], "w", newline="")
        w = csv.DictWriter(f, fieldnames=st["fields"], restval="")
        w.writeheader()
        for row in st["rows"]:
            w.writerow(row)
        st["file"] = f

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        if not trial.local_dir:
            return
        flat = _flatten(result)
        st = self._state.get(trial.trial_id)
        if st is None:
            path = os.path.join(trial.local_dir, "progress.csv")
            st = {"path": path, "fields": list(flat.keys()), "rows": [],
                  "file": None}
            if os.path.exists(path):
                # Resumed trial: keep prior rows so restore doesn't truncate
                # history (result.json appends; the two must stay in sync).
                try:
                    with open(path, newline="") as old:
                        reader = csv.DictReader(old)
                        if reader.fieldnames:
                            st["fields"] = list(reader.fieldnames)
                            st["rows"] = list(reader)
                except Exception:
                    pass
            self._state[trial.trial_id] = st
        new_keys = [k for k in flat if k not in st["fields"]]
        st["rows"].append(flat)
        if new_keys or st["file"] is None:
            st["fields"].extend(new_keys)
            self._rewrite(st)
        else:
            csv.DictWriter(st["file"], fieldnames=st["fields"],
                           restval="", extrasaction="ignore").writerow(flat)
        st["file"].flush()

    def on_experiment_end(self, controller) -> None:
        for st in self._state.values():
            if st["file"] is not None:
                st["file"].close()
        self._state.clear()


class TensorBoardLoggerCallback(Callback):
    """Per-trial TensorBoard event files under <trial_dir>/ (reference:
    tune/logger/tensorboardx.py TBXLoggerCallback; writer is the
    dependency-free util/tensorboard.py — the image ships no tensorboardX).
    Steps use the result's training_iteration when present."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}
        self._steps: Dict[str, int] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        if not trial.local_dir:
            return
        w = self._writers.get(trial.trial_id)
        if w is None:
            from ray_tpu.util.tensorboard import EventFileWriter

            w = self._writers[trial.trial_id] = EventFileWriter(
                trial.local_dir)
        step = result.get("training_iteration")
        if not isinstance(step, int):
            step = self._steps.get(trial.trial_id, 0) + 1
        self._steps[trial.trial_id] = step
        w.add_scalars(_flatten(result), step=step)

    def on_experiment_end(self, controller) -> None:
        for w in self._writers.values():
            w.close()
        self._writers.clear()
