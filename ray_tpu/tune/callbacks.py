"""Callbacks + built-in loggers.

Parity: reference tune/callback.py (Callback hooks) and tune/logger/
(CSVLoggerCallback, JsonLoggerCallback) — per-trial progress.csv,
result.json (jsonl) and params.json files in the trial dir, the layout
analysis tools expect.
"""
from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Optional, TextIO


class Callback:
    def on_experiment_start(self, controller) -> None:
        pass

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def on_experiment_end(self, controller) -> None:
        pass


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


class JsonLoggerCallback(Callback):
    """Appends each result as a JSON line to <trial_dir>/result.json and
    writes params.json once."""

    def __init__(self):
        self._files: Dict[str, TextIO] = {}

    def _ensure(self, trial) -> Optional[TextIO]:
        if not trial.local_dir:
            return None
        f = self._files.get(trial.trial_id)
        if f is None:
            with open(os.path.join(trial.local_dir, "params.json"), "w") as pf:
                json.dump(trial.config, pf, default=str)
            f = open(os.path.join(trial.local_dir, "result.json"), "a")
            self._files[trial.trial_id] = f
        return f

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        f = self._ensure(trial)
        if f:
            f.write(json.dumps(result, default=str) + "\n")
            f.flush()

    def on_experiment_end(self, controller) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()


class CSVLoggerCallback(Callback):
    """Appends flattened results to <trial_dir>/progress.csv."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}

    def on_trial_result(self, trial, result: Dict[str, Any]) -> None:
        if not trial.local_dir:
            return
        flat = _flatten(result)
        entry = self._writers.get(trial.trial_id)
        if entry is None:
            f = open(os.path.join(trial.local_dir, "progress.csv"), "w", newline="")
            w = csv.DictWriter(f, fieldnames=list(flat.keys()), extrasaction="ignore")
            w.writeheader()
            entry = (f, w)
            self._writers[trial.trial_id] = entry
        f, w = entry
        w.writerow(flat)
        f.flush()

    def on_experiment_end(self, controller) -> None:
        for f, _ in self._writers.values():
            f.close()
        self._writers.clear()
