"""Trainable: the unit of execution Tune schedules.

Parity: reference tune/trainable/trainable.py (class API: setup/step/
save_checkpoint/load_checkpoint, driven by train()/save()/restore()) and
tune/trainable/function_trainable.py (function API: user fn runs on its own
thread, `tune.report(...)` hands results to the controller one step at a
time). `wrap_trainer_as_trainable` is the Train->Tune glue the reference
builds in base_trainer._generate_trainable_cls (:693).
"""
from __future__ import annotations

import inspect
import os
import pickle
import queue
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

RESULT_DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class API: subclass and override setup/step/save_checkpoint/load_checkpoint."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self._iteration = 0
        self._start_time = time.time()
        self.setup(self.config)

    # -------------------------------------------------------------- overrides

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        """Write state into checkpoint_dir."""
        raise NotImplementedError

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        raise NotImplementedError

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """In-place config swap (PBT fast path). Return True if handled."""
        return False

    # ------------------------------------------------------------ driver API

    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault("time_total_s", time.time() - self._start_time)
        result.setdefault(RESULT_DONE, False)
        return result

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        d = checkpoint_dir or tempfile.mkdtemp(prefix="rtpu_trial_ckpt_")
        os.makedirs(d, exist_ok=True)
        self.save_checkpoint(d)
        with open(os.path.join(d, ".tune_metadata.pkl"), "wb") as f:
            pickle.dump({"iteration": self._iteration}, f)
        return d

    def restore(self, checkpoint_path: str) -> None:
        self.load_checkpoint(checkpoint_path)
        meta = os.path.join(checkpoint_path, ".tune_metadata.pkl")
        if os.path.exists(meta):
            with open(meta, "rb") as f:
                self._iteration = pickle.load(f)["iteration"]

    def reset(self, new_config: Dict[str, Any]) -> bool:
        if self.reset_config(new_config):
            self.config = dict(new_config)
            return True
        return False

    def stop(self) -> None:
        self.cleanup()


# ---------------------------------------------------------------- function API

_session = threading.local()


def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None) -> None:
    """Called from inside a function trainable (reference: tune.report /
    ray.train.report under Tune)."""
    sess = getattr(_session, "current", None)
    if sess is None:
        raise RuntimeError("tune.report() called outside a Tune function trainable")
    sess.put(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    sess = getattr(_session, "current", None)
    return sess.restore_checkpoint if sess else None


class _FnSession:
    def __init__(self, restore_checkpoint: Optional[Checkpoint]):
        self.results: "queue.Queue[Any]" = queue.Queue()
        self.resume: "queue.Queue[None]" = queue.Queue()
        self.restore_checkpoint = restore_checkpoint

    def put(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint]) -> None:
        self.results.put((dict(metrics), checkpoint))
        self.resume.get()  # block until the driver consumed it (backpressure)


class FunctionTrainable(Trainable):
    """Adapts `def train_fn(config)` to the class API via a worker thread."""

    _fn: Callable = None  # set by subclass factory

    def setup(self, config: Dict[str, Any]) -> None:
        self._sess = _FnSession(restore_checkpoint=None)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._latest_checkpoint: Optional[Checkpoint] = None
        self._last_metrics: Dict[str, Any] = {}

    def _runner(self) -> None:
        _session.current = self._sess
        try:
            fn = type(self)._fn
            sig = inspect.signature(fn)
            if len(sig.parameters) >= 1:
                fn(self.config)
            else:
                fn()
        except BaseException as e:  # surfaced on the next train()
            self._error = e
        finally:
            self._sess.results.put(None)  # sentinel: function returned

    def step(self) -> Dict[str, Any]:
        if self._thread is None:
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()
        item = self._sess.results.get()
        if item is None:
            if self._error is not None:
                raise self._error
            # Terminal result keeps the last reported metrics (reference:
            # function_trainable delivers the final report with done=True).
            final = dict(self._last_metrics)
            final[RESULT_DONE] = True
            return final
        metrics, checkpoint = item
        if checkpoint is not None:
            self._latest_checkpoint = checkpoint
        self._sess.resume.put(None)
        metrics.setdefault(RESULT_DONE, False)
        self._last_metrics = dict(metrics)
        return metrics

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        if self._latest_checkpoint is not None:
            self._latest_checkpoint.to_directory(checkpoint_dir)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        self._sess.restore_checkpoint = Checkpoint.from_directory(checkpoint_dir)


def wrap_function(fn: Callable) -> type:
    """Build a FunctionTrainable subclass bound to `fn`."""
    name = getattr(fn, "__name__", "fn")
    return type(f"FnTrainable_{name}", (FunctionTrainable,), {"_fn": staticmethod(fn)})


def wrap_trainer_as_trainable(trainer) -> type:
    """Train->Tune glue (reference base_trainer._generate_trainable_cls:693):
    a trial runs `trainer.fit()` with the trial's config merged into
    train_loop_config. Each rank-0 `train.report` inside the fit streams to
    the Tune controller as an intermediate result (so ASHA/PBT can act
    mid-trial), and the final result carries the best checkpoint."""
    import copy

    def _trainable_fn(config: Dict[str, Any]) -> None:
        t = copy.copy(trainer)
        merged = dict(t.train_loop_config or {})
        merged.update(config.get("train_loop_config", config))
        t.train_loop_config = merged
        t._tune_report_hook = lambda item: report(
            {**item["metrics"], "training_iteration": item["iteration"]})
        result = t.fit()
        report(dict(result.metrics), checkpoint=result.checkpoint)

    return wrap_function(_trainable_fn)


def resolve_trainable(trainable) -> type:
    """Accept a class or function; normalize to a Trainable class."""
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if callable(trainable):
        return wrap_function(trainable)
    if hasattr(trainable, "as_trainable"):
        return trainable.as_trainable()
    raise TypeError(f"not a trainable: {trainable!r}")
