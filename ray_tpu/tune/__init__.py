"""ray_tpu.tune — experiment runner (SURVEY.md §2.5, §7 step 7).

Hosts trainers and RL algorithms as trials: Tuner → TuneController → trial
actors, with searchers (grid/random + pluggable Searcher) and schedulers
(FIFO/ASHA/MedianStopping/PBT). reference: python/ray/tune.
"""
from .callbacks import (Callback, CSVLoggerCallback, JsonLoggerCallback,
                        TensorBoardLoggerCallback)
from .experiment import Trial
from .schedulers import (
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from .search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Searcher,
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    randn,
    uniform,
)
from .trainable import FunctionTrainable, Trainable, get_checkpoint, report
from .tuner import ResultGrid, TrialResult, TuneConfig, Tuner, run, with_resources

__all__ = [
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "Callback",
    "ConcurrencyLimiter",
    "CSVLoggerCallback",
    "FIFOScheduler",
    "FunctionTrainable",
    "JsonLoggerCallback",
    "TensorBoardLoggerCallback",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "Trainable",
    "Trial",
    "TrialResult",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "loguniform",
    "qrandint",
    "randint",
    "randn",
    "report",
    "run",
    "uniform",
    "with_resources",
]
