"""Searcher interface + ConcurrencyLimiter.

Parity: reference tune/search/searcher.py (Searcher.suggest/on_trial_result/
on_trial_complete, save/restore) and concurrency_limiter.py. External
optimizers (Optuna/HyperOpt/...) plug in behind this interface exactly as in
the reference; BasicVariantGenerator is the built-in default.
"""
from __future__ import annotations

from typing import Any, Dict, Optional


class Searcher:
    """Suggests configs; observes results. Subclasses implement `suggest`."""

    FINISHED = "FINISHED"

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config, None to wait, or Searcher.FINISHED."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(
        self, trial_id: str, result: Optional[Dict[str, Any]] = None, error: bool = False
    ) -> None:
        pass

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(metric=searcher.metric, mode=searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != Searcher.FINISHED:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)

    def get_state(self):
        return {"inner": self.searcher.get_state()}

    def set_state(self, state):
        self.searcher.set_state(state.get("inner", {}))
