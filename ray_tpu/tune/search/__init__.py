"""Search: spaces, variant generation, searcher interface."""
from .sample import (
    Categorical,
    Domain,
    Float,
    Integer,
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    randn,
    uniform,
)
from .searcher import ConcurrencyLimiter, Searcher
from .basic_variant import BasicVariantGenerator
from .tpe import TPESearcher

__all__ = [
    "BasicVariantGenerator",
    "TPESearcher",
    "Categorical",
    "ConcurrencyLimiter",
    "Domain",
    "Float",
    "Integer",
    "Searcher",
    "choice",
    "grid_search",
    "loguniform",
    "qrandint",
    "randint",
    "randn",
    "uniform",
]
