"""Search-space primitives.

Parity: reference python/ray/tune/search/sample.py (Domain/Float/Integer/
Categorical + sampler attachment) — the public helpers `tune.uniform`,
`tune.loguniform`, `tune.choice`, `tune.randint`, `tune.qrandint`,
`tune.randn`, `tune.grid_search` used inside `param_space` dicts.
"""
from __future__ import annotations

import random
from typing import Any, Dict, List, Sequence


class Domain:
    """A dimension of the search space that knows how to draw a sample."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    # PBT-style perturbation support: resample by default.
    def perturb(self, value: Any, rng: random.Random) -> Any:
        return self.sample(rng)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log = float(lower), float(upper), log

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math

            return math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)

    def perturb(self, value: Any, rng: random.Random) -> float:
        factor = rng.choice([0.8, 1.2])
        return min(self.upper, max(self.lower, float(value) * factor))


class Integer(Domain):
    def __init__(self, lower: int, upper: int, q: int = 1):
        self.lower, self.upper, self.q = int(lower), int(upper), int(q)

    def sample(self, rng: random.Random) -> int:
        v = rng.randrange(self.lower, self.upper)
        return max(self.lower, (v // self.q) * self.q)

    def perturb(self, value: Any, rng: random.Random) -> int:
        factor = rng.choice([0.8, 1.2])
        v = int(round(int(value) * factor))
        return min(self.upper - 1, max(self.lower, (v // self.q) * self.q))


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)

    def perturb(self, value: Any, rng: random.Random) -> Any:
        # Move to a neighboring category (reference pbt.py explore behavior).
        try:
            i = self.categories.index(value)
        except ValueError:
            return self.sample(rng)
        j = max(0, min(len(self.categories) - 1, i + rng.choice([-1, 1])))
        return self.categories[j]


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = float(mean), float(sd)

    def sample(self, rng: random.Random) -> float:
        return rng.gauss(self.mean, self.sd)


# ------------------------------------------------------------- public helpers


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    """Marker dict, expanded as a cross-product by the variant generator
    (reference: tune/search/variant_generator.py grid handling)."""
    return {"grid_search": list(values)}


def is_grid(spec: Any) -> bool:
    return isinstance(spec, dict) and set(spec.keys()) == {"grid_search"}
