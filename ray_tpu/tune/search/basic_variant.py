"""BasicVariantGenerator: grid cross-product x random sampling.

Parity: reference tune/search/basic_variant.py (grid expansion + num_samples
repetition; each `grid_search` key multiplies the variant count, Domain values
are drawn per variant). Nested dicts in param_space are traversed; values that
are Domains are sampled, `grid_search` markers are expanded, callables are
invoked with the resolved spec, and plain values pass through.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .sample import Domain, is_grid
from .searcher import Searcher


def _walk(spec: Any, path: Tuple[str, ...] = ()) -> Iterator[Tuple[Tuple[str, ...], Any]]:
    if isinstance(spec, dict) and not is_grid(spec):
        for k, v in spec.items():
            yield from _walk(v, path + (str(k),))
    else:
        yield path, spec


def _set_path(d: Dict[str, Any], path: Tuple[str, ...], value: Any) -> None:
    cur = d
    for p in path[:-1]:
        cur = cur.setdefault(p, {})
    cur[path[-1]] = value


def generate_variants(
    param_space: Dict[str, Any], num_samples: int, seed: Optional[int] = None
) -> List[Dict[str, Any]]:
    """Fully materialize the variant list (grids x num_samples draws)."""
    rng = random.Random(seed)
    grid_items: List[Tuple[Tuple[str, ...], List[Any]]] = []
    other_items: List[Tuple[Tuple[str, ...], Any]] = []
    for path, leaf in _walk(param_space):
        if is_grid(leaf):
            grid_items.append((path, leaf["grid_search"]))
        else:
            other_items.append((path, leaf))

    grids = [vals for _, vals in grid_items] or [[None]]
    variants: List[Dict[str, Any]] = []
    for _ in range(num_samples):
        for combo in itertools.product(*grids):
            cfg: Dict[str, Any] = {}
            if grid_items:
                for (path, _), val in zip(grid_items, combo):
                    _set_path(cfg, path, val)
            deferred = []
            for path, leaf in other_items:
                if isinstance(leaf, Domain):
                    _set_path(cfg, path, leaf.sample(rng))
                elif callable(leaf):
                    deferred.append((path, leaf))  # lambdas see the resolved spec
                else:
                    _set_path(cfg, path, leaf)
            for path, fn in deferred:
                _set_path(cfg, path, fn(cfg))
            variants.append(cfg)
    return variants


class BasicVariantGenerator(Searcher):
    """Default searcher: pre-materialized grid/random variants."""

    def __init__(
        self,
        param_space: Optional[Dict[str, Any]] = None,
        num_samples: int = 1,
        metric: Optional[str] = None,
        mode: str = "max",
        seed: Optional[int] = None,
    ):
        super().__init__(metric=metric, mode=mode)
        self._queue: List[Dict[str, Any]] = (
            generate_variants(param_space or {}, num_samples, seed)
        )
        self._idx = 0

    @property
    def total_variants(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._queue):
            return Searcher.FINISHED
        cfg = self._queue[self._idx]
        self._idx += 1
        return cfg

    def get_state(self):
        return {"idx": self._idx, "queue": self._queue}

    def set_state(self, state):
        self._idx = state["idx"]
        self._queue = state["queue"]
