"""Tree-structured Parzen Estimator searcher — the built-in model-based
optimizer.

Parity: the reference ships model-based search via external libraries
(tune/search/hyperopt/, optuna/ — HyperOpt's core algorithm IS TPE); none
of those are in this image, so the algorithm itself lives here, dependency
free, behind the same Searcher interface (search/searcher.py).

Standard TPE (Bergstra et al., NeurIPS 2011): after ``n_initial`` random
trials, split observations at the ``gamma`` quantile of the metric into
good/bad sets; model each with Parzen windows (per-dimension Gaussian KDE
for Float/Integer — log-space when the domain is log — and smoothed
category frequencies for Categorical); draw candidates from the good
model and keep the one maximizing l_good(x)/l_bad(x). Dimensions are
modeled independently (the "tree" factorization over the flat space).
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from .basic_variant import _set_path, _walk
from .sample import Categorical, Domain, Float, Integer, Normal, is_grid
from .searcher import Searcher


class TPESearcher(Searcher):
    def __init__(
        self,
        space: Dict[str, Any],
        *,
        metric: str,
        mode: str = "max",
        n_initial: int = 10,
        gamma: float = 0.25,
        n_candidates: int = 24,
        max_trials: Optional[int] = None,
        seed: int = 0,
    ):
        super().__init__(metric=metric, mode=mode)
        self.space = space
        self.dims: List[Tuple[Tuple[str, ...], Domain]] = [
            (path, dom) for path, dom in _walk(space)
            if isinstance(dom, Domain)
        ]
        self.fixed: List[Tuple[Tuple[str, ...], Any]] = [
            (path, v) for path, v in _walk(space)
            if not isinstance(v, Domain)
        ]
        # grid_search markers and callable leaves only mean something to the
        # variant generator; passed through as "fixed" they would land
        # verbatim in trial configs — refuse upfront instead.
        for path, v in self.fixed:
            if is_grid(v):
                raise ValueError(
                    f"TPESearcher does not support grid_search (at "
                    f"{'.'.join(path)}); use tune.choice(...) so TPE can "
                    f"model the dimension")
            if callable(v):
                raise ValueError(
                    f"TPESearcher does not support callable/sample_from "
                    f"leaves (at {'.'.join(path)}); use a Domain from "
                    f"tune.search.sample")
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.max_trials = max_trials
        self.rng = random.Random(seed)
        self._suggested: Dict[str, Dict[str, Any]] = {}
        # (flat config values per dim, score) for completed trials
        self._obs: List[Tuple[List[Any], float]] = []
        self._count = 0

    # ----------------------------------------------------------- modeling

    def _split(self) -> Tuple[List[List[Any]], List[List[Any]]]:
        obs = sorted(self._obs, key=lambda o: o[1],
                     reverse=(self.mode == "max"))
        n_good = max(1, int(math.ceil(self.gamma * len(obs))))
        good = [o[0] for o in obs[:n_good]]
        bad = [o[0] for o in obs[n_good:]] or good
        return good, bad

    @staticmethod
    def _to_model_space(dom: Domain, v: Any) -> float:
        if isinstance(dom, Float) and dom.log:
            return math.log(v)
        return float(v)

    @staticmethod
    def _extent(dom: Domain) -> Tuple[float, float, bool]:
        """(lo, hi, bounded) of the domain in model space. Normal is
        unbounded; its +/-3sd prior extent only sizes the KDE bandwidth."""
        if isinstance(dom, Float) and dom.log:
            return math.log(dom.lower), math.log(dom.upper), True
        if isinstance(dom, (Float, Integer)):
            return float(dom.lower), float(dom.upper), True
        if isinstance(dom, Normal):
            return dom.mean - 3.0 * dom.sd, dom.mean + 3.0 * dom.sd, False
        raise TypeError(dom)

    def _kde_logpdf(self, dom: Domain, values: List[float], x: float) -> float:
        """Parzen window: mixture of Gaussians at observed values with a
        shared rule-of-thumb bandwidth over the domain extent."""
        lo, hi, _ = self._extent(dom)
        bw = max((hi - lo) / max(len(values) ** 0.5, 1.0), 1e-12)
        acc = 0.0
        for mu in values:
            z = (x - mu) / bw
            acc += math.exp(-0.5 * z * z)
        return math.log(max(acc / (len(values) * bw), 1e-300))

    def _cat_logp(self, dom: Categorical, values: List[Any], x: Any) -> float:
        k = len(dom.categories)
        counts = {c: 1.0 for c in dom.categories}  # +1 smoothing
        for v in values:
            counts[v] = counts.get(v, 1.0) + 1.0
        return math.log(counts[x] / (len(values) + k))

    def _score(self, cand: List[Any], good, bad) -> float:
        """log l(x|good) - log l(x|bad), factorized over dims."""
        s = 0.0
        for i, (_, dom) in enumerate(self.dims):
            if isinstance(dom, Categorical):
                s += (self._cat_logp(dom, [g[i] for g in good], cand[i])
                      - self._cat_logp(dom, [b[i] for b in bad], cand[i]))
            else:
                x = self._to_model_space(dom, cand[i])
                gv = [self._to_model_space(dom, g[i]) for g in good]
                bv = [self._to_model_space(dom, b[i]) for b in bad]
                s += (self._kde_logpdf(dom, gv, x)
                      - self._kde_logpdf(dom, bv, x))
        return s

    def _sample_from_good(self, good: List[List[Any]]) -> List[Any]:
        """Draw one candidate from the good model: pick a good observation
        per dim and jitter it by the bandwidth (Gaussian for numeric,
        frequency-weighted resample for categorical)."""
        cand: List[Any] = []
        for i, (_, dom) in enumerate(self.dims):
            anchor = self.rng.choice(good)[i]
            if isinstance(dom, Categorical):
                # Mostly keep; occasionally explore by frequency smoothing.
                if self.rng.random() < 1.0 / (len(good) + 1):
                    cand.append(dom.sample(self.rng))
                else:
                    cand.append(anchor)
                continue
            lo, hi, bounded = self._extent(dom)
            mu = self._to_model_space(dom, anchor)
            bw = max((hi - lo) / max(len(good) ** 0.5, 1.0), 1e-12)
            x = self.rng.gauss(mu, bw)
            if bounded:
                x = min(hi, max(lo, x))
            if isinstance(dom, Integer):
                v = int(round(x))
                v = max(dom.lower, min(dom.upper - 1, (v // dom.q) * dom.q))
                cand.append(v)
            elif isinstance(dom, Float) and dom.log:
                cand.append(math.exp(x))
            else:  # linear Float or unbounded Normal
                cand.append(x)
        return cand

    # ----------------------------------------------------------- Searcher

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self.max_trials is not None and self._count >= self.max_trials:
            return Searcher.FINISHED
        self._count += 1
        if len(self._obs) < self.n_initial or not self.dims:
            flat = [dom.sample(self.rng) for _, dom in self.dims]
        else:
            good, bad = self._split()
            cands = [self._sample_from_good(good)
                     for _ in range(self.n_candidates)]
            flat = max(cands, key=lambda c: self._score(c, good, bad))
        cfg: Dict[str, Any] = {}
        for (path, _), v in zip(self.dims, flat):
            _set_path(cfg, path, v)
        for path, v in self.fixed:
            _set_path(cfg, path, v)
        self._suggested[trial_id] = {"flat": flat}
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        info = self._suggested.pop(trial_id, None)
        if info is None or error or not result or self.metric not in result:
            return
        self._obs.append((info["flat"], float(result[self.metric])))

    def get_state(self):
        return {"obs": self._obs, "count": self._count,
                "rng": self.rng.getstate()}

    def set_state(self, state):
        self._obs = [(list(f), s) for f, s in state.get("obs", [])]
        self._count = state.get("count", 0)
        if "rng" in state:
            self.rng.setstate(tuple(
                tuple(x) if isinstance(x, list) else x
                for x in state["rng"]))
