"""Tuner + TuneConfig + ResultGrid — the public experiment API.

Parity: reference tune/tuner.py:344 (Tuner.fit), tune/tune_config.py,
tune/result_grid.py (get_best_result, get_dataframe), tuner restore
(tuner.py Tuner.restore — resumes unfinished trials from experiment state).
"""
from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig

from .callbacks import (Callback, CSVLoggerCallback, JsonLoggerCallback,
                        TensorBoardLoggerCallback)
from .experiment import ERROR, TERMINATED, Trial, load_experiment_state
from .schedulers import FIFOScheduler, TrialScheduler
from .search.basic_variant import BasicVariantGenerator
from .search.searcher import Searcher
from .trainable import resolve_trainable
from .tune_controller import TuneController


@dataclass
class TuneConfig:
    """reference tune/tune_config.py — experiment-wide knobs."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    seed: Optional[int] = None


@dataclass
class TrialResult:
    metrics: Dict[str, Any]
    config: Dict[str, Any]
    path: str
    checkpoint: Optional[Checkpoint]
    error: Optional[str] = None

    @property
    def metrics_dataframe(self):
        import pandas as pd

        import json

        p = os.path.join(self.path, "result.json")
        rows = []
        if os.path.exists(p):
            with open(p) as f:
                rows = [json.loads(line) for line in f if line.strip()]
        return pd.DataFrame(rows)


class ResultGrid:
    """reference tune/result_grid.py."""

    def __init__(self, results: List[TrialResult], metric: Optional[str], mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> TrialResult:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric or pass one)")
        candidates = [r for r in self._results if metric in r.metrics]
        if not candidates:
            raise RuntimeError(f"no trial reported metric {metric!r}")
        key: Callable = lambda r: r.metrics[metric]
        return max(candidates, key=key) if mode == "max" else min(candidates, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row.update({f"config/{k}": v for k, v in r.config.items()})
            row["trial_path"] = r.path
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(
        self,
        trainable: Union[type, Callable, Any],
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        _restore_path: Optional[str] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_path = _restore_path

    # ---------------------------------------------------------------- restore

    @classmethod
    def restore(cls, path: str, trainable: Union[type, Callable, Any]) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference tuner.py Tuner.restore)."""
        return cls(trainable, _restore_path=path)

    # -------------------------------------------------------------------- fit

    def _experiment_dir(self) -> str:
        if self._restore_path:
            return self._restore_path
        name = self.run_config.name or "tune_experiment"
        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "rtpu_results"
        )
        d = os.path.join(os.path.expanduser(base), name)
        os.makedirs(d, exist_ok=True)
        return d

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)

        tc = self.tune_config
        exp_dir = self._experiment_dir()

        restored_trials: List[Trial] = []
        searcher = tc.search_alg
        if self._restore_path:
            state = load_experiment_state(self._restore_path)
            if state:
                meta = state.get("meta", {})
                tc.metric = tc.metric or meta.get("metric")
                if meta.get("mode"):
                    tc.mode = meta["mode"]
                for td in state["trials"]:
                    t = Trial.from_json(td)
                    if t.status not in (TERMINATED, ERROR):
                        t.status = "PENDING"  # re-run unfinished work
                    restored_trials.append(t)
            searcher = searcher or BasicVariantGenerator(
                param_space={}, num_samples=0, metric=tc.metric, mode=tc.mode
            )
            if state and searcher is not None:
                try:
                    searcher.set_state(state.get("searcher", {}))
                except Exception:
                    pass
        if searcher is None:
            searcher = BasicVariantGenerator(
                param_space=self.param_space,
                num_samples=tc.num_samples,
                metric=tc.metric,
                mode=tc.mode,
                seed=tc.seed,
            )
        scheduler = tc.scheduler or FIFOScheduler(metric=tc.metric, mode=tc.mode)

        callbacks: List[Callback] = [JsonLoggerCallback(), CSVLoggerCallback(),
                             TensorBoardLoggerCallback()]
        if self.run_config.callbacks:
            callbacks.extend(self.run_config.callbacks)

        resources = getattr(self.trainable, "_tune_resources", None) or {"num_cpus": 1}

        trainable_cls = resolve_trainable(self.trainable)
        # Reference semantics (tune/impl/tuner_internal.py): unset
        # checkpoint_at_end defaults to True for the class API (which
        # implements save_checkpoint) and False for function trainables
        # (they report checkpoints in-band; forcing a save would produce
        # phantom empty checkpoint dirs).
        ckpt_at_end = self.run_config.checkpoint_config.checkpoint_at_end
        if ckpt_at_end is None:
            from .trainable import FunctionTrainable

            ckpt_at_end = not issubclass(trainable_cls, FunctionTrainable)

        controller = TuneController(
            trainable_cls,
            searcher,
            scheduler,
            exp_dir,
            metric=tc.metric,
            mode=tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            max_failures=self.run_config.failure_config.max_failures,
            checkpoint_freq=getattr(self.run_config.checkpoint_config, "checkpoint_frequency", 0),
            checkpoint_at_end=bool(ckpt_at_end),
            stop=self.run_config.stop,
            callbacks=callbacks,
            resources_per_trial=resources,
            trials=restored_trials,
            # The basic variant generator consumes num_samples itself
            # (grid_size x num_samples trials, then FINISHED) — capping it
            # at TuneConfig.num_samples (default 1) would drop its grid
            # variants. The controller-level cap is for OTHER user-supplied
            # searchers, which suggest forever (reference semantics:
            # num_samples bounds Optuna/HyperOpt searchers too).
            num_samples=(tc.num_samples
                         if tc.search_alg is not None
                         and not isinstance(tc.search_alg,
                                            BasicVariantGenerator)
                         else None),
        )
        trials = controller.run()

        results = [
            TrialResult(
                metrics=t.last_result,
                config=t.config,
                path=t.local_dir,
                checkpoint=(
                    Checkpoint.from_directory(t.checkpoint_path)
                    if t.checkpoint_path
                    else None
                ),
                error=t.error_msg,
            )
            for t in trials
        ]
        return ResultGrid(results, tc.metric, tc.mode)


def with_resources(trainable, resources: Dict[str, float]):
    """Attach per-trial resource requests (reference tune/trainable/util.py
    tune.with_resources)."""
    trainable._tune_resources = resources
    return trainable


def run(
    trainable,
    *,
    config: Optional[Dict[str, Any]] = None,
    num_samples: int = 1,
    metric: Optional[str] = None,
    mode: str = "max",
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    stop: Optional[Dict[str, Any]] = None,
    max_failures: int = 0,
    checkpoint_freq: int = 0,
    checkpoint_at_end: bool = False,
    name: Optional[str] = None,
    storage_path: Optional[str] = None,
    callbacks: Optional[list] = None,
    max_concurrent_trials: Optional[int] = None,
) -> ResultGrid:
    """Legacy `tune.run` facade over Tuner (reference tune/tune.py run())."""
    from ray_tpu.train.config import (CheckpointConfig, FailureConfig,
                                      RunConfig)

    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            scheduler=scheduler,
            search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
        ),
        run_config=RunConfig(
            name=name,
            storage_path=storage_path,
            stop=stop,
            callbacks=callbacks,
            failure_config=FailureConfig(max_failures=max_failures),
            checkpoint_config=CheckpointConfig(
                checkpoint_frequency=checkpoint_freq,
                checkpoint_at_end=checkpoint_at_end,
            ),
        ),
    )
    return tuner.fit()
