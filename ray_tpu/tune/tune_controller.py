"""TuneController: drives trial actors to completion.

Parity: reference tune/execution/tune_controller.py (step loop: start actors,
collect training results, apply scheduler decisions, retry failures, persist
experiment state) over ray_tpu core actors instead of RayActorManager. One
trial = one actor hosting the Trainable; `train()` calls stream results back
as futures.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.api import ActorHandle

from . import schedulers as sched
from .callbacks import Callback
from .experiment import (
    ERROR,
    PAUSED,
    PENDING,
    RUNNING,
    TERMINATED,
    Trial,
    save_experiment_state,
)
from .search.searcher import Searcher
from .trainable import RESULT_DONE

logger = logging.getLogger(__name__)


class _TrialRunner:
    """Hosts one Trainable inside an actor process."""

    def __init__(self, trainable_cls_pickled: bytes, config: Dict[str, Any]):
        import cloudpickle

        cls = cloudpickle.loads(trainable_cls_pickled)
        self._trainable = cls(config)

    def train(self) -> Dict[str, Any]:
        return self._trainable.train()

    def save(self, checkpoint_dir: str) -> str:
        return self._trainable.save(checkpoint_dir)

    def restore(self, checkpoint_path: str) -> None:
        self._trainable.restore(checkpoint_path)

    def reset(self, new_config: Dict[str, Any]) -> bool:
        return self._trainable.reset(new_config)

    def stop(self) -> None:
        self._trainable.stop()


class TuneController:
    def __init__(
        self,
        trainable_cls: type,
        searcher: Searcher,
        scheduler: sched.TrialScheduler,
        experiment_dir: str,
        *,
        metric: Optional[str] = None,
        mode: str = "max",
        max_concurrent: int = 0,
        max_failures: int = 0,
        checkpoint_freq: int = 0,
        checkpoint_at_end: bool = False,
        stop: Optional[Dict[str, Any]] = None,
        callbacks: Optional[List[Callback]] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        trials: Optional[List[Trial]] = None,
        num_samples: Optional[int] = None,
    ):
        import cloudpickle

        self.trainable_blob = cloudpickle.dumps(trainable_cls)
        self.searcher = searcher
        self.scheduler = scheduler
        self.metric = metric
        self.mode = mode
        self.experiment_dir = experiment_dir
        self.max_failures = max_failures
        self.checkpoint_freq = checkpoint_freq
        self.checkpoint_at_end = checkpoint_at_end
        # Trial-count cap applying to ANY searcher (reference semantics:
        # num_samples bounds Optuna/HyperOpt searchers too, not just the
        # basic variant generator). None falls back to the runaway
        # backstop.
        self.num_samples = num_samples
        self.stop_criteria = stop or {}
        self.callbacks = callbacks or []
        self.resources_per_trial = resources_per_trial or {"num_cpus": 1}
        if max_concurrent <= 0:
            cpus = ray_tpu.cluster_resources().get("CPU", 1)
            per = self.resources_per_trial.get("num_cpus", 1) or 1
            max_concurrent = max(1, int(cpus // per))
        self.max_concurrent = max_concurrent

        self.trials: List[Trial] = trials or []
        # Injected (restored) trials must still enter the scheduler's
        # population or PBT/ASHA silently ignore them.
        for t in self.trials:
            self.scheduler.on_trial_add(t)
        self._actors: Dict[str, ActorHandle] = {}
        self._inflight: Dict[Any, Trial] = {}  # ObjectRef -> trial
        self._searcher_done = False

    # ----------------------------------------------------------------- helpers

    def _trial_dir(self, trial: Trial) -> str:
        d = os.path.join(self.experiment_dir, trial.trial_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _make_actor(self, trial: Trial) -> ActorHandle:
        opts = dict(self.resources_per_trial)
        actor = ray_tpu.remote(_TrialRunner).options(**opts).remote(
            self.trainable_blob, trial.config
        )
        return actor

    def _start_trial(self, trial: Trial, restore_path: Optional[str] = None) -> None:
        trial.local_dir = self._trial_dir(trial)
        actor = self._make_actor(trial)
        if restore_path:
            try:
                ray_tpu.get(actor.restore.remote(restore_path))
            except Exception as e:
                # A broken/unreachable checkpoint is a *trial* failure, not an
                # experiment abort: count it against max_failures like any
                # other trial error (reference: trial-level FailureConfig).
                try:
                    ray_tpu.kill(actor)
                except Exception:
                    pass
                logger.exception(
                    "restore of trial %s from %s failed", trial.trial_id,
                    restore_path)
                trial.status = RUNNING  # so _handle_error's retry accounting runs
                self._handle_error(trial, e)
                return
        self._actors[trial.trial_id] = actor
        trial.status = RUNNING
        self._submit_train(trial)

    def _submit_train(self, trial: Trial) -> None:
        ref = self._actors[trial.trial_id].train.remote()
        self._inflight[ref] = trial

    def _kill_actor(self, trial: Trial, graceful: bool = True) -> None:
        actor = self._actors.pop(trial.trial_id, None)
        if actor is None:
            return
        if graceful:
            try:
                ray_tpu.get(actor.stop.remote(), timeout=5)
            except Exception:
                pass
        try:
            ray_tpu.kill(actor)
        except Exception:
            pass

    def _checkpoint_trial(self, trial: Trial) -> Optional[str]:
        actor = self._actors.get(trial.trial_id)
        if actor is None:
            return None
        n = trial.iteration
        d = os.path.join(trial.local_dir, f"checkpoint_{n:06d}")
        try:
            path = ray_tpu.get(actor.save.remote(d))
            trial.checkpoint_path = path
            return path
        except Exception:
            logger.exception("checkpoint of trial %s failed", trial.trial_id)
            return None

    def _should_stop(self, result: Dict[str, Any]) -> bool:
        if result.get(RESULT_DONE):
            return True
        for k, v in self.stop_criteria.items():
            if k in result and result[k] >= v:
                return True
        return False

    # ------------------------------------------------------------ trial intake

    def _trial_cap(self) -> int:
        """num_samples when set, else the runaway backstop. Trial intake and
        the run loop's done-check MUST use the same cap or they diverge."""
        return self.num_samples or 10_000

    def _maybe_request_trials(self) -> None:
        while not self._searcher_done and len(self.trials) < self._trial_cap():
            live = sum(1 for t in self.trials if t.status in (PENDING, RUNNING, PAUSED))
            if live >= self.max_concurrent * 2:
                return
            import uuid

            tid = uuid.uuid4().hex[:8]
            cfg = self.searcher.suggest(tid)
            if cfg == Searcher.FINISHED:
                self._searcher_done = True
                return
            if cfg is None:
                return
            trial = Trial(config=cfg, trial_id=tid)
            self.trials.append(trial)
            self.scheduler.on_trial_add(trial)
            for cb in self.callbacks:
                cb.on_trial_start(trial)

    # ------------------------------------------------------------- result path

    def _complete(self, trial: Trial, result: Dict[str, Any], status: str) -> None:
        if status == TERMINATED and (self.checkpoint_at_end or self.checkpoint_freq):
            self._checkpoint_trial(trial)
        self._kill_actor(trial)
        trial.status = status
        self.scheduler.on_trial_complete(trial, result)
        self.searcher.on_trial_complete(trial.trial_id, result, error=False)
        for cb in self.callbacks:
            cb.on_trial_complete(trial)

    def _handle_result(self, trial: Trial, result: Dict[str, Any]) -> None:
        trial.record_result(result)
        for cb in self.callbacks:
            cb.on_trial_result(trial, result)
        self.searcher.on_trial_result(trial.trial_id, result)

        if self._should_stop(result):
            self._complete(trial, result, TERMINATED)
            return

        decision = self.scheduler.on_trial_result(trial, result)
        if decision == sched.STOP:
            self._complete(trial, result, TERMINATED)
        elif decision == sched.PAUSE:
            donor: Optional[Trial] = getattr(trial, "_pbt_donor", None)
            new_config: Optional[Dict] = getattr(trial, "_pbt_new_config", None)
            if donor is not None and new_config is not None:
                self._exploit(trial, donor, new_config)
            else:
                self._checkpoint_trial(trial)
                self._kill_actor(trial)
                trial.status = PAUSED
        else:
            if self.checkpoint_freq and trial.iteration % self.checkpoint_freq == 0:
                self._checkpoint_trial(trial)
            self._submit_train(trial)

    def _exploit(self, trial: Trial, donor: Trial, new_config: Dict[str, Any]) -> None:
        """PBT exploit+explore: replace trial's state with donor's checkpoint
        and a perturbed config (reference pbt.py _exploit)."""
        trial._pbt_donor = None  # type: ignore[attr-defined]
        trial._pbt_new_config = None  # type: ignore[attr-defined]
        donor_ckpt = self._checkpoint_trial(donor) or donor.checkpoint_path
        if donor_ckpt is None:
            self._submit_train(trial)
            return
        # Drop any in-flight ref for this trial's old actor.
        self._inflight = {r: t for r, t in self._inflight.items() if t is not trial}
        self._kill_actor(trial, graceful=False)
        trial.config = new_config
        self._start_trial(trial, restore_path=donor_ckpt)

    def _handle_error(self, trial: Trial, err: BaseException) -> None:
        trial.num_failures += 1
        for cb in self.callbacks:
            cb.on_trial_error(trial)
        if self.max_failures < 0 or trial.num_failures <= self.max_failures:
            logger.warning(
                "trial %s failed (%s), retry %d/%d",
                trial.trial_id, err, trial.num_failures, self.max_failures,
            )
            self._kill_actor(trial, graceful=False)
            self._start_trial(trial, restore_path=trial.checkpoint_path)
            return
        self._kill_actor(trial, graceful=False)
        trial.status = ERROR
        trial.error_msg = str(err)
        self.scheduler.on_trial_error(trial)
        self.searcher.on_trial_complete(trial.trial_id, None, error=True)
        for cb in self.callbacks:
            cb.on_trial_complete(trial)

    # -------------------------------------------------------------- main loop

    def step(self) -> bool:
        """One controller iteration; returns False when the experiment is done."""
        self._maybe_request_trials()

        running = [t for t in self.trials if t.status == RUNNING]
        pending = [t for t in self.trials if t.status == PENDING]
        paused = [t for t in self.trials if t.status == PAUSED]
        while pending and len(running) < self.max_concurrent:
            trial = self.scheduler.choose_trial_to_run(pending)
            if trial is None:
                break
            pending.remove(trial)
            # Restored trials resume from their last checkpoint rather than
            # retraining from scratch (reference: trial restore on resume).
            self._start_trial(trial, restore_path=trial.checkpoint_path)
            running.append(trial)
        # Resume paused trials when capacity allows.
        while paused and len(running) < self.max_concurrent:
            trial = paused.pop(0)
            self._start_trial(trial, restore_path=trial.checkpoint_path)
            running.append(trial)

        if not self._inflight:
            live = [t for t in self.trials if t.status in (PENDING, RUNNING, PAUSED)]
            # Done when nothing is live AND no further trial can be
            # requested — either the searcher said FINISHED or the
            # num_samples cap is reached (a searcher that never finishes,
            # e.g. TPE without max_trials, must not spin this loop forever).
            can_request = (not self._searcher_done
                           and len(self.trials) < self._trial_cap())
            return bool(live) or can_request

        refs = list(self._inflight.keys())
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=10.0)
        for ref in ready:
            trial = self._inflight.pop(ref)
            if trial.status != RUNNING:
                continue  # stale ref from a replaced actor
            try:
                result = ray_tpu.get(ref)
            except Exception as e:
                self._handle_error(trial, e)
                continue
            self._handle_result(trial, result)
        return True

    def run(self) -> List[Trial]:
        for cb in self.callbacks:
            cb.on_experiment_start(self)
        last_save = 0.0
        try:
            while self.step():
                if time.time() - last_save > 5:
                    save_experiment_state(
                        self.experiment_dir, self.trials, self.searcher.get_state(),
                        meta={"metric": self.metric, "mode": self.mode},
                    )
                    last_save = time.time()
        finally:
            for t in self.trials:
                if t.status == RUNNING:
                    self._kill_actor(t, graceful=False)
                    t.status = ERROR
                    t.error_msg = "experiment interrupted"
            save_experiment_state(
                self.experiment_dir, self.trials, self.searcher.get_state(),
                meta={"metric": self.metric, "mode": self.mode},
            )
            for cb in self.callbacks:
                cb.on_experiment_end(self)
        return self.trials
