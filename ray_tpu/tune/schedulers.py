"""Trial schedulers: FIFO, ASHA, MedianStopping, PBT.

Parity: reference tune/schedulers/ — trial_scheduler.py (decision protocol
CONTINUE/PAUSE/STOP), async_hyperband.py (ASHA rungs + reduction factor),
median_stopping_rule.py, pbt.py (exploit top quantile's checkpoint + explore
by perturbing hyperparams). Decisions are made per-result; the controller
enacts them.
"""
from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from .experiment import RUNNING, TERMINATED, Trial

CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def _score(self, value: float) -> float:
        """Normalize so larger is always better."""
        return value if self.mode == "max" else -value

    def on_trial_add(self, trial: Trial) -> None:
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_error(self, trial: Trial) -> None:
        pass

    def choose_trial_to_run(self, pending: List[Trial]) -> Optional[Trial]:
        return pending[0] if pending else None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference schedulers/async_hyperband.py _Bracket): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung is stopped
    unless it is in the top 1/reduction_factor of results recorded there."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: float = 3.0,
        max_t: int = 100,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        # rung milestones: grace, grace*rf, grace*rf^2, ... < max_t
        self.rungs: Dict[float, List[float]] = {}
        m = float(grace_period)
        while m < max_t:
            self.rungs[m] = []
            m *= reduction_factor
        self._next_rung: Dict[str, List[float]] = {}

    def on_trial_add(self, trial: Trial) -> None:
        self._next_rung[trial.trial_id] = sorted(self.rungs.keys())

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        pending_rungs = self._next_rung.setdefault(
            trial.trial_id, sorted(self.rungs.keys())
        )
        while pending_rungs and t >= pending_rungs[0]:
            rung = pending_rungs.pop(0)
            recorded = self.rungs[rung]
            score = self._score(float(v))
            recorded.append(score)
            k = max(1, int(len(recorded) / self.rf))
            cutoff = sorted(recorded, reverse=True)[k - 1]
            if score < cutoff:
                # Cut at the first failed rung; don't pollute later rungs'
                # populations with a score the trial never legitimately
                # reached (it would drag their cutoffs down).
                return STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of the
    running averages of completed/running trials at the same step
    (reference schedulers/median_stopping_rule.py)."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is None:
            return CONTINUE
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(self._score(float(v)))
        if t < self.grace_period or len(self._avgs) < self.min_samples:
            return CONTINUE
        my_avg = sum(hist) / len(hist)
        others = [sum(h) / len(h) for tid, h in self._avgs.items() if tid != trial.trial_id]
        if not others:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        return STOP if my_avg < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference schedulers/pbt.py): every perturbation_interval, a
    bottom-quantile trial clones the checkpoint of a top-quantile trial
    (exploit) and perturbs its hyperparameters (explore). The controller reads
    the decision `PAUSE` + `trial._pbt_new_config/_pbt_donor` to enact the
    clone-and-restart."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[Dict[str, Any]] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
    ):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        self._population: List[Trial] = []

    def on_trial_add(self, trial: Trial) -> None:
        self._population.append(trial)

    def _quantiles(self) -> (List[Trial], List[Trial]):
        scored = [
            t
            for t in self._population
            if t.metric_value(self.metric) is not None and t.status == RUNNING
        ]
        if len(scored) < 2:
            return [], []
        scored.sort(key=lambda t: self._score(t.metric_value(self.metric)))
        n = max(1, int(math.ceil(len(scored) * self.quantile)))
        if n > len(scored) / 2:
            n = len(scored) // 2
        return scored[:n], scored[-n:] if n else []

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search.sample import Domain

        new = dict(config)
        for key, spec in self.mutations.items():
            cur = new.get(key)
            if isinstance(spec, list):
                if self.rng.random() < self.resample_p or cur not in spec:
                    new[key] = self.rng.choice(spec)
                else:
                    i = spec.index(cur)
                    j = max(0, min(len(spec) - 1, i + self.rng.choice([-1, 1])))
                    new[key] = spec[j]
            elif isinstance(spec, Domain):
                if self.rng.random() < self.resample_p:
                    new[key] = spec.sample(self.rng)
                else:
                    new[key] = spec.perturb(cur, self.rng)
            elif callable(spec):
                new[key] = spec()
        return new

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        bottom, top = self._quantiles()
        if trial in bottom and top:
            donor = self.rng.choice(top)
            # The controller checkpoints the donor on demand (its actor is
            # live); no need for a pre-existing checkpoint here.
            trial._pbt_donor = donor  # type: ignore[attr-defined]
            trial._pbt_new_config = self._explore(donor.config)  # type: ignore
            return PAUSE  # controller performs exploit+explore
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Dict[str, Any]) -> None:
        if trial in self._population:
            self._population.remove(trial)

    def on_trial_error(self, trial: Trial) -> None:
        if trial in self._population:
            self._population.remove(trial)
