"""Trial state machine + experiment bookkeeping.

Parity: reference tune/experiment/trial.py (Trial status PENDING/RUNNING/
PAUSED/TERMINATED/ERROR, checkpoint tracking, result log) — trimmed to the
fields the controller and schedulers actually consume.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    last_result: Dict[str, Any] = field(default_factory=dict)
    results: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error_msg: Optional[str] = None
    num_failures: int = 0
    resources: Dict[str, float] = field(default_factory=dict)
    local_dir: str = ""

    def metric_value(self, metric: str) -> Optional[float]:
        v = self.last_result.get(metric)
        return None if v is None else float(v)

    @property
    def iteration(self) -> int:
        return int(self.last_result.get("training_iteration", 0))

    def record_result(self, result: Dict[str, Any]) -> None:
        self.last_result = result
        self.results.append(result)

    # ------------------------------------------------------------ persistence

    def to_json(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "checkpoint_path": self.checkpoint_path,
            "error_msg": self.error_msg,
            "num_failures": self.num_failures,
            "local_dir": self.local_dir,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Trial":
        t = cls(config=d["config"], trial_id=d["trial_id"])
        t.status = d["status"]
        t.last_result = d.get("last_result", {})
        t.checkpoint_path = d.get("checkpoint_path")
        t.error_msg = d.get("error_msg")
        t.num_failures = d.get("num_failures", 0)
        t.local_dir = d.get("local_dir", "")
        return t


def save_experiment_state(
    path: str,
    trials: List[Trial],
    searcher_state: Dict,
    meta: Optional[Dict[str, Any]] = None,
) -> None:
    os.makedirs(path, exist_ok=True)
    tmp = os.path.join(path, ".experiment_state.tmp")
    with open(tmp, "w") as f:
        json.dump(
            {
                "timestamp": time.time(),
                "trials": [t.to_json() for t in trials],
                "searcher": searcher_state,
                "meta": meta or {},
            },
            f,
            default=str,
        )
    os.replace(tmp, os.path.join(path, "experiment_state.json"))


def load_experiment_state(path: str) -> Optional[Dict[str, Any]]:
    p = os.path.join(path, "experiment_state.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)
