"""Web dashboard: cluster overview UI + JSON API over the state surface.

Parity: reference dashboard head (dashboard/head.py + http_server_head.py,
modules under dashboard/modules/: node, actor, job, serve, state, metrics,
healthz, reporter). The reference runs a separate aiohttp process per
cluster plus a per-node agent; here one aiohttp server embeds in (or
attaches to) the driver process and reads everything through the same
controller RPC the state API uses — the controller is already the
aggregation point (its task-event buffer and Prometheus endpoint), so a
second aggregator daemon would be redundant at this scale. Per-node
cpu/mem comes from psutil sampled by the serving process for the local
host and from host-agent heartbeats for remote nodes.

Endpoints:
    GET /                    HTML overview (auto-refreshing)
    GET /api/cluster         resources + node table
    GET /api/nodes           state API list_nodes
    GET /api/actors          state API list_actors
    GET /api/tasks           state API list_tasks (+ ?summary=1,
                             ?breakdown=1 for per-phase latency p50/p99)
    GET /api/workers         state API list_workers
    GET /api/objects         state API list_objects
    GET /api/memory          cluster object census (?group_by, ?min_size,
                             ?limit) — the `rtpu memory` backend
    GET /objects             object census page (per-owner/tier/node/
                             callsite bytes + largest objects)
    GET /api/jobs            job list (ray_tpu.jobs)
    GET /api/serve           serve application status (if running)
    GET /api/serve_requests  per-request ledger (?model, ?status,
                             ?min_latency_s, ?since; ?request_id= adds
                             the hop-span waterfall)
    GET /api/timeline        chrome-trace events (open in chrome://tracing)
    GET /api/dags            compiled-DAG registry + channel-meter rollups
                             (stage busy fractions, edge ring stats,
                             steps/s, bottleneck verdict)
    GET /api/dag_timeline    per-stage step chrome trace with recv/
                             compute/send/blocked sub-slices (?dag=)
    GET /api/usage           local host cpu/mem (reporter_agent.py role)
    GET /api/logs            cluster log index (?all=1), one host's
                             list/tail (?node, ?name), or ranged /
                             task-attributed chunks (?task_id, ?actor_id,
                             ?worker_id, ?offset)
    GET /api/events          cluster event feed (?severity, ?kind,
                             ?task_id, ?actor_id, ?node, ?worker_id)
    GET /api/telemetry       metrics history series from the controller
                             TSDB (?name, ?prefix, ?since, ?stat,
                             ?window) — the overview sparkline backend
    GET /api/alerts          alert rules + currently-firing alerts
    GET /logs                log viewer page (live tail via /api/logs)
    GET /events              event feed page (hang events expose their
                             captured stacks)
    GET /serve-requests      request ledger page (?request_id= renders
                             one request's per-hop waterfall)
    GET /healthz             200 ok (dashboard/modules/healthz)
    GET /metrics             proxied controller Prometheus text
"""
from __future__ import annotations

import html
import json
import threading
from typing import Any, Optional

from ray_tpu.util import state as state_api


def _local_usage() -> dict:
    try:
        import psutil

        vm = psutil.virtual_memory()
        return {
            "cpu_percent": psutil.cpu_percent(interval=None),
            "mem_total": vm.total,
            "mem_used": vm.used,
            "mem_percent": vm.percent,
        }
    except Exception:
        return {}


_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a2e; }}
 h1 {{ font-size: 1.3rem; }} h2 {{ font-size: 1.05rem; margin-top: 1.5rem; }}
 table {{ border-collapse: collapse; width: 100%; font-size: .85rem; }}
 th, td {{ text-align: left; padding: .3rem .6rem; border-bottom: 1px solid #ddd; }}
 th {{ background: #f4f4f8; }}
 .pill {{ padding: .1rem .5rem; border-radius: 999px; font-size: .75rem; }}
 .ok {{ background: #e0f2e9; }} .bad {{ background: #fde2e2; }}
 code {{ background: #f4f4f8; padding: .05rem .3rem; }}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<p>{cluster}</p>
<p><a href="/logs">log viewer</a> · <a href="/timeline">timeline</a> ·
<a href="/events">events</a> · <a href="/objects">objects</a></p>
<h2>Nodes</h2>{nodes}
<h2>Compiled DAGs</h2>{dags}
<h2>Telemetry</h2>{telemetry}
<h2>Recent events</h2>{events}
<h2>Actors</h2>{actors}
<h2>Task summary</h2>{tasks}
<h2>Recent tasks</h2>{recent}
<h2>Jobs</h2>{jobs}
<p style="margin-top:2rem;color:#888">JSON under <code>/api/*</code>;
Prometheus at <code>/metrics</code>; timeline at
<code>/api/timeline</code>; logs at <code>/api/logs</code>.</p>
</body></html>"""


def _table(rows, cols, raw=()) -> str:
    if not rows:
        return "<p><i>none</i></p>"
    # Every cell is user-controlled data (actor names, job entrypoints,
    # labels) — escape or a crafted name is stored XSS in the viewer.
    # Columns in `raw` carry server-rendered HTML (log-viewer links built
    # from escaped values) and are trusted as-is.
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in cols)
    body = "".join(
        "<tr>"
        + "".join(
            f"<td>{r.get(c, '') if c in raw else html.escape(str(r.get(c, '')))}</td>"
            for c in cols)
        + "</tr>"
        for r in rows[:200]
    )
    return f"<table><tr>{head}</tr>{body}</table>"


def _fmt_ts(ts) -> str:
    import time as _time

    try:
        return _time.strftime("%H:%M:%S", _time.localtime(float(ts or 0)))
    except Exception:
        return "?"


def _sparkline(points, w: int = 220, h: int = 34) -> str:
    """Inline SVG polyline over [t, v] points — rendered server-side so
    the str.format overview template stays JS-free."""
    vals = [p[1] for p in points]
    if not vals:
        return "<i>no data</i>"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    n = len(vals)
    xs = [(i * (w - 2) / max(1, n - 1)) + 1 for i in range(n)]
    ys = [h - 2 - (v - lo) / span * (h - 4) for v in vals]
    pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(xs, ys))
    return (f'<svg width="{w}" height="{h}">'
            f'<polyline fill="none" stroke="#4e79a7" stroke-width="1.5" '
            f'points="{pts}"/></svg>')


def _log_link(param: str, value) -> str:
    from urllib.parse import quote

    if not value:
        return ""
    return (f'<a href="/logs?{param}={quote(str(value))}">logs</a>')


_TIMELINE_PAGE = """<!doctype html>
<html><head><title>ray_tpu task timeline</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.2rem; color: #1a1a2e; }
 h1 { font-size: 1.2rem; } .lane-label { font-size: 11px; fill: #555; }
 .slice { stroke: #fff; stroke-width: .5; cursor: pointer; }
 .slice:hover { opacity: .75; }
 #tip { position: fixed; background: #1a1a2e; color: #fff; padding: 4px 8px;
        border-radius: 4px; font-size: 12px; pointer-events: none;
        display: none; z-index: 10; }
 .axis { stroke: #ddd; } .axis-label { font-size: 10px; fill: #888; }
 #empty { color: #888; }
</style></head><body>
<h1>Task timeline <small style="color:#888">(per node / worker swimlanes;
 auto-refreshes)</small></h1>
<div id="tip"></div><div id="empty"></div>
<svg id="chart" width="100%" height="60"></svg>
<h1 style="margin-top:1.5rem">Latency breakdown <small style="color:#888">
(per label, flight-recorder phases)</small></h1>
<div id="breakdown" style="color:#888">no phase events yet</div>
<script>
const COLORS = ["#4e79a7","#f28e2b","#59a14f","#e15759","#b07aa1",
                "#76b7b2","#edc948","#ff9da7","#9c755f","#bab0ac"];
function colorFor(name) {
  let h = 0; for (const c of name) h = (h * 31 + c.charCodeAt(0)) >>> 0;
  return COLORS[h % COLORS.length];
}
async function draw() {
  const r = await fetch("/api/timeline"); const events = await r.json();
  const slices = events.filter(e => e.ph === "X");
  const empty = document.getElementById("empty");
  if (!slices.length) { empty.textContent =
      "no completed task spans yet — run some tasks and refresh"; return; }
  empty.textContent = "";
  const lanes = new Map();   // "pid/tid" -> row index
  for (const s of slices) {
    const key = s.pid + " / " + s.tid;
    if (!lanes.has(key)) lanes.set(key, lanes.size);
  }
  const t0 = Math.min(...slices.map(s => s.ts));
  const t1 = Math.max(...slices.map(s => s.ts + s.dur));
  const span = Math.max(t1 - t0, 1);
  const W = document.body.clientWidth - 40, LBL = 170, ROW = 22, TOP = 24;
  const svg = document.getElementById("chart");
  svg.setAttribute("height", TOP + lanes.size * ROW + 10);
  let parts = [];
  for (let i = 0; i <= 6; i++) {
    const x = LBL + (W - LBL) * i / 6;
    const t = (span * i / 6) / 1e6;
    parts.push(`<line class="axis" x1="${x}" y1="${TOP - 6}" x2="${x}"
      y2="${TOP + lanes.size * ROW}"></line>`);
    parts.push(`<text class="axis-label" x="${x + 2}" y="${TOP - 10}">
      ${t.toFixed(2)}s</text>`);
  }
  for (const [key, row] of lanes) {
    parts.push(`<text class="lane-label" x="0"
      y="${TOP + row * ROW + 14}">${key}</text>`);
  }
  slices.forEach((s, i) => {
    const row = lanes.get(s.pid + " / " + s.tid);
    const x = LBL + (s.ts - t0) / span * (W - LBL);
    const w = Math.max(1.5, s.dur / span * (W - LBL));
    const ms = (s.dur / 1000).toFixed(2);
    parts.push(`<rect class="slice" data-i="${i}" x="${x}"
      y="${TOP + row * ROW + 2}" width="${w}" height="${ROW - 5}"
      fill="${colorFor(s.name)}"
      data-tip="${s.name} — ${ms}ms (${(s.args||{}).outcome||''})"></rect>`);
  });
  svg.innerHTML = parts.join("");
  const tip = document.getElementById("tip");
  svg.querySelectorAll(".slice").forEach(el => {
    el.onmousemove = ev => { tip.style.display = "block";
      tip.style.left = (ev.clientX + 12) + "px";
      tip.style.top = (ev.clientY + 12) + "px";
      tip.textContent = el.dataset.tip; };
    el.onmouseout = () => tip.style.display = "none";
  });
}
async function drawBreakdown() {
  const r = await fetch("/api/tasks?breakdown=1");
  const rows = await r.json();
  const labels = Object.keys(rows || {}).sort();
  if (!labels.length) return;
  const ms = v => (v * 1000).toFixed(2);
  // Labels are user task names: escape or a crafted name is stored XSS.
  const esc = s => String(s).replace(/[&<>"']/g,
      c => "&#" + c.charCodeAt(0) + ";");
  let html = `<table style="border-collapse:collapse;font-size:12px">
    <tr><th style="text-align:left;padding:2px 10px">label</th>
    <th style="text-align:left;padding:2px 10px">phase</th>
    <th style="padding:2px 10px">count</th>
    <th style="padding:2px 10px">mean ms</th>
    <th style="padding:2px 10px">p50 ms</th>
    <th style="padding:2px 10px">p99 ms</th></tr>`;
  for (const label of labels) {
    for (const [phase, st] of Object.entries(rows[label])) {
      html += `<tr><td style="padding:2px 10px">${esc(label)}</td>
        <td style="padding:2px 10px">${esc(phase)}</td>
        <td style="padding:2px 10px;text-align:right">${st.count}</td>
        <td style="padding:2px 10px;text-align:right">${ms(st.mean)}</td>
        <td style="padding:2px 10px;text-align:right">${ms(st.p50)}</td>
        <td style="padding:2px 10px;text-align:right">${ms(st.p99)}</td>
        </tr>`;
    }
  }
  document.getElementById("breakdown").innerHTML = html + "</table>";
}
draw(); drawBreakdown();
setInterval(() => { draw(); drawBreakdown(); }, 5000);
</script></body></html>
"""


_LOGS_PAGE = """<!doctype html>
<html><head><title>ray_tpu logs</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 1.2rem; color: #1a1a2e; }
 h1 { font-size: 1.2rem; } h3 { font-size: 1rem; margin-bottom: .2rem; }
 pre { background: #f7f7fa; padding: .8rem; font-size: 12px;
       white-space: pre-wrap; word-break: break-all; }
 a { color: #2a6fbb; } #meta { color: #888; font-size: .85rem; }
</style></head><body>
<h1>Logs <small style="color:#888">(<a href="/">overview</a>)</small></h1>
<div id="meta"></div><div id="list"></div><pre id="out"></pre>
<script>
const q = new URLSearchParams(location.search);
const out = document.getElementById("out");
const esc = s => String(s).replace(/[&<>"']/g,
    c => "&#" + c.charCodeAt(0) + ";");
async function list() {
  const r = await fetch("/api/logs?all=1"); const data = await r.json();
  let h = "";
  for (const [nid, files] of Object.entries(data || {})) {
    h += `<h3>node ${esc(nid)}</h3><ul>`;
    for (const f of files) {
      const href = `/logs?node=${encodeURIComponent(nid)}` +
                   `&name=${encodeURIComponent(f.name)}`;
      h += `<li><a href="${href}">${esc(f.name)}</a>` +
           ` (${f.size} bytes)</li>`;
    }
    h += "</ul>";
  }
  document.getElementById("list").innerHTML = h || "<i>no log files</i>";
}
let offset = null;
async function poll() {
  const p = new URLSearchParams();
  for (const k of ["name", "task_id", "actor_id", "worker_id"])
    if (q.get(k)) p.set(k, q.get(k));
  if (q.get("node")) p.set("node", q.get("node"));
  p.set("offset", offset === null
      ? (q.get("task_id") || q.get("actor_id") ? 0 : -65536) : offset);
  try {
    const r = await fetch("/api/logs?" + p); const d = await r.json();
    if (d && typeof d === "object") {
      if (d.error) document.getElementById("meta").textContent = d.error;
      if (d.data) out.textContent += d.data;
      if (d.offset !== undefined) offset = d.offset;
    }
  } catch (e) {}
  setTimeout(poll, 1500);  // live tail: new bytes append on each poll
}
if (q.get("name") || q.get("task_id") || q.get("actor_id")
    || q.get("worker_id")) {
  document.getElementById("meta").textContent =
      "following " + (q.get("name") || q.get("task_id")
                      || q.get("actor_id") || q.get("worker_id"));
  poll();
} else list();
</script></body></html>
"""


class Dashboard:
    """aiohttp server bound to a running ray_tpu session."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._runner = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stop = None  # asyncio.Event inside the loop
        self._loop = None
        # /metrics proxy cache: (scrape wall-time, text). Serves repeat
        # scrapes within ~1s without re-hitting the controller, and keeps
        # the LAST GOOD payload to serve (as a 503) when a scrape times
        # out — a slow controller degrades the proxy, never blanks it.
        self._metrics_cache: tuple = (0.0, None)

    # -- request handlers --------------------------------------------------
    async def _index(self, request):
        from aiohttp import web

        try:
            import ray_tpu

            res = ray_tpu.cluster_resources()
            avail = ray_tpu.available_resources()
            cluster = (
                f"resources: <code>{html.escape(json.dumps(res))}</code> · "
                f"available: <code>{html.escape(json.dumps(avail))}</code>"
            )
        except Exception as e:
            cluster = f"cluster unavailable: {html.escape(repr(e))}"
        node_rows = self._safe(state_api.list_nodes) or []
        for r in node_rows:
            # Draining badge: the lifecycle state plus its reason, so an
            # operator sees "draining (preemption)" at a glance.
            st = r.get("state", "alive" if r.get("alive") else "dead")
            if st in ("draining", "drained") and r.get("drain_reason"):
                st = f"{st} ({r['drain_reason']})"
            r["state"] = st
        nodes = _table(node_rows,
                       ["node_id", "state", "resources", "labels"])
        actor_rows = self._safe(state_api.list_actors) or []
        for r in actor_rows:
            r["logs"] = _log_link("actor_id", r.get("actor_id"))
        actors = _table(actor_rows,
                        ["actor_id", "class_name", "state", "node_id",
                         "name", "logs"], raw={"logs"})
        summary = self._safe(state_api.summarize_tasks) or {}
        tasks = _table(
            [{"func": k, **v} for k, v in summary.items()],
            ["func", "running", "finished", "failed", "pending"],
        )
        recent_rows = (self._safe(state_api.list_tasks) or [])[-20:]
        for r in recent_rows:
            r["logs"] = _log_link("task_id", r.get("task_id"))
        recent = _table(recent_rows,
                        ["task_id", "name", "state", "node_id", "logs"],
                        raw={"logs"})
        jobs = _table(self._safe(self._jobs),
                      ["job_id", "status", "entrypoint"])
        # Compiled DAGs with channel-meter rollups: the pipelines whose
        # steady-state dispatch never touches the controller, with their
        # live steps/s and bottleneck verdict (`rtpu dag stats` detail).
        dag_rows = []
        for d in self._safe(state_api.list_compiled_dags) or []:
            methods = {f"s{s.get('idx')}": s.get("method", "")
                       for s in d.get("stages") or ()}
            bn = d.get("bottleneck")
            sps = d.get("steps_per_s")
            dag_rows.append({
                "dag_id": d.get("dag_id", "")[:12],
                "stages": len(d.get("stages") or ()),
                "depth": d.get("depth", 0),
                "steps/s": f"{sps:.1f}" if sps is not None else "-",
                "recoveries": (str(d.get("recoveries", 0))
                               + ("*" if d.get("recovering") else "")),
                "bottleneck": (f"{bn} {methods.get(bn, '')}".strip()
                               if bn else "-"),
                "last_cause": d.get("last_cause") or "",
            })
        dags = _table(dag_rows, ["dag_id", "stages", "depth", "steps/s",
                                 "recoveries", "bottleneck", "last_cause"])
        # Recent-events feed (reference: the dashboard event feed): the
        # newest cluster events, newest first, with the full log one click
        # away on /events.
        ev_rows = list(reversed(
            self._safe(lambda: state_api.list_events(limit=12)) or []))
        for r in ev_rows:
            r["time"] = _fmt_ts(r.get("ts"))
        events = _table(ev_rows,
                        ["time", "severity", "kind", "message"])
        return web.Response(
            text=_PAGE.format(cluster=cluster, nodes=nodes, actors=actors,
                              tasks=tasks, recent=recent, jobs=jobs,
                              events=events, dags=dags,
                              telemetry=self._telemetry_html()),
            content_type="text/html")

    def _telemetry_html(self) -> str:
        """Sparkline history charts on the overview (reference: the
        dashboard's time-series panels), fed by the controller TSDB via
        query_metrics — zero external services."""
        wanted = [("rtpu_pending_tasks", None), ("rtpu_workers", None),
                  ("rtpu_nodes_alive", None), ("rtpu_task_exec_s", "p99"),
                  ("rtpu_node_cpu_percent", None),
                  ("rtpu_node_mem_fraction", None),
                  ("rtpu_arena_used_bytes", None)]
        rows = []
        enabled = False
        for name, stat in wanted:
            resp = self._safe(lambda n=name, s=stat: state_api.
                              query_metrics(n, stat=s, limit_series=8))
            if not isinstance(resp, dict) or not resp.get("enabled"):
                continue
            enabled = True
            for ser in resp.get("series", ()):
                tag = ",".join(f"{k}={v}"
                               for k, v in sorted(ser["tags"].items()))
                label = ser["name"] + (f"{{{tag}}}" if tag else "")
                if ser.get("stat") not in (None, "value"):
                    label += f" ({ser['stat']})"
                pts = ser.get("points") or []
                last = pts[-1][1] if pts else 0.0
                rows.append(
                    f"<tr><td><code>{html.escape(label)}</code></td>"
                    f"<td>{_sparkline(pts)}</td>"
                    f'<td style="text-align:right">{last:.4g}</td></tr>')
        if not enabled:
            return ("<p><i>telemetry disabled (RTPU_TSDB=0) or "
                    "controller unreachable</i></p>")
        if not rows:
            return "<p><i>no samples yet</i></p>"
        return ("<table><tr><th>series</th><th>history</th><th>latest"
                "</th></tr>" + "".join(rows) + "</table>")

    @staticmethod
    def _safe(fn):
        try:
            return fn()
        except Exception:
            return []

    @staticmethod
    def _jobs():
        from ray_tpu import flags

        if flags.get("RTPU_JOBS_FT"):
            # Durable job table: full records (attempt accounting,
            # placement, bounded status history) straight from the
            # controller — terminal jobs keep real status/returncode.
            return state_api.list_jobs()
        from ray_tpu.jobs import JobSubmissionClient

        return [vars(j) for j in JobSubmissionClient().list_jobs()]

    async def _api(self, request):
        from aiohttp import web

        kind = request.match_info["kind"]
        try:
            if kind == "cluster":
                import ray_tpu

                data: Any = {
                    "resources": ray_tpu.cluster_resources(),
                    "available": ray_tpu.available_resources(),
                    "nodes": state_api.list_nodes(),
                }
            elif kind == "nodes":
                data = state_api.list_nodes()
            elif kind == "actors":
                data = state_api.list_actors()
            elif kind == "tasks":
                if request.query.get("breakdown"):
                    data = state_api.summarize_tasks(breakdown=True)
                elif request.query.get("summary"):
                    data = state_api.summarize_tasks()
                else:
                    data = state_api.list_tasks()
            elif kind == "workers":
                data = state_api.list_workers()
            elif kind == "objects":
                data = state_api.list_objects()
            elif kind == "memory":
                # Cluster object census (the `rtpu memory` backend):
                # ?group_by=owner|tier|node|callsite, ?min_size=, ?limit=.
                q = request.query
                data = state_api.summarize_objects(
                    min_size=int(q.get("min_size", 0)),
                    limit=int(q.get("limit", 500)))
            elif kind == "jobs":
                data = self._jobs()
            elif kind == "serve":
                from ray_tpu.serve.api import status as serve_status

                data = serve_status() or {}  # None = serve not running
            elif kind == "serve_requests":
                # The cluster request ledger (?model=, ?status=,
                # ?min_latency_s=, ?since=, ?request_id= adds the hop
                # spans — the `rtpu serve requests/trace` backend).
                q = request.query
                if q.get("request_id"):
                    data = state_api.serve_trace(q["request_id"])
                else:
                    data = state_api.list_serve_requests(
                        model=q.get("model"), status=q.get("status"),
                        min_latency_s=(float(q["min_latency_s"])
                                       if q.get("min_latency_s")
                                       else None),
                        since=(float(q["since"]) if q.get("since")
                               else None),
                        limit=int(q.get("limit", 100)))
            elif kind == "timeline":
                data = state_api.timeline()
            elif kind == "dags":
                # Compiled-DAG registry + channel-meter rollups (stage
                # busy fractions, edge ring stats, steps/s, bottleneck) —
                # the `rtpu dag stats` backend.
                data = state_api.list_compiled_dags()
            elif kind == "dag_timeline":
                # Chrome-trace of per-stage steps with recv/compute/send/
                # blocked sub-slices (merged with the task trace).
                data = state_api.dag_timeline(
                    dag=request.query.get("dag"))
            elif kind == "profile":
                # Dashboard-triggered stack capture (reference: reporter
                # py-spy endpoint); in an executor — it blocks up to
                # `timeout` while workers reply.
                import asyncio as _aio

                t = float(request.query.get("timeout", 1.0))
                data = await _aio.get_running_loop().run_in_executor(
                    None, lambda: state_api.profile_workers(t))
            elif kind == "usage":
                data = _local_usage()
            elif kind == "telemetry":
                # Metrics history from the controller's TSDB ring
                # (?name=, ?prefix=, ?since=, ?stat=, ?window=): the
                # sparkline charts' backend, and a generic JSON series
                # API for anything else that wants history.
                q = request.query
                data = state_api.query_metrics(
                    q.get("name"), prefix=q.get("prefix"),
                    since=float(q["since"]) if q.get("since") else None,
                    stat=q.get("stat"),
                    window_s=float(q.get("window", 60.0)),
                    limit_series=int(q.get("limit", 64)))
            elif kind == "alerts":
                data = state_api.list_alerts()
            elif kind == "events":
                q = request.query
                data = state_api.list_events(
                    severity=q.get("severity"),
                    kind=q.getall("kind") if q.get("kind") else None,
                    task_id=q.get("task_id"), actor_id=q.get("actor_id"),
                    node_id=q.get("node"), worker_id=q.get("worker_id"),
                    limit=int(q.get("limit", 200)))
            elif kind == "logs":
                # ?all=1 -> cluster log index; ?task_id/?actor_id/
                # ?worker_id or ?offset -> ranged/attributed chunk
                # ({data, offset, size, eof} — the viewer's poll cursor);
                # legacy: ?node + optional ?name lists/tails one host.
                q = request.query
                if q.get("all"):
                    data = state_api.list_logs()
                elif (q.get("task_id") or q.get("actor_id")
                        or q.get("worker_id") or q.get("offset")):
                    data = state_api.get_log(
                        name=q.get("name"), node_id=q.get("node", ""),
                        task_id=q.get("task_id"),
                        actor_id=q.get("actor_id"),
                        worker_id=q.get("worker_id"),
                        offset=int(q.get("offset", 0)),
                        max_bytes=int(q.get("bytes", 65536)))
                else:
                    from ray_tpu.core import context as _ctx

                    data = _ctx.get_worker_context().client.request({
                        "kind": "worker_logs",
                        "node_id": q.get("node", ""),
                        "name": q.get("name"),
                        "bytes": int(q.get("bytes", 65536)),
                    })
            else:
                return web.Response(status=404, text=f"unknown: {kind}")
        except Exception as e:
            return web.json_response({"error": repr(e)}, status=500)
        return web.json_response(data, dumps=lambda o: json.dumps(o, default=str))

    async def _events_page(self, request):
        """Cluster event feed (reference: the dashboard event page):
        severity/kind/entity filters via query params; hang-watchdog
        events expose their captured stacks in a collapsible block."""
        from aiohttp import web

        q = request.query
        try:
            evs = state_api.list_events(
                severity=q.get("severity"),
                kind=q.getall("kind") if q.get("kind") else None,
                task_id=q.get("task_id"), actor_id=q.get("actor_id"),
                node_id=q.get("node"), worker_id=q.get("worker_id"),
                limit=int(q.get("limit", 200)))
        except Exception as e:
            evs = []
            err = html.escape(repr(e))
        else:
            err = ""
        rows = []
        for ev in reversed(evs):  # newest first
            stack = (ev.get("data") or {}).get("stack")
            msg = html.escape(str(ev.get("message", "")))
            if stack:
                msg += (f"<details><summary>captured stacks</summary>"
                        f"<pre>{html.escape(stack)}</pre></details>")
            ids = " ".join(
                f"{k.split('_')[0]}={html.escape(ev[k][:12])}"
                for k in ("task_id", "actor_id", "worker_id", "node_id")
                if ev.get(k))
            rows.append({
                "time": _fmt_ts(ev.get("ts")),
                "severity": ev.get("severity", ""),
                "kind": ev.get("kind", ""),
                "entities": ids,
                "message": msg,
            })
        table = _table(rows, ["time", "severity", "kind", "entities",
                              "message"], raw={"message"})
        body = (
            "<!doctype html><html><head><title>ray_tpu events</title>"
            '<meta http-equiv="refresh" content="5"><style>'
            "body { font-family: system-ui, sans-serif; margin: 1.2rem; "
            "color: #1a1a2e; } h1 { font-size: 1.2rem; } "
            "table { border-collapse: collapse; width: 100%; "
            "font-size: .85rem; } th, td { text-align: left; "
            "padding: .3rem .6rem; border-bottom: 1px solid #ddd; } "
            "th { background: #f4f4f8; } pre { background: #f7f7fa; "
            "padding: .6rem; font-size: 11px; white-space: pre-wrap; }"
            "</style></head><body>"
            '<h1>Cluster events <small style="color:#888">'
            '(<a href="/">overview</a>; filters: ?severity=, ?kind=, '
            "?task_id=, ?actor_id=, ?node=)</small></h1>"
            + (f"<p>{err}</p>" if err else "")
            + table + "</body></html>")
        return web.Response(text=body, content_type="text/html")

    async def _objects_page(self, request):
        """Cluster memory census (reference: the dashboard object view /
        `ray memory`): per-group bytes by owner / tier / node / callsite
        plus the largest individual objects, straight off the
        controller's object_census aggregation."""
        from aiohttp import web

        group_by = request.query.get("group_by", "owner")
        if group_by not in ("owner", "tier", "node", "callsite"):
            group_by = "owner"
        try:
            s = state_api.summarize_objects(
                min_size=int(request.query.get("min_size", 0)), limit=100)
        except Exception as e:
            s = {"enabled": False, "errors": [repr(e)], "objects": [],
                 "groups": {}, "num_objects": 0, "total_bytes": 0}
        errs = "".join(f"<p style='color:#b00'>{html.escape(str(e))}</p>"
                       for e in s.get("errors", ()))
        hdr = (f"<p>{s.get('num_objects', 0)} objects, "
               f"{s.get('total_bytes', 0)} bytes across "
               f"{s.get('shards', '?')} shard(s)</p>")
        links = " · ".join(
            f'<a href="/objects?group_by={g}">{g}</a>'
            for g in ("owner", "tier", "node", "callsite"))
        grows = [{"key": k, "bytes": v["bytes"], "count": v["count"],
                  "tiers": ", ".join(f"{t}={b}" for t, b in
                                     sorted(v.get("tiers", {}).items()))}
                 for k, v in sorted(
                     (s.get("groups", {}).get(group_by) or {}).items(),
                     key=lambda kv: -kv[1]["bytes"])]
        groups = _table(grows, ["key", "bytes", "count", "tiers"])
        orows = [{"object_id": (o.get("object_id") or "")[:16],
                  "size": o.get("size", 0), "tier": o.get("tier", "?"),
                  "node": (o.get("node_id") or "")[:12],
                  "owner": o.get("owner", "?"),
                  "age_s": round(o.get("age_s") or 0, 1),
                  "callsite": o.get("callsite") or ""}
                 for o in s.get("objects", ())]
        objects = _table(orows, ["object_id", "size", "tier", "node",
                                 "owner", "age_s", "callsite"])
        body = (
            "<!doctype html><html><head><title>ray_tpu objects</title>"
            '<meta http-equiv="refresh" content="10"><style>'
            "body { font-family: system-ui, sans-serif; margin: 1.2rem; "
            "color: #1a1a2e; } h1 { font-size: 1.2rem; } "
            "h2 { font-size: 1.05rem; margin-top: 1.2rem; } "
            "table { border-collapse: collapse; width: 100%; "
            "font-size: .85rem; } th, td { text-align: left; "
            "padding: .3rem .6rem; border-bottom: 1px solid #ddd; } "
            "th { background: #f4f4f8; }"
            "</style></head><body>"
            '<h1>Object census <small style="color:#888">'
            '(<a href="/">overview</a>)</small></h1>'
            + hdr + errs
            + f"<p>group by: {links}</p>"
            + f"<h2>By {html.escape(group_by)}</h2>" + groups
            + "<h2>Largest objects</h2>" + objects
            + "</body></html>")
        return web.Response(text=body, content_type="text/html")

    async def _serve_requests_page(self, request):
        """Per-request serving ledger page: newest requests with status /
        latency / token stats; ?request_id= renders one request's hop
        waterfall (dwell bars indented by span depth)."""
        from aiohttp import web

        q = request.query
        rid = q.get("request_id")
        style = (
            "<style>body { font-family: system-ui, sans-serif; "
            "margin: 1.2rem; color: #1a1a2e; } h1 { font-size: 1.2rem; } "
            "table { border-collapse: collapse; width: 100%; "
            "font-size: .85rem; } th, td { text-align: left; "
            "padding: .3rem .6rem; border-bottom: 1px solid #ddd; } "
            "th { background: #f4f4f8; } .bar { background: #4a7fd4; "
            "height: 10px; display: inline-block; }</style>")
        if rid:
            try:
                row = state_api.serve_trace(rid)
            except Exception as e:
                return web.Response(
                    text=f"<p>{html.escape(repr(e))}</p>",
                    content_type="text/html")
            wf = row.get("waterfall") or []
            wall = row.get("wall_s") or max(
                [e["dwell_s"] for e in wf] or [0]) or 1e-9
            rows = []
            for e in wf:
                a = e.get("attributes") or {}
                detail = " ".join(f"{k}={a[k]}" for k in sorted(a))
                pct = min(100.0, e["dwell_s"] / wall * 100.0)
                rows.append({
                    "hop": ("&nbsp;" * 2 * e["depth"]
                            + html.escape(e["name"] or "")),
                    "dwell": f"{e['dwell_s'] * 1e3:.2f} ms",
                    "self": f"{e['self_s'] * 1e3:.2f} ms",
                    "share": f'<span class="bar" '
                             f'style="width:{pct:.1f}%"></span>',
                    "detail": html.escape(detail),
                })
            table = _table(rows, ["hop", "dwell", "self", "share",
                                  "detail"], raw={"hop", "share"})
            hdr = (f"<p>deployment={html.escape(row.get('deployment') or '-')} "
                   f"proto={html.escape(row.get('proto') or '-')} "
                   f"status={html.escape(str(row.get('status')))} "
                   + (f"wall={row['wall_s'] * 1e3:.1f}ms "
                      if row.get("wall_s") is not None else "")
                   + ("<b>SLO-MISS</b> " if row.get("slo_miss") else "")
                   + (f"tokens={row['tokens']} " if row.get("tokens")
                      is not None else "")
                   + (f"error={html.escape(row['error'])}"
                      if row.get("error") else "") + "</p>")
            body = (
                "<!doctype html><html><head><title>serve trace</title>"
                + style + "</head><body>"
                f"<h1>Request {html.escape(row['request_id'])} "
                '<small style="color:#888">'
                '(<a href="/serve-requests">ledger</a>)</small></h1>'
                + hdr + table + "</body></html>")
            return web.Response(text=body, content_type="text/html")
        try:
            reqs = state_api.list_serve_requests(
                model=q.get("model"), status=q.get("status"),
                limit=int(q.get("limit", 100)))
        except Exception as e:
            reqs = []
            err = f"<p>{html.escape(repr(e))}</p>"
        else:
            err = ""
        rows = []
        for r in reqs:
            wall = r.get("wall_s")
            itl = r.get("itl_p99_s")
            rows.append({
                "request": f'<a href="/serve-requests?request_id='
                           f'{html.escape(r["request_id"])}">'
                           f'{html.escape(r["request_id"][:16])}</a>',
                "deployment": r.get("deployment") or "-",
                "proto": r.get("proto") or "-",
                "status": r.get("status") or "?",
                "wall": (f"{wall * 1e3:.1f} ms"
                         if wall is not None else "-"),
                "tokens": r.get("tokens", "-"),
                "itl p99": (f"{itl * 1e3:.2f} ms"
                            if itl is not None else "-"),
                "slo": "MISS" if r.get("slo_miss") else "",
                "started": _fmt_ts(r.get("start_ts")),
                "error": (r.get("error") or "")[:60],
            })
        table = _table(rows, ["request", "deployment", "proto", "status",
                              "wall", "tokens", "itl p99", "slo",
                              "started", "error"], raw={"request"})
        body = (
            "<!doctype html><html><head><title>serve requests</title>"
            '<meta http-equiv="refresh" content="5">' + style
            + "</head><body>"
            '<h1>Serve requests <small style="color:#888">'
            '(<a href="/">overview</a>; filters: ?model=, ?status=, '
            "?limit=)</small></h1>" + err + table + "</body></html>")
        return web.Response(text=body, content_type="text/html")

    async def _logs_page(self, request):
        """Log viewer (reference: the dashboard log viewer): lists the
        cluster log index, or — given ?node&name / ?task_id / ?actor_id /
        ?worker_id — live-tails that file / attributed output by polling
        /api/logs with an offset cursor."""
        from aiohttp import web

        return web.Response(text=_LOGS_PAGE, content_type="text/html")

    async def _timeline_page(self, request):
        """Per-worker swimlane view of the task-event buffer, rendered
        in-browser from /api/timeline (reference: the dashboard's task
        timeline; data is the same chrome-trace JSON, so perfetto remains
        an option for big traces)."""
        from aiohttp import web

        return web.Response(text=_TIMELINE_PAGE, content_type="text/html")

    async def _healthz(self, request):
        from aiohttp import web

        return web.Response(text="ok")

    async def _metrics(self, request):
        from aiohttp import web

        addr = state_api.metrics_address()
        if not addr:
            return web.Response(status=503, text="# metrics disabled\n")
        import asyncio
        import time as _time
        import urllib.request

        ts, cached = self._metrics_cache
        if cached is not None and _time.time() - ts < 1.0:
            return web.Response(text=cached, content_type="text/plain")

        def scrape() -> str:
            with urllib.request.urlopen(f"http://{addr}/metrics",
                                        timeout=2) as resp:
                return resp.read().decode()

        try:
            # Blocking scrape goes to a thread: a slow/hung controller must
            # not stall every other dashboard request for the 2s timeout.
            text = await asyncio.get_running_loop().run_in_executor(
                None, scrape)
            self._metrics_cache = (_time.time(), text)
            return web.Response(text=text, content_type="text/plain")
        except Exception as e:
            if cached is not None:
                # Stale-but-real beats empty: a Prometheus poller keeps
                # its series (and sees the 503) while the controller is
                # slow.
                return web.Response(status=503, text=cached,
                                    content_type="text/plain")
            return web.Response(status=502, text=f"# scrape failed: {e!r}\n")

    # -- lifecycle ---------------------------------------------------------
    async def _serve(self):
        import asyncio

        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/logs", self._logs_page)
        app.router.add_get("/objects", self._objects_page)
        app.router.add_get("/events", self._events_page)
        app.router.add_get("/serve-requests", self._serve_requests_page)
        app.router.add_get("/timeline", self._timeline_page)
        app.router.add_get("/api/{kind}", self._api)
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self._stop = asyncio.Event()
        self._started.set()
        await self._stop.wait()
        await self._runner.cleanup()

    def start(self) -> str:
        import asyncio

        def body():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(self._serve())
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=body, daemon=True,
                                        name="rtpu-dashboard")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("dashboard failed to start")
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._loop is not None and self._stop is not None:
            self._loop.call_soon_threadsafe(self._stop.set)
        if self._thread is not None:
            self._thread.join(timeout=5)


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    """Start the dashboard against the current session; returns the handle
    (``.port`` is the bound port — pass port=0 for ephemeral)."""
    dash = Dashboard(host, port)
    dash.start()
    return dash
