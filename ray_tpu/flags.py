"""Central registry of every ray_tpu configuration knob.

One place defining each ``RTPU_*`` environment flag with its type, default,
and documentation — the reference concentrates its ~217 knobs in
``src/ray/common/ray_config_def.h`` for the same reason: scattering
``os.environ.get(...)`` at point of use means no single list of what can be
tuned, no defaults audit, and typo'd names that silently fall back.

Rules:
- Every module reads flags through :func:`get` (call-time lookup, so flags
  set by a parent before spawning a worker, or by a test, are honored).
- Writes (the few flags that double as process-tree plumbing, e.g.
  ``RTPU_HOST_ID``) go through :func:`set_env` / :func:`unset_env`.
- External variables we consume-but-don't-own (``JAX_PLATFORMS``,
  ``XLA_FLAGS``, ``TPU_ACCELERATOR_TYPE``) are registered as EXTERNAL for
  documentation and read through the same accessors.
- ``child_env()`` is the sanctioned way to snapshot the environment when
  spawning subprocesses.

``python -m ray_tpu.flags`` prints the full flag table.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str
    type: type
    default: Any
    doc: str
    external: bool = False  # owned by another system (jax/libtpu/GCE)


REGISTRY: Dict[str, Flag] = {}


def _define(name: str, type_: type, default: Any, doc: str,
            external: bool = False) -> None:
    REGISTRY[name] = Flag(name, type_, default, doc, external)


# -- session / addressing ----------------------------------------------------
_define("RTPU_ADDRESS", str, None,
        "Controller address host:port a driver connects to when "
        "init(address=...) is not given (reference RAY_ADDRESS).")
_define("RTPU_CONTROLLER", str, None,
        "Controller address injected into spawned workers/job drivers; "
        "internal process-tree plumbing.")
_define("RTPU_NODE_ID", str, None,
        "Node id a spawning agent assigns to its workers (internal).")
_define("RTPU_HOST_ID", str, None,
        "Logical host id of this process; set by the host agent so object "
        "plane chooses shm vs TCP pulls (multi-host tests force distinct "
        "ids to exercise real transfers).")
_define("RTPU_SPAWN_TOKEN", str, None,
        "Opaque token tying a spawned worker back to its lease (internal).")
_define("RTPU_SYS_PATH", str, None,
        "Extra sys.path entry for workers (working_dir runtime env).")
_define("RTPU_STATE_PATH", str, None,
        "Controller persistence snapshot path; enables restart recovery.")
_define("RTPU_TPU_WORKER", bool, False,
        "Marks a worker as TPU-capable (set on workers granted TPU "
        "resources; gates device initialization).")

_define("RTPU_DIRECT_DISPATCH", bool, True,
        "Push actor calls directly to the hosting worker (lease-then-push); "
        "0 routes every call through the controller.")
_define("RTPU_CONTAINER_RUNTIME", str, "podman",
        "Container runtime binary used to wrap worker launches when a "
        "runtime_env requests 'container' (reference: worker-in-podman).")
_define("RTPU_TASK_LEASE_MAX", int, 16,
        "Max leased workers per (resources, env) signature for direct "
        "stateless-task dispatch; 0 disables task leasing entirely.")
_define("RTPU_LEASE_BLOCK", int, 8,
        "Workers requested per lease_block controller RPC: one round trip "
        "grants a block of direct-dispatch workers for a (resources, env) "
        "signature, so a submission wave fans across the pool with no "
        "further controller involvement (reference: the raylet granting "
        "leases per scheduling class, direct_task_transport.h:75). 1 "
        "degenerates to the old one-lease-per-RPC negotiation.")
_define("RTPU_SUBMIT_BATCH", bool, True,
        "Coalesce direct task/actor-call pushes, their completion acks, "
        "and result-location publishes into multi-entry framed messages: "
        "specs submitted in the same event-loop beat ride one pickle and "
        "one syscall per hop (reference: the batched lease/push RPCs in "
        "direct_task_transport + CoreWorker's batched task-status "
        "reports). 0 reverts to one message per call; the submit path "
        "then pays one flag check.")
_define("RTPU_SUBMIT_BATCH_MAX", int, 512,
        "Entries per open submit batch: a batch reaching this many pending "
        "specs is sealed and a new one opened, bounding both frame size "
        "and the per-batch reply payload.")
_define("RTPU_DISTRIBUTED_REFS", bool, True,
        "Distributed ownership: ObjectRef handles are counted per process, "
        "borrowers register with owners worker-to-worker, and drained "
        "objects are freed with one batched controller message. 0 reverts "
        "to never-free-until-pressure semantics.")
_define("RTPU_FREE_DELAY_S", float, 1.0,
        "Grace window between an object draining (no handles, borrowers or "
        "holds anywhere) and the batched free, absorbing in-flight races.")
_define("RTPU_HOLD_RELEASE_GRACE_S", float, 2.0,
        "Grace before a submit-hold is released on locally OBSERVING a "
        "task's outcome (vs the worker's ordered release message): bounds "
        "how late an executing worker's borrow_add may arrive.")
_define("RTPU_DIRECT_BIND", str, None,
        "Interface the worker direct-dispatch server binds. Default: the "
        "local address of the worker's controller connection, so loopback "
        "clusters never expose the direct endpoint off-host.")

_define("RTPU_SCHED_HYBRID_THRESHOLD", float, 0.5,
        "Hybrid scheduling threshold: nodes below this CPU utilization are "
        "packed in index order; above it, placement spreads by load "
        "(reference hybrid_scheduling_policy).")
_define("RTPU_SCHED_TOP_K", int, 1,
        "Randomize DEFAULT placement among the best k nodes (anti-herding "
        "at scale); 1 keeps placement deterministic.")
_define("RTPU_EVENT_EXPORT_PATH", str, None,
        "Append structured control-plane events (task/actor/node "
        "lifecycle) as JSONL to this file for external pipelines "
        "(reference export-event files).")
_define("RTPU_TRACING", bool, False,
        "OpenTelemetry span propagation through task submission "
        "(util/tracing.py setup_tracing); workers inherit via env.")
_define("RTPU_SPILLBACK_MEM_FRACTION", float, 0.97,
        "A worker whose host memory use exceeds this fraction rejects "
        "dispatched tasks back to the scheduler (raylet spillback shape); "
        "0 disables admission checks.")

# -- controller tunables -----------------------------------------------------
_define("RTPU_MAX_WORKERS_PER_NODE", int, 32,
        "Upper bound on workers the controller spawns per node.")
_define("RTPU_LINEAGE_MAX", int, 10000,
        "Bounded lineage log length for object reconstruction.")
_define("RTPU_TASK_EVENTS_MAX", int, 50000,
        "Ring-buffer size of task events feeding the state API/timeline.")
_define("RTPU_METRICS_PORT", int, 0,
        "Controller Prometheus port (0 = disabled).")
_define("RTPU_MAX_RECONSTRUCTIONS", int, 3,
        "Max lineage re-executions per object before giving up.")
_define("RTPU_NODE_TIMEOUT_S", float, 10.0,
        "Heartbeat silence after which a node is marked SUSPECT: the "
        "scheduler stops placing work on it and actor calls buffer, but "
        "nothing is killed — a healed partition rejoins without actor "
        "churn (reference: the SWIM-style suspect phase in front of "
        "gcs_health_check_manager death declarations).")
_define("RTPU_DEAD_TIMEOUT_S", float, 30.0,
        "Heartbeat silence after which a suspect node is declared DEAD "
        "and its work re-queues/restarts elsewhere. The suspect->dead "
        "two-phase detector means a partition shorter than this heals "
        "with no duplicate actor instance and no double-allocation; "
        "must be >= RTPU_NODE_TIMEOUT_S (clamped if not).")
_define("RTPU_HEARTBEAT_S", float, 2.0,
        "Host-agent heartbeat period.")
_define("RTPU_MEMORY_MONITOR", bool, True,
        "Kill a worker when a host crosses the memory threshold "
        "(reference memory_monitor + retriable-FIFO kill policy).")
_define("RTPU_MEMORY_USAGE_THRESHOLD", float, 0.95,
        "Host memory fraction that triggers the memory monitor.")
_define("RTPU_MEMORY_MONITOR_S", float, 2.0,
        "Memory monitor sampling period.")

# -- controller fault tolerance ----------------------------------------------
_define("RTPU_RECONNECT_MAX_S", float, 20.0,
        "Total time a disconnected client/worker/host-agent keeps retrying "
        "the controller before giving up (reference: GCS client reconnect "
        "window, gcs_rpc_server reconnection timeout). Workers and agents "
        "fate-share once the deadline passes; drivers raise ConnectionError.")
_define("RTPU_RECONNECT_BACKOFF_S", float, 0.1,
        "Initial reconnect backoff; doubles per attempt, capped at 2s.")
_define("RTPU_RECONNECT_GRACE_S", float, 2.0,
        "After a controller restart with persisted state, how long restored "
        "detached actors wait for their (possibly still-alive) hosting "
        "workers to reconnect and re-claim them before being re-created "
        "from scratch (reference: GCS waits for raylet re-registration on "
        "NotifyGCSRestart before reconstructing actors).")
_define("RTPU_TESTING_RPC_DELAY_MS", str, None,
        "Fault-injection: per-message-kind handler delays, e.g. "
        "'register=200,heartbeat=50' or '*=20' (reference: "
        "RAY_testing_asio_delay_us). Applied server-side in the protocol "
        "layer before the handler runs; testing only.")
_define("RTPU_TESTING_RPC_DROP", str, None,
        "Fault-injection: per-message-kind DROP probabilities, e.g. "
        "'submit_actor_task=0.3,*=0.05'. A dropped message is read off "
        "the wire and silently discarded before its handler runs — no "
        "response is ever sent, modeling a lossy/partitioned network. "
        "Survivable only for idempotent request kinds retried under "
        "RTPU_RPC_TIMEOUT_S; testing only.")
_define("RTPU_TESTING_NET_ID", str, None,
        "Fault-injection: this process's identity for NetworkPartitioner "
        "blackholes (testing.NetworkPartitioner). Inherited by spawned "
        "children, so tagging a host agent partitions its whole host.")
_define("RTPU_TESTING_PARTITION_FILE", str, None,
        "Fault-injection: JSON file naming partitioned net ids "
        "({\"isolated\": [...]}). A process whose RTPU_TESTING_NET_ID is "
        "listed drops every inbound AND outbound protocol frame (a "
        "symmetric blackhole: TCP stays open, bytes vanish) until the "
        "entry is removed; testing only.")
_define("RTPU_RPC_TIMEOUT_S", float, 0.0,
        "Per-request control-plane timeout with capped exponential "
        "backoff retry: a blocking client request that gets no response "
        "within this window treats the connection as suspect, re-dials, "
        "and re-sends (submit handlers are idempotent by task/actor id, "
        "so blind re-sends never double-execute). 0 (default) disables — "
        "requests wait indefinitely, as before; enable on partition- or "
        "loss-prone networks (chaos tests set it).")

# -- node drain / preemption -------------------------------------------------
_define("RTPU_DRAIN_DEADLINE_S", float, 30.0,
        "Default grace window a draining node gives its running tasks "
        "before they are killed and re-queued (the DrainNode deadline; "
        "reference autoscaler.proto DrainNode deadline_timestamp_ms). "
        "Callers of drain_node may override per drain.")
_define("RTPU_PREEMPTION_WATCHER", bool, False,
        "Host agent polls the cloud metadata preemption endpoint and "
        "self-drains (reason='preemption') when an imminent-preemption "
        "notice appears, so a spot/preemptible TPU VM migrates its work "
        "instead of crashing. Off by default: only meaningful on "
        "preemptible capacity.")
_define("RTPU_PREEMPTION_URL", str,
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "preempted",
        "Metadata endpoint the preemption watcher polls. A body of "
        "TRUE/FALSE (the GCE contract) — any other non-empty truthy body "
        "also counts as a notice. Tests point this at a "
        "testing.PreemptionInjector fake.")
_define("RTPU_PREEMPTION_POLL_S", float, 1.0,
        "Preemption watcher polling period.")

# -- actor checkpoints / exactly-once replay ---------------------------------
_define("RTPU_ACTOR_CHECKPOINT", bool, True,
        "Durable actor checkpoints: actors created with "
        "checkpoint_interval_s / checkpoint_every_n periodically "
        "serialize their state (plus the exactly-once call journal) to a "
        "host-local file and ship an async copy to the controller, so a "
        "crash restart restores the newest reachable checkpoint instead "
        "of re-running the constructor (reference: gcs_actor_manager "
        "restart + the Ray paper's actor checkpointing story). 0 "
        "disables the subsystem entirely: no checkpoint threads exist "
        "and the per-call path pays one flag check at actor creation.")
_define("RTPU_CHECKPOINT_DIR", str, None,
        "Directory for host-local actor checkpoint files (default: a "
        "per-host dir under the system temp root). Shared by every "
        "worker on the host so a restarted actor placed on the same "
        "host can restore a checkpoint newer than the controller's "
        "shipped copy.")
_define("RTPU_CHECKPOINT_TICK_S", float, 0.25,
        "Worker-side sweep period for interval-based actor checkpoints "
        "(the timer thread only exists while a checkpointing actor with "
        "checkpoint_interval_s is hosted).")

# -- object transfer (inter-node pulls / broadcast) --------------------------
_define("RTPU_PULL_STREAM", bool, True,
        "Streamed inter-node object pulls: one pull_stream request ships "
        "every chunk back-to-back under a credit window instead of one "
        "request/response round trip per chunk (reference: the object "
        "manager's chunked Push/Pull, object_manager.proto). 0 reverts to "
        "the serial per-chunk loop; the pull path then pays one flag check.")
_define("RTPU_PULL_CHUNK", int, 4 * 1024 * 1024,
        "Chunk size in bytes for inter-node object transfer (streamed and "
        "serial pulls, broadcast chains).")
_define("RTPU_PULL_WINDOW", int, 8,
        "Credit window for streamed pulls / broadcast chains: how many "
        "chunks may be in flight before the sender waits for the "
        "receiver's consumption credits.")
_define("RTPU_PULL_PARALLEL", int, 2,
        "Max concurrent source hosts one pull fans across when the "
        "controller knows replica locations (broadcast copies). 1 "
        "disables range-splitting.")
_define("RTPU_WORKER_SERVE", bool, True,
        "Producing processes serve their own objects' bytes over their "
        "existing direct-call/ref server (Ray's plasma + pull-manager "
        "split: the controller keeps location metadata only). Consumers "
        "fall back to the host agent when the producer is gone. 0 routes "
        "every cross-host pull through the host agent.")

# -- compiled DAG channels ---------------------------------------------------
_define("RTPU_DAG_CHANNELS", bool, True,
        "Compiled DAGs execute over reusable mutable channels: one shm "
        "slot ring (same-host edges) or persistent raw-tail stream "
        "(cross-host edges) per DAG edge, with a resident per-actor loop "
        "on the worker, so steady-state execute() is a header write + one "
        "doorbell with zero controller involvement (reference: aDAG's "
        "MutableObjectManager channels, SURVEY.md §2.2). 0 falls back to "
        "per-execute task submission through the normal submit path.")
_define("RTPU_DAG_SLOT_BYTES", int, 128 * 1024,
        "Payload capacity of one shm channel slot. A value that pickles "
        "larger than this ships via a one-off sidecar shm segment named "
        "in the slot (still zero controller involvement); size it to the "
        "common per-edge payload so the sidecar path stays cold.")
_define("RTPU_DAG_SPIN_US", int, 200,
        "How long a channel reader/writer spins on the seqno header "
        "before arming its doorbell and blocking — spinning covers the "
        "common back-to-back case without syscalls; 0 blocks immediately "
        "(right for oversubscribed 1-core hosts).")
_define("RTPU_DAG_STALL_S", float, 2.0,
        "How long a compiled-DAG get() tolerates zero channel progress "
        "before probing participant liveness (direct dag_status pings, "
        "then resolve_actor). Probes run only when stalled, so the "
        "steady state stays controller-free; a dead/restarted "
        "participant tears the DAG down with DAGTeardownError.")
_define("RTPU_DAG_RECOVERY", bool, True,
        "Compiled DAGs heal in place: when the stall probe finds a dead "
        "restartable participant, the driver quiesces the pipeline, waits "
        "for the controller's actor-restart path (restoring the stage's "
        "durable checkpoint when one is configured), rebuilds only the "
        "affected edges under a bumped ring epoch, and replays retained "
        "items from the last seqno each reader applied, so every "
        "microbatch is delivered exactly once. Non-restartable stages "
        "(max_restarts=0) and an exhausted restart budget still raise "
        "DAGTeardownError; 0 restores the PR 10 fail-fast semantics.")
_define("RTPU_DAG_RECOVERY_TIMEOUT_S", float, 60.0,
        "How long a recovering DAG waits for a dead stage actor to come "
        "back alive (restart scheduling + checkpoint restore) before "
        "giving up and tearing down with DAGTeardownError.")
_define("RTPU_DAG_METER", bool, True,
        "Channel-fabric telemetry: every shm slot ring carries per-writer/"
        "per-reader counter blocks (items, bytes, blocked/starved ns) and "
        "every resident stage loop accounts recv/compute/send phase time, "
        "sampled out-of-band on the worker's metrics-flush heartbeat into "
        "rtpu_dag_edge_*/rtpu_dag_stage_* TSDB families (`rtpu dag "
        "stats`, `rtpu top`, state.dag_timeline()). The hot path adds "
        "only plain cache-line counter stores plus a few amortized "
        "monotonic clock reads; 0 removes even those (perf-guarded in "
        "test_perf_regression.py).")

# -- streaming data plane fault tolerance ------------------------------------
_define("RTPU_DATA_FT", bool, True,
        "Fault-tolerant streaming data plane: actor-pool stages detect "
        "dead/preempted pool actors on the in-flight ref, replace the "
        "actor in place and resubmit the affected batch (bounded by "
        "RTPU_DATA_FT_RETRIES; preempted deaths never burn the budget), "
        "pools proactively migrate off draining nodes, and all-to-all "
        "shards lost to node death re-derive from their recorded "
        "producing call (riding the controller's lineage path first). "
        "0 reproduces the legacy fail-fast plane byte-for-byte; every "
        "stage then pays one flag check at stage start.")
_define("RTPU_DATA_FT_RETRIES", int, 3,
        "Per-batch retry budget of a self-healing actor-pool stage: how "
        "many times one input block may be resubmitted after its pool "
        "actor CRASHED before the stage surfaces the error. Preempted "
        "deaths (drain/spot reclamation) re-submit without consuming "
        "the budget — planned departures are not failures (the PR 4 "
        "drain semantics applied to the data plane).")
_define("RTPU_DATA_DRAIN_POLL_S", float, 1.0,
        "How often (at most) an actor-pool stage refreshes the cluster's "
        "draining-node set while submitting work. A pool actor observed "
        "on a draining node is proactively replaced (new actor placed by "
        "the scheduler, which already excludes draining nodes) instead "
        "of waiting for the drain deadline to kill it mid-batch. 0 "
        "disables the poll; pools then heal only reactively.")

# -- object store / spilling -------------------------------------------------
_define("RTPU_NATIVE_STORE", bool, True,
        "Use the C++ shm arena when available (0 forces pickle fallback).")
_define("RTPU_STORE_LIB", str, None,
        "Alternate librtpu_store build to load (sanitizer variants).")
_define("RTPU_ARENA", str, None,
        "Name of the shm arena segment (internal, set by the creator).")
_define("RTPU_ARENA_SIZE", int, 1 << 30,
        "Arena segment size in bytes.")
_define("RTPU_FORCE_INLINE", bool, False,
        "Force inline (in-band) object payloads; chaos/multinode tests.")
_define("RTPU_SPILL_DIR", str, None,
        "Directory for spilled objects (enables arena spilling).")
_define("RTPU_SPILL_HIGH", float, 0.8,
        "Arena fill fraction that triggers spilling.")
_define("RTPU_SPILL_LOW", float, 0.6,
        "Arena fill fraction spilling drains down to.")
_define("RTPU_SPILL_DELETE_GRACE_S", float, 10.0,
        "Grace period before spilled files of freed objects are deleted.")

# -- runtime env -------------------------------------------------------------
_define("RTPU_RUNTIME_ENV", str, None,
        "Serialized runtime env JSON applied inside a worker (internal).")
_define("RTPU_RUNTIME_ENV_CACHE", str, None,
        "Cache dir for working_dir zips and pip venvs "
        "(default ~/.ray_tpu/runtime_env).")
_define("RTPU_WORKING_DIR_MAX_BYTES", int, 100 * 1024 * 1024,
        "Refuse to package working_dirs larger than this "
        "(reference default cap).")

# -- accelerators / jax ------------------------------------------------------
_define("RTPU_NUM_TPUS", int, None,
        "Override detected local TPU chip count.")
_define("RTPU_TPU_GENERATION", str, None,
        "Override detected TPU generation (v4/v5e/v5p/v6e).")
_define("RTPU_JAX_PLATFORM", str, None,
        "Force the JAX platform ray_tpu initializes (cpu/tpu).")
_define("RTPU_WORKFLOW_STORAGE", str, None,
        "Workflow durability root (default ~/.ray_tpu/workflows).")

_define("RTPU_ATTN_IMPL", str, "auto",
        "Attention implementation: auto (flash on TPU, else XLA) | flash | "
        "xla. 'xla' keeps the whole program Pallas-free, for environments "
        "where the Mosaic compile path is unavailable (remote-compile "
        "tunnels that hang on tpu_custom_call).")
_define("RTPU_SP_MODE", str, "ring",
        "Context-parallel attention scheme over the seq mesh axis: "
        "ring | ulysses | auto (ulysses when head counts divide the axis).")

# -- observability -----------------------------------------------------------
_define("RTPU_METRICS_FLUSH_S", float, 1.0,
        "Flush period for app metrics (util/metrics.py) to the controller.")
_define("RTPU_TASK_EVENTS", bool, True,
        "Worker-side task flight recorder: per-task phase timestamps "
        "(scheduling delay, queue wait, arg fetch, execute, result store) "
        "buffered and shipped to the controller in batches together with "
        "finished tracing spans (reference: TaskEventBuffer -> "
        "GcsTaskManager, task_event_buffer.h:206). 0 disables recording "
        "entirely; the hot path then pays one flag check.")
_define("RTPU_TASK_EVENTS_FLUSH_S", float, 0.5,
        "Flight-recorder flush period: how often a worker ships its "
        "buffered phase events + spans to the controller.")
_define("RTPU_TASK_EVENTS_BUF", int, 4096,
        "Per-worker flight-recorder buffer (bounded deque): oldest phase "
        "events drop first when the controller is unreachable longer than "
        "the buffer covers.")
_define("RTPU_SPANS_MAX", int, 20000,
        "Controller-side ring of finished tracing spans shipped by worker "
        "flight recorders (serves get_cluster_spans()).")
_define("RTPU_LOG_TO_DRIVER", bool, True,
        "Tee worker stdout/stderr to connected drivers' consoles.")
_define("RTPU_WORKER_LOG_MAX", int, 16 * 1024 * 1024,
        "Rotate a worker's log file to a .1 backup when it exceeds this "
        "on (re)open (the sidecar attribution index rotates with it).")
_define("RTPU_LOG_ATTRIBUTION", bool, True,
        "Stamp worker log files with task/actor attribution markers and "
        "maintain a per-file task->byte-range index so `rtpu logs "
        "--task-id` fetches one task's output without scanning "
        "(reference: the log_monitor magic-line protocol). 0 disables; "
        "the write path then pays one flag check per write.")
_define("RTPU_EVENTS", bool, True,
        "Cluster event subsystem (core/events.py): structured node/actor/"
        "task/placement-group/autoscaler lifecycle events in a bounded "
        "controller ring, persisted as JSONL alongside --state-path and "
        "served by `rtpu events` / state.list_events (reference: `ray "
        "list cluster-events` + the dashboard event feed). 0 disables; "
        "emit sites then pay one flag check.")
_define("RTPU_EVENTS_MAX", int, 10000,
        "Controller-side cluster-event ring size (and the number of "
        "persisted JSONL lines reloaded after a controller bounce).")
_define("RTPU_EVENTS_FLUSH_S", float, 0.5,
        "Flush period for worker/driver-side cluster events shipped to "
        "the controller in batches.")
_define("RTPU_EVENTS_BUF", int, 2048,
        "Per-process bounded buffer of unshipped cluster events: oldest "
        "drop first when the controller is unreachable longer than the "
        "buffer covers.")
_define("RTPU_JOBS_FT", bool, True,
        "Durable job plane (core/job_manager.py + jobs.py): the controller "
        "owns a persisted job table, the per-job supervisor is a "
        "restartable checkpointed actor whose attempts survive worker "
        "SIGKILL / node death / drain preemption under a capped-"
        "exponential retry budget, job output streams into the worker-log "
        "plane, and wait_job becomes a controller long-poll (reference: "
        "GcsJobManager + dashboard/modules/job JobSupervisor semantics). "
        "0 keeps the legacy fail-fast supervisor: job dies with its "
        "worker, in-memory logs, busy-poll waits.")
_define("RTPU_JOB_MAX_ATTEMPTS", int, 3,
        "Default entrypoint attempt budget per job (submit_job "
        "max_attempts overrides). Crashed/failed attempts consume budget; "
        "attempts lost to a draining/preempted node never do (the "
        "PR 4/16 planned-departure convention).")
_define("RTPU_JOB_BACKOFF_BASE_S", float, 0.5,
        "Base delay of the capped-exponential backoff between billed job "
        "attempts (retry n sleeps min(base * 2^(n-1), RTPU_JOB_BACKOFF_"
        "MAX_S)); preemption-driven restarts relaunch immediately.")
_define("RTPU_JOB_BACKOFF_MAX_S", float, 30.0,
        "Upper bound on the exponential backoff between job attempts.")
_define("RTPU_JOB_STOP_GRACE_S", float, 3.0,
        "stop_job escalation grace: SIGTERM the entrypoint's whole "
        "process group, wait this long, then SIGKILL whatever survives "
        "(shell=True children included) and reap before returning.")
_define("RTPU_JOB_SUP_CHECKPOINT_S", float, 5.0,
        "checkpoint_interval_s applied to FT job supervisor actors: the "
        "hosting worker durably snapshots the supervisor (attempt number "
        "+ child-pid state) this often, so a restore resumes attempt "
        "accounting instead of starting cold. 0 disables supervisor "
        "checkpoints (the controller job table still survives).")
_define("RTPU_JOBS_MAX", int, 1000,
        "Bound on the controller job table: once exceeded, the oldest "
        "TERMINAL job records are evicted (running jobs are never "
        "dropped).")
_define("RTPU_JOB_ID", str, None,
        "Set by the job supervisor in every entrypoint's environment: the "
        "submission id of the job this driver belongs to. Resumable "
        "drivers key their checkpoints/DataIterator resume_key off it.",
        external=True)
_define("RTPU_JOB_ATTEMPT", str, None,
        "Set by the job supervisor in every entrypoint's environment: "
        "1-based attempt number of this launch. Attempt 1 starts cold; "
        "attempt >1 should restore from RTPU_JOB_ID-keyed state instead "
        "of restarting from scratch.",
        external=True)
_define("RTPU_HANG_WATCHDOG", bool, True,
        "Controller watchdog sweeping running tasks/actor calls for hangs "
        "and stragglers: a task older than max(RTPU_HANG_MIN_S, "
        "RTPU_HANG_P99_FACTOR x its label's exec-latency p99) emits a "
        "TASK_HUNG/TASK_STRAGGLER cluster event with an automatic "
        "all-thread stack capture from the executing worker (reference: "
        "the LlamaRL silent-hang failure mode; `ray stack` made "
        "automatic). 0 disables the sweep entirely.")
_define("RTPU_HANG_MIN_S", float, 300.0,
        "Hard floor before the hang watchdog flags any task — no label "
        "history can lower the threshold below this.")
_define("RTPU_HANG_P99_FACTOR", float, 10.0,
        "Straggler threshold multiplier over the label's observed "
        "exec-latency p99 (from the rtpu_task_exec_s histogram).")
_define("RTPU_HANG_POLL_S", float, 2.0,
        "Hang-watchdog sweep period.")
_define("RTPU_EXIT_DETAIL_BYTES", int, 2048,
        "On worker death, quote up to this many bytes of the crashed "
        "process's log tail in the task/actor error surfaced to the "
        "driver (reference: RayTaskError exit_detail); 0 disables the "
        "post-mortem fetch.")
_define("RTPU_TSDB", bool, True,
        "In-controller metrics history (core/telemetry.py): every "
        "registered metric family (core rtpu_* gauges/counters/histograms "
        "plus util/metrics.py app metrics) is sampled into a fixed-step "
        "ring buffer served by the query_metrics RPC and `rtpu top` / the "
        "dashboard sparklines (reference: the Ray dashboard's built-in "
        "time-series view). 0 disables the sampler loop entirely; "
        "query_metrics then reports disabled.")
_define("RTPU_TSDB_STEP_S", float, 5.0,
        "Telemetry sampling step: one point per series per step.")
_define("RTPU_TSDB_RETAIN", int, 720,
        "Points retained per series (ring buffer length); with the "
        "default 5s step this holds one hour of history.")
_define("RTPU_TSDB_PERSIST_S", float, 15.0,
        "How often the telemetry ring (and alert state) is persisted "
        "beside --state-path so history survives a controller bounce. "
        "0 persists only on clean shutdown.")
_define("RTPU_ALERT_RULES", str, None,
        "JSON list of alert rules evaluated over the telemetry ring each "
        "sampling step, merged by name over the built-in defaults "
        "(telemetry.DEFAULT_ALERT_RULES). Rule: {name, metric, stat?, "
        "tags?, op, threshold, for_s, severity?, message?, disabled?}. "
        "Firing/resolving rules emit ALERT_FIRING/ALERT_RESOLVED cluster "
        "events (rtpu events --kind ALERT_FIRING).")
_define("RTPU_PROFILER", bool, True,
        "Cluster flamegraph profiler (core/profiler.py): the profile RPC "
        "fans a pure-Python sys._current_frames() wall-clock sampler out "
        "to workers and merges collapsed stacks (reference: py-spy-based "
        "`ray stack` / dashboard flamegraphs, without the py-spy "
        "dependency). 0 rejects profile requests; workers never sample.")
_define("RTPU_PROFILER_HZ", float, 67.0,
        "Default sampling frequency of the wall-clock profiler.")
_define("RTPU_CALLSITE", bool, False,
        "Record the creating Python callsite (file:line) of every owned "
        "object ref in the ownership census (reference: "
        "RAY_record_ref_creation_sites). Adds a stack walk per put/task "
        "submission, so it is off by default and perf-guarded; enable "
        "when hunting a leak so `rtpu memory --group-by callsite` can "
        "name the allocating line.")
_define("RTPU_CENSUS", bool, True,
        "Cluster object census (`rtpu memory`, state.summarize_objects, "
        "the dashboard /objects page): each process's ownership table "
        "records owner/size/tier/pins per ref and answers the "
        "controller's object_census fan-out. 0 skips all per-ref census "
        "bookkeeping (the ref hot path pays one flag check) and census "
        "RPCs report disabled.")
_define("RTPU_CENSUS_TIMEOUT_S", float, 2.0,
        "Deadline for the object_census worker fan-out; shards that miss "
        "it (dead or wedged processes) are reported as per-shard error "
        "strings while survivors' totals still aggregate.")
_define("RTPU_LEAK_WATCHDOG", bool, True,
        "Leak watchdog (needs RTPU_EVENTS): periodically flags directory "
        "objects older than RTPU_LEAK_AGE_S whose owning process is dead "
        "or unreachable with an OBJECT_LEAK_SUSPECT event (once per "
        "object). 0 disables the sweep entirely.")
_define("RTPU_LEAK_AGE_S", float, 300.0,
        "Minimum age before an object with a dead/unreachable owner is "
        "flagged as OBJECT_LEAK_SUSPECT.")
_define("RTPU_LEAK_POLL_S", float, 10.0,
        "Leak-watchdog sweep period.")
_define("RTPU_DATA_PROGRESS", bool, False,
        "Per-operator progress lines from the streaming data executor "
        "(one stderr line per operator every RTPU_DATA_PROGRESS_S while "
        "a stage runs, reference: Ray Data's ProgressBar rows). Off by "
        "default: interactive use only.")
_define("RTPU_DATA_PROGRESS_S", float, 5.0,
        "Seconds between data-executor progress lines when "
        "RTPU_DATA_PROGRESS is on.")
_define("RTPU_DATA_STATS_ROWS", int, 256,
        "Per-operator bound on retained per-batch stat rows in the "
        "streaming executor (bounded deque + running aggregates keep "
        "Dataset.stats() O(1) memory on long streams).")

# -- serve: deadlines, admission control, circuit breaking -------------------
_define("RTPU_SERVE_ADMISSION", bool, True,
        "Overload protection in the serve router: bounded per-deployment "
        "queues (shed with BackPressureError -> HTTP 503 + Retry-After), "
        "per-replica circuit breakers the power-of-two picker skips, and "
        "a retry budget capped as a fraction of admitted traffic. 0 "
        "restores the legacy unbounded-queue router; the request path "
        "then pays exactly one flag check.")
_define("RTPU_SERVE_MAX_QUEUED", int, 100,
        "Default per-deployment queued-request bound (queued = accepted "
        "by routers beyond the replicas' max_ongoing_requests capacity) "
        "when the deployment does not set max_queued_requests. -1 means "
        "unbounded.")
_define("RTPU_SERVE_REQUEST_TIMEOUT_S", float, 60.0,
        "Default end-to-end deadline for serve requests that do not carry "
        "an explicit one (HTTP X-Request-Timeout-S header, gRPC envelope "
        "timeout_s, or handle .options(deadline_s=...)). Expired work is "
        "dropped with DeadlineExceededError at every queue boundary "
        "instead of executing. <=0 means no default deadline.")
_define("RTPU_SERVE_READY_TIMEOUT_S", float, 60.0,
        "How long serve.run() waits for a deployment's replicas to become "
        "ready before raising (was a hard-coded 60s).")
_define("RTPU_SERVE_BREAKER_THRESHOLD", int, 5,
        "Consecutive failures/timeouts on one replica before its circuit "
        "breaker opens and the router routes around it.")
_define("RTPU_SERVE_BREAKER_COOLDOWN_S", float, 5.0,
        "How long an open replica breaker waits before letting one "
        "half-open probe request through.")
_define("RTPU_SERVE_RETRY_BUDGET", float, 0.2,
        "Retry budget as a fraction of admitted traffic: each admitted "
        "request earns this many retry tokens (bucket capped at 10x), "
        "each retry spends one. Prevents retry amplification during an "
        "outage.")

# -- serve: disaggregated LLM plane (prefill/decode pools, prefix cache) -----
_define("RTPU_SERVE_DISAGG", bool, True,
        "Disaggregated LLM serving: build_disagg_llm_deployment splits "
        "prefill and decode into separately-scaled replica pools with a "
        "streamed K/V handoff between them. 0 collapses the builder to "
        "the unified continuous-batching deployment (identical request/"
        "response behavior, one pool).")
_define("RTPU_SERVE_DISAGG_RETRIES", int, 3,
        "How many times the disagg ingress re-dispatches a token stream "
        "to another decode replica after a mid-stream replica failure "
        "before surfacing the error to the client.")
_define("RTPU_PREFIX_CACHE", bool, True,
        "Decode-replica prefix cache: prefilled K/V keyed by token-prefix "
        "hash stays resident (LRU by KV bytes), so repeated prompts skip "
        "prefill entirely. 0 disables lookup, insert, and the "
        "controller-side cluster index.")
_define("RTPU_PREFIX_CACHE_MAX_MB", float, 256.0,
        "Per-replica prefix-cache budget in MiB of cached K/V (+logits) "
        "bytes; least-recently-used entries evict past it.")
_define("RTPU_PREFIX_CACHE_PROMOTE_HITS", int, 3,
        "Cluster-index promotion threshold: once a prefix accumulates "
        "this many cluster-wide hits, the serve controller broadcasts it "
        "to decode replicas that don't hold it yet. <=0 disables "
        "promotion.")
_define("RTPU_SERVE_AUTOSCALE", bool, True,
        "Signal-driven serve autoscaler: pool replica counts follow TTFT "
        "p99 / slot occupancy / queue depth through the AlertEngine's "
        "threshold+for-duration machinery for deployments that set a "
        "scaling_policy. 0 freezes pools at their deployed size (the "
        "legacy queue-length autoscaling_config path is unaffected).")
_define("RTPU_SERVE_DRAIN_DEADLINE_S", float, 30.0,
        "Scale-down grace: a draining replica stops receiving new "
        "requests immediately (routers drop it on version bump) but is "
        "only killed once idle or after this many seconds, so in-flight "
        "streams finish across a resize.")
_define("RTPU_SERVE_SCALE_COOLDOWN_S", float, 5.0,
        "Minimum seconds between two autoscaler actions on the same "
        "deployment, bounding resize churn.")
_define("RTPU_SERVE_TRACE", bool, True,
        "Per-request serving trace plane: every hop (proxy, router "
        "assign, replica, batch seal, engine slot wait, prefill, KV "
        "handoff, token stream) emits a span on its host's monotonic "
        "clock, finished requests ship to the controller's request "
        "ledger (`rtpu serve requests` / `rtpu serve trace ID`), and the "
        "engine records per-token timelines into rtpu_serve_itl_s. 0 "
        "reduces the whole plane to one flag check per hop.")
_define("RTPU_SERVE_STALL_S", float, 30.0,
        "Stream-stall detector threshold: a live generation slot that "
        "emits no token for this many seconds raises one STREAM_STALLED "
        "event (per request) with the replica's all-thread stack capture "
        "attached. <=0 disables the detector.")
_define("RTPU_SERVE_LEDGER_MAX", int, 2048,
        "Controller request-ledger capacity (finished serve request "
        "records with their spans). Past it, LRU rows evict — except "
        "SLO-miss / shed / deadline-exceeded rows, which are only "
        "reclaimed once every unflagged row is gone.")
_define("RTPU_SERVE_SLO_MS", float, 0.0,
        "Serving latency SLO in milliseconds: finished requests slower "
        "than this count into rtpu_serve_slo_miss_total, are retained "
        "ahead of LRU eviction in the request ledger, and feed the "
        "serve_slo_miss_rate_high alert rule. <=0 means no latency SLO "
        "(shed / deadline-exceeded outcomes still count as misses).")

# -- bench -------------------------------------------------------------------
_define("RTPU_BENCH_TPU_TIMEOUT", int, 1500,
        "bench.py per-attempt TPU wall clock budget (seconds).")
_define("RTPU_BENCH_CPU_TIMEOUT", int, 900,
        "bench.py CPU-fallback wall clock budget (seconds).")

# -- external (documented, not owned) ----------------------------------------
_define("JAX_PLATFORMS", str, None,
        "JAX platform list; ray_tpu honors and may set it to 'cpu' for "
        "virtual-mesh tests.", external=True)
_define("XLA_FLAGS", str, None,
        "XLA flags; cpu_mesh_env appends "
        "--xla_force_host_platform_device_count.", external=True)
_define("TPU_ACCELERATOR_TYPE", str, None,
        "GCE metadata accelerator type (e.g. v5litepod-16); used for "
        "generation detection.", external=True)
_define("TPU_NAME", str, None,
        "TPU pod/slice name from GCE/GKE metadata; when set, the node "
        "advertises the per-pod custom resource {TPU_NAME: 1} "
        "(reference tpu.py:335-398 scheme).", external=True)
_define("TPU_WORKER_ID", str, None,
        "Worker index within a TPU pod; worker 0 additionally advertises "
        "TPU-<type>-head: 1.", external=True)
_define("TPU_VISIBLE_CHIPS", str, None,
        "Comma-separated chip ids visible to this process (the TPU analog "
        "of CUDA_VISIBLE_DEVICES; reference tpu.py TPU_VISIBLE_CHIPS).",
        external=True)


# Hot-path environment access: os.environ.get pays encodekey + a decoded
# copy on every call (~2us), and flag reads sit on the per-call submit and
# execute paths. os._Environ keeps the real mapping in ``_data`` keyed by
# ENCODED names; reading it directly with a precomputed key skips both
# costs while staying write-coherent (os.environ.__setitem__/__delitem__ —
# including monkeypatch.setenv — mutate the same dict). Fallback to the
# public API when the implementation detail is absent.
_env_data = getattr(os.environ, "_data", None)
try:
    _encode_key = os.environ.encodekey  # type: ignore[attr-defined]
except AttributeError:
    _env_data = None
    _encode_key = None
_keyb: Dict[str, Any] = {}


def _env_raw(name: str) -> Optional[str]:
    if _env_data is None:
        return os.environ.get(name)
    kb = _keyb.get(name)
    if kb is None:
        kb = _keyb[name] = _encode_key(name)
    raw = _env_data.get(kb)
    if raw is None:
        return None
    return os.fsdecode(raw)


def get(name: str, default: Any = None) -> Any:
    """Read a registered flag from the environment (call-time).

    ``default`` overrides the registered default when the flag is unset
    (for call sites with contextual fallbacks).
    """
    f = REGISTRY[name]
    raw = _env_raw(name)
    if raw is None:
        return default if default is not None else f.default
    if f.type is bool:
        return raw.strip().lower() not in ("0", "", "false", "no")
    if f.type in (int, float):
        return f.type(raw)
    return raw


def is_set(name: str) -> bool:
    REGISTRY[name]  # typo guard
    return name in os.environ


def raw(name: str) -> Optional[str]:
    """Uncoerced environment value — for error paths that must not re-raise
    on a malformed value."""
    REGISTRY[name]
    return os.environ.get(name)


def set_env(name: str, value: Any) -> None:
    """Set a registered flag in this process's environment (the sanctioned
    write path for process-tree plumbing flags)."""
    REGISTRY[name]  # typo guard
    os.environ[name] = str(value)


def unset_env(name: str) -> None:
    REGISTRY[name]
    os.environ.pop(name, None)


def set_raw(name: str, value: str) -> None:
    """Set an UNregistered environment variable (user runtime_env env_vars —
    arbitrary names the registry cannot enumerate)."""
    os.environ[name] = value


def child_env(**overrides: str) -> Dict[str, str]:
    """Snapshot of the current environment for spawning subprocesses."""
    env = dict(os.environ)
    env.update({k: str(v) for k, v in overrides.items()})
    return env


def describe() -> str:
    lines = []
    for f in sorted(REGISTRY.values(), key=lambda f: (f.external, f.name)):
        tag = " (external)" if f.external else ""
        lines.append(f"{f.name}{tag} [{f.type.__name__}, "
                     f"default={f.default!r}]\n    {f.doc}")
    return "\n".join(lines)


if __name__ == "__main__":
    print(describe())
