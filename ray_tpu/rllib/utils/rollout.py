"""Fixed-length rollout fragments: the throughput-oriented sample format.

Parity: the reference's high-throughput path samples fixed
rollout_fragment_length column batches per env runner (reference
rllib/env/single_agent_env_runner.py:127 with vector envs; IMPALA's
sample queue carries exactly such fragments). Episode objects cost a
Python loop per env per step; fragments are preallocated [T, N] arrays
the sampler fills with pure vector ops — the difference between ~3k and
~100k+ env-steps/s per runner.

Fragment layout (dict of arrays):
    obs        [T, N, ...]  observation fed to the policy at step t
    actions    [T, N]
    logp       [T, N] f32   behavior log-prob
    vf         [T, N] f32   V(obs[t])
    rewards    [T, N] f32
    dones      [T, N] bool  episode ended AT t (term or trunc)
    truncs     [T, N] bool  ended by truncation (bootstrap needed)
    valid      [T, N] f32   0 at autoreset rows (gymnasium NEXT_STEP mode)
    bootstrap  [N]   f32    V(obs after the fragment) per column
    episode_returns list[float]  returns of episodes completed in-fragment
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from .gae import compute_gae


def fragments_to_ppo_batch(
    frags: Sequence[Dict[str, Any]],
    *,
    gamma: float,
    lam: float,
    standardize: bool = True,
) -> Dict[str, np.ndarray]:
    """Fragments -> flat transition batch with GAE advantages.

    GAE runs vectorized over [N_total, T] columns. Truncation bootstrap:
    the value of a truncated episode's final observation is exactly the
    vf recorded at the FOLLOWING row (the autoreset row sees the final
    obs, gymnasium NEXT_STEP) or the fragment bootstrap when truncation
    lands on the last row — folded into the reward, the same trick
    episodes_to_batch uses, so the scan needs no special cases.
    """
    obs = np.concatenate([f["obs"] for f in frags], axis=1)
    actions = np.concatenate([f["actions"] for f in frags], axis=1)
    logp = np.concatenate([f["logp"] for f in frags], axis=1)
    vf = np.concatenate([f["vf"] for f in frags], axis=1)
    rewards = np.concatenate([f["rewards"] for f in frags], axis=1).copy()
    dones = np.concatenate([f["dones"] for f in frags], axis=1)
    truncs = np.concatenate([f["truncs"] for f in frags], axis=1)
    valid = np.concatenate([f["valid"] for f in frags], axis=1)
    bootstrap = np.concatenate([f["bootstrap"] for f in frags], axis=0)

    T, N = rewards.shape
    # Fold the truncation bootstrap into the truncated step's reward.
    t_idx, n_idx = np.nonzero(truncs)
    if t_idx.size:
        nxt_vf = np.where(t_idx + 1 < T, vf[np.minimum(t_idx + 1, T - 1), n_idx],
                          bootstrap[n_idx])
        rewards[t_idx, n_idx] += gamma * nxt_vf
    # Columns whose fragment was cut mid-episode bootstrap via the [N]
    # value; columns that ended exactly at T-1 have dones=1 there, which
    # zeroes the bootstrap term inside the scan.
    adv, vtarg = compute_gae(
        rewards.T, vf.T, dones.T.astype(np.float32), bootstrap,
        gamma=gamma, lam=lam)
    adv = np.asarray(adv).T
    vtarg = np.asarray(vtarg).T

    mask = valid.astype(np.float32)
    if standardize:
        sel = mask > 0
        a = adv[sel]
        adv = (adv - a.mean()) / (a.std() + 1e-8)

    def flat(x):
        return x.reshape(T * N, *x.shape[2:])

    return {
        "obs": flat(obs),
        "actions": flat(actions),
        "logp": flat(logp).astype(np.float32),
        "advantages": flat(adv).astype(np.float32),
        "value_targets": flat(vtarg).astype(np.float32),
        "mask": flat(mask),
    }
