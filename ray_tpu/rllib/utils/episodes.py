"""Episode container + batch building.

Parity: reference rllib/env/single_agent_episode.py (episode as the sampling
currency of the new API stack) and policy/sample_batch.py (column batches).
Episodes are plain numpy on the CPU sampling side; batches are dense
[B, T] arrays padded to a fixed T so the learner's jitted update sees ONE
static shape (dynamic shapes would recompile XLA every iteration).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class SingleAgentEpisode:
    """A (chunk of an) episode collected by an env runner."""

    observations: List[Any] = dataclasses.field(default_factory=list)
    actions: List[Any] = dataclasses.field(default_factory=list)
    rewards: List[float] = dataclasses.field(default_factory=list)
    logp: List[float] = dataclasses.field(default_factory=list)
    vf_preds: List[float] = dataclasses.field(default_factory=list)
    terminated: bool = False
    truncated: bool = False
    # value estimate of the obs AFTER the last action (bootstrap); 0 if
    # terminated.
    bootstrap_value: float = 0.0

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def is_done(self) -> bool:
        return self.terminated or self.truncated

    def total_reward(self) -> float:
        return float(sum(self.rewards))


def episodes_to_batch(
    episodes: List[SingleAgentEpisode],
    max_t: int,
    *,
    gamma: Optional[float] = None,
) -> Dict[str, np.ndarray]:
    """Pack episodes into padded [B, T] columns with a validity mask.

    With `gamma` set, each row's bootstrap value is FOLDED into its last
    valid reward (r[T-1] += gamma * V_boot) and dones[T-1] is set — the
    classic truncation-bootstrap trick. This makes GAE/v-trace exact per row
    regardless of padding (cont=0 at the true last step blocks the reverse
    scan from pulling padded-garbage values into the valid region, and the
    bootstrap lands at the right step instead of the padded column). Rows
    clipped at max_t mid-episode bootstrap from the recorded V(obs[max_t]).
    """
    B = len(episodes)
    obs0 = np.asarray(episodes[0].observations[0])
    obs_shape = obs0.shape
    obs_dtype = obs0.dtype
    act0 = np.asarray(episodes[0].actions[0])

    obs = np.zeros((B, max_t) + obs_shape, obs_dtype)
    actions = np.zeros((B, max_t) + act0.shape, act0.dtype)
    rewards = np.zeros((B, max_t), np.float32)
    logp = np.zeros((B, max_t), np.float32)
    vf = np.zeros((B, max_t), np.float32)
    dones = np.zeros((B, max_t), np.float32)
    mask = np.zeros((B, max_t), np.float32)
    bootstrap = np.zeros((B,), np.float32)

    for i, ep in enumerate(episodes):
        T = min(len(ep), max_t)
        obs[i, :T] = np.asarray(ep.observations[:T])
        actions[i, :T] = np.asarray(ep.actions[:T])
        rewards[i, :T] = np.asarray(ep.rewards[:T], np.float32)
        logp[i, :T] = np.asarray(ep.logp[:T], np.float32)
        vf[i, :T] = np.asarray(ep.vf_preds[:T], np.float32)
        mask[i, :T] = 1.0
        if T < len(ep):
            # Clipped at max_t mid-episode: the sampler recorded
            # V(obs[T]) as vf_preds[T] — that's the exact bootstrap.
            boot = float(ep.vf_preds[T])
            terminal = False
        elif ep.terminated:
            boot = 0.0
            terminal = True
        else:  # truncated by the env or cut at the rollout boundary
            boot = ep.bootstrap_value
            terminal = False
        if gamma is not None:
            rewards[i, T - 1] += gamma * boot
            dones[i, T - 1] = 1.0
            bootstrap[i] = 0.0
        else:
            if terminal:
                dones[i, T - 1] = 1.0
            bootstrap[i] = boot
    return {
        "obs": obs,
        "actions": actions,
        "rewards": rewards,
        "logp": logp,
        "vf_preds": vf,
        "dones": dones,
        "mask": mask,
        "bootstrap_value": bootstrap,
    }


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def pad_batch_to_buckets(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Pad B and T up to powers of two (zero rows, mask 0) so the learner's
    jitted update sees a small, finite set of shapes instead of recompiling
    for every (num_episodes, max_len) the sampler happens to produce."""
    B, T = batch["rewards"].shape
    B2, T2 = _next_pow2(B), _next_pow2(T)
    if B2 == B and T2 == T:
        return batch
    out = {}
    for k, v in batch.items():
        if v.ndim == 1:  # [B]
            pad = [(0, B2 - B)]
        else:            # [B, T, ...]
            pad = [(0, B2 - B), (0, T2 - T)] + [(0, 0)] * (v.ndim - 2)
        out[k] = np.pad(v, pad)
    return out
