"""Replay buffer suite (reference: rllib/utils/replay_buffers/ —
replay_buffer.py uniform sampling, prioritized_episode_buffer.py
proportional prioritization with importance weights).

Design: buffers are HOST-side ring stores over preallocated numpy columns
(observations may be images — device memory is for the learner), generic
over action dtype/shape so both discrete (DQN) and continuous (SAC)
algorithms share them. ``sample()`` returns a flat dict of arrays that
drops straight into a jitted learner update. Prioritized sampling uses a
Fenwick (binary indexed) tree: O(log n) priority updates and O(log n)
proportional draws — the array-backed analog of the reference's segment
tree (rllib/execution/segment_tree.py)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform FIFO transition buffer.

    Columns: obs, next_obs, actions, rewards, dones. ``action_shape`` /
    ``action_dtype`` default to scalar int32 (discrete); SAC passes
    ``action_shape=(act_dim,), action_dtype=np.float32``."""

    def __init__(self, capacity: int, obs_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 action_dtype=np.int32):
        self.capacity = int(capacity)
        self.size = 0
        self.pos = 0
        self.obs = np.zeros((capacity, *obs_shape), np.float32)
        self.next_obs = np.zeros((capacity, *obs_shape), np.float32)
        self.actions = np.zeros((capacity, *action_shape), action_dtype)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)

    # ------------------------------------------------------------------ add

    def add(self, obs, next_obs, action, reward, done) -> int:
        """Add one transition; returns the slot index it landed in."""
        i = self.pos
        self.obs[i] = obs
        self.next_obs[i] = next_obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.dones[i] = done
        self.pos = (self.pos + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)
        return i

    def add_episodes(self, episodes: Sequence) -> int:
        """Flatten SingleAgentEpisode objects into transitions."""
        n = 0
        for ep in episodes:
            T = len(ep.actions)
            for t in range(T):
                nxt = ep.observations[t + 1] if t + 1 < len(ep.observations) \
                    else ep.observations[t]
                done = float(ep.terminated and t == T - 1)
                self.add(ep.observations[t], nxt, ep.actions[t],
                         ep.rewards[t], done)
                n += 1
        return n

    # --------------------------------------------------------------- sample

    def sample(self, batch_size: int,
               rng: np.random.Generator) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, batch_size)
        return self._rows(idx)

    def _rows(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }

    def __len__(self) -> int:
        return self.size


class _FenwickTree:
    """Prefix-sum tree over ``n`` slots (1-indexed internally)."""

    def __init__(self, n: int):
        self.n = n
        self.tree = np.zeros(n + 1, np.float64)
        self.values = np.zeros(n, np.float64)

    def set(self, i: int, value: float) -> None:
        delta = value - self.values[i]
        self.values[i] = value
        j = i + 1
        while j <= self.n:
            self.tree[j] += delta
            j += j & (-j)

    def total(self) -> float:
        return self._prefix(self.n)

    def _prefix(self, i: int) -> float:
        s = 0.0
        while i > 0:
            s += self.tree[i]
            i -= i & (-i)
        return s

    def find_prefix(self, mass: float) -> int:
        """Largest index whose prefix sum is < mass (proportional draw)."""
        idx = 0
        bit = 1 << (self.n.bit_length())
        while bit:
            nxt = idx + bit
            if nxt <= self.n and self.tree[nxt] < mass:
                idx = nxt
                mass -= self.tree[nxt]
            bit >>= 1
        return min(idx, self.n - 1)


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    prioritized_episode_buffer.py; Schaul et al. 2016).

    ``sample`` additionally returns ``weights`` (importance corrections,
    normalized to max 1) and ``idx`` (pass back to ``update_priorities``
    with the new |TD errors|)."""

    def __init__(self, capacity: int, obs_shape: Tuple[int, ...],
                 action_shape: Tuple[int, ...] = (),
                 action_dtype=np.int32,
                 alpha: float = 0.6, beta: float = 0.4,
                 eps: float = 1e-6):
        super().__init__(capacity, obs_shape, action_shape, action_dtype)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        self._tree = _FenwickTree(self.capacity)
        self._max_priority = 1.0

    def add(self, obs, next_obs, action, reward, done) -> int:
        i = super().add(obs, next_obs, action, reward, done)
        # New transitions get max priority so everything is seen at least
        # once before its priority decays (reference behavior).
        self._tree.set(i, self._max_priority ** self.alpha)
        return i

    def sample(self, batch_size: int,
               rng: np.random.Generator) -> Dict[str, np.ndarray]:
        total = self._tree._prefix(self.capacity)
        if total <= 0:
            return super().sample(batch_size, rng)
        # Stratified proportional draws (one uniform per segment).
        seg = total / batch_size
        mass = (np.arange(batch_size) + rng.random(batch_size)) * seg
        idx = np.array([self._tree.find_prefix(m) for m in mass], np.int64)
        idx = np.minimum(idx, max(self.size - 1, 0))
        out = self._rows(idx)
        probs = self._tree.values[idx] / total
        # IS weights: (N * P(i))^-beta, normalized by the max weight.
        weights = (self.size * np.maximum(probs, 1e-12)) ** (-self.beta)
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["idx"] = idx
        return out

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(np.asarray(td_errors, np.float64)) + self.eps
        for i, p in zip(np.asarray(idx, np.int64), prios):
            self._tree.set(int(i), float(p) ** self.alpha)
            self._max_priority = max(self._max_priority, float(p))


def make_buffer(config: Optional[Dict], capacity: int,
                obs_shape: Tuple[int, ...],
                action_shape: Tuple[int, ...] = (),
                action_dtype=np.int32) -> ReplayBuffer:
    """Config-driven construction (reference: replay_buffer_config dicts,
    {"type": "PrioritizedEpisodeReplayBuffer", "alpha": ..., "beta": ...})."""
    cfg = dict(config or {})
    btype = str(cfg.pop("type", "uniform")).lower()
    if "prior" in btype:
        return PrioritizedReplayBuffer(
            capacity, obs_shape, action_shape, action_dtype,
            alpha=float(cfg.get("alpha", 0.6)),
            beta=float(cfg.get("beta", 0.4)))
    return ReplayBuffer(capacity, obs_shape, action_shape, action_dtype)
