"""Advantage estimators as jittable scans.

Parity: reference rllib/evaluation/postprocessing.py compute_advantages
(GAE) and rllib/algorithms/impala/vtrace_torch.py (v-trace). Both are
expressed as `lax.scan` over reversed time — compiler-friendly TPU control
flow instead of the reference's Python/torch loops.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("gamma", "lam"))
def compute_gae(
    rewards: jax.Array,      # [T] or [B, T]
    values: jax.Array,       # same shape
    dones: jax.Array,        # same shape (1.0 where episode ended at t)
    bootstrap_value: jax.Array,  # [] or [B]
    *,
    gamma: float = 0.99,
    lam: float = 0.95,
):
    """Returns (advantages, value_targets), same shape as rewards.

    jitted (static gamma/lam): the reversed-time scan would otherwise run
    eagerly — one dispatch per step, pathological on remote-dispatch
    platforms. Callers bound recompilation by padding [B, T] to powers of
    two (episodes.pad_batch_to_buckets)."""
    if rewards.ndim == 1:
        adv, vt = compute_gae(rewards[None], values[None], dones[None],
                              jnp.asarray(bootstrap_value)[None],
                              gamma=gamma, lam=lam)
        return adv[0], vt[0]

    cont = 1.0 - dones.astype(jnp.float32)
    next_values = jnp.concatenate(
        [values[:, 1:], bootstrap_value[:, None]], axis=1)
    # next value is 0 where the episode terminated at t
    deltas = rewards + gamma * next_values * cont - values

    def scan_fn(carry, xs):
        delta_t, cont_t = xs
        adv = delta_t + gamma * lam * cont_t * carry
        return adv, adv

    _, adv_rev = jax.lax.scan(
        scan_fn,
        jnp.zeros(rewards.shape[0], jnp.float32),
        (deltas.T[::-1], cont.T[::-1]),
    )
    advantages = adv_rev[::-1].T
    return advantages, advantages + values


def vtrace(
    behavior_logp: jax.Array,   # [B, T] log pi_b(a|s)
    target_logp: jax.Array,     # [B, T] log pi(a|s)
    rewards: jax.Array,         # [B, T]
    values: jax.Array,          # [B, T]
    dones: jax.Array,           # [B, T]
    bootstrap_value: jax.Array,  # [B]
    *,
    gamma: float = 0.99,
    clip_rho: float = 1.0,
    clip_c: float = 1.0,
):
    """IMPALA v-trace targets (Espeholt et al. 2018) as a reverse scan.

    Returns (vs, pg_advantages): vs are the corrected value targets; the
    policy gradient uses rho_t * (r_t + gamma*vs_{t+1} - V(s_t)).
    """
    rho = jnp.exp(target_logp - behavior_logp)
    rho_c = jnp.minimum(clip_rho, rho)
    c = jnp.minimum(clip_c, rho)
    cont = 1.0 - dones.astype(jnp.float32)

    next_values = jnp.concatenate(
        [values[:, 1:], bootstrap_value[:, None]], axis=1)
    deltas = rho_c * (rewards + gamma * next_values * cont - values)

    def scan_fn(acc, xs):
        delta_t, c_t, cont_t = xs
        acc = delta_t + gamma * cont_t * c_t * acc
        return acc, acc

    _, acc_rev = jax.lax.scan(
        scan_fn,
        jnp.zeros(rewards.shape[0], jnp.float32),
        (deltas.T[::-1], c.T[::-1], cont.T[::-1]),
    )
    vs_minus_v = acc_rev[::-1].T
    vs = values + vs_minus_v

    next_vs = jnp.concatenate([vs[:, 1:], bootstrap_value[:, None]], axis=1)
    pg_adv = rho_c * (rewards + gamma * next_vs * cont - values)
    return vs, pg_adv
