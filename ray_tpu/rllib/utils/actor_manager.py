"""Fault-tolerant actor pool for sampling/learner actors.

Parity: reference rllib/utils/actor_manager.py:196 FaultTolerantActorManager
(foreach_actor :573, probe_unhealthy_actors :823): calls fan out to a set of
actors; actors whose calls raise are marked unhealthy and skipped; restart
recreates them from the saved factory so a lost env runner never kills the
training loop.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class FaultTolerantActorManager:
    def __init__(
        self,
        actor_factory: Callable[[int], Any],
        num_actors: int,
        *,
        max_restarts: int = 3,
    ):
        self._factory = actor_factory
        self._max_restarts = max_restarts
        self._actors: Dict[int, Any] = {
            i: actor_factory(i) for i in range(num_actors)
        }
        self._healthy: Dict[int, bool] = {i: True for i in self._actors}
        self._restarts: Dict[int, int] = {i: 0 for i in self._actors}
        # Actors whose last failure carried the preempted flag (planned
        # node departure): restoring them must not consume restart budget —
        # on elastic spot capacity every preemption wave would otherwise
        # permanently shrink the pool.
        self._preempted: set = set()

    # ------------------------------------------------------------------ info

    @property
    def num_actors(self) -> int:
        return len(self._actors)

    def healthy_actor_ids(self) -> List[int]:
        return [i for i, ok in self._healthy.items() if ok]

    def actor(self, i: int):
        return self._actors[i]

    # ------------------------------------------------------------------ calls

    def foreach_actor(
        self,
        fn_name: str,
        *args,
        actor_ids: Optional[Sequence[int]] = None,
        timeout: Optional[float] = None,
        **kwargs,
    ) -> List[Tuple[int, Any]]:
        """Call method `fn_name(*args, **kwargs)` on each healthy actor;
        returns [(actor_id, result)] for the calls that succeeded and marks
        failed actors unhealthy."""
        ids = [i for i in (actor_ids or self.healthy_actor_ids())
               if self._healthy.get(i)]
        refs = {}
        for i in ids:
            try:
                refs[i] = getattr(self._actors[i], fn_name).remote(
                    *args, **kwargs)
            except Exception:
                logger.exception("submit to actor %d failed", i)
                self._healthy[i] = False
        out: List[Tuple[int, Any]] = []
        for i, ref in refs.items():
            try:
                out.append((i, ray_tpu.get(ref, timeout=timeout)))
            except Exception as e:
                logger.exception("actor %d call %s failed", i, fn_name)
                self._healthy[i] = False
                if self._is_preempted_error(e):
                    self._preempted.add(i)
        return out

    @staticmethod
    def _is_preempted_error(e: BaseException) -> bool:
        """True when the failure stems from a planned node departure
        (NodePreemptedError carries preempted=True, possibly wrapped in a
        TaskError's cause chain)."""
        seen = 0
        cur: Optional[BaseException] = e
        while cur is not None and seen < 8:
            if getattr(cur, "preempted", False):
                return True
            cur = getattr(cur, "cause", None) or cur.__cause__
            seen += 1
        return False

    @staticmethod
    def _actor_state(actor) -> str:
        from ray_tpu.core import context as ctx

        try:
            info = ctx.get_worker_context().client.request(
                {"kind": "resolve_actor", "actor_id": actor._actor_id,
                 "wait": 0})
            return info.get("state", "?")
        except Exception:
            return "?"

    def restore_unhealthy(self) -> int:
        """Recreate dead actors from the factory (bounded by max_restarts;
        preemption-flagged deaths don't count against it). Returns the
        number restored."""
        restored = 0
        for i, ok in list(self._healthy.items()):
            if ok:
                continue
            preempted = i in self._preempted
            if not preempted and self._restarts[i] >= self._max_restarts:
                continue
            # Skip the kill when the actor is already dead — killing a
            # corpse wastes an RPC and can tear down the worker that
            # meanwhile hosts the actor's restarted incarnation.
            if self._actor_state(self._actors[i]) != "dead":
                try:
                    ray_tpu.kill(self._actors[i])
                except Exception:
                    pass
            self._actors[i] = self._factory(i)
            self._healthy[i] = True
            if not preempted:
                self._restarts[i] += 1
            self._preempted.discard(i)
            restored += 1
        return restored

    def shutdown(self) -> None:
        for a in self._actors.values():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors.clear()
        self._healthy.clear()
