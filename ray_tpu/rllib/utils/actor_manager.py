"""Fault-tolerant actor pool for sampling/learner actors.

Parity: reference rllib/utils/actor_manager.py:196 FaultTolerantActorManager
(foreach_actor :573, probe_unhealthy_actors :823): calls fan out to a set of
actors; actors whose calls raise are marked unhealthy and skipped; restart
recreates them from the saved factory so a lost env runner never kills the
training loop.
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class FaultTolerantActorManager:
    def __init__(
        self,
        actor_factory: Callable[[int], Any],
        num_actors: int,
        *,
        max_restarts: int = 3,
    ):
        self._factory = actor_factory
        self._max_restarts = max_restarts
        self._actors: Dict[int, Any] = {
            i: actor_factory(i) for i in range(num_actors)
        }
        self._healthy: Dict[int, bool] = {i: True for i in self._actors}
        self._restarts: Dict[int, int] = {i: 0 for i in self._actors}

    # ------------------------------------------------------------------ info

    @property
    def num_actors(self) -> int:
        return len(self._actors)

    def healthy_actor_ids(self) -> List[int]:
        return [i for i, ok in self._healthy.items() if ok]

    def actor(self, i: int):
        return self._actors[i]

    # ------------------------------------------------------------------ calls

    def foreach_actor(
        self,
        fn_name: str,
        *args,
        actor_ids: Optional[Sequence[int]] = None,
        timeout: Optional[float] = None,
        **kwargs,
    ) -> List[Tuple[int, Any]]:
        """Call method `fn_name(*args, **kwargs)` on each healthy actor;
        returns [(actor_id, result)] for the calls that succeeded and marks
        failed actors unhealthy."""
        ids = [i for i in (actor_ids or self.healthy_actor_ids())
               if self._healthy.get(i)]
        refs = {}
        for i in ids:
            try:
                refs[i] = getattr(self._actors[i], fn_name).remote(
                    *args, **kwargs)
            except Exception:
                logger.exception("submit to actor %d failed", i)
                self._healthy[i] = False
        out: List[Tuple[int, Any]] = []
        for i, ref in refs.items():
            try:
                out.append((i, ray_tpu.get(ref, timeout=timeout)))
            except Exception:
                logger.exception("actor %d call %s failed", i, fn_name)
                self._healthy[i] = False
        return out

    def restore_unhealthy(self) -> int:
        """Recreate dead actors from the factory (bounded by max_restarts).
        Returns the number restored."""
        restored = 0
        for i, ok in list(self._healthy.items()):
            if ok:
                continue
            if self._restarts[i] >= self._max_restarts:
                continue
            try:
                ray_tpu.kill(self._actors[i])
            except Exception:
                pass
            self._actors[i] = self._factory(i)
            self._healthy[i] = True
            self._restarts[i] += 1
            restored += 1
        return restored

    def shutdown(self) -> None:
        for a in self._actors.values():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors.clear()
        self._healthy.clear()
