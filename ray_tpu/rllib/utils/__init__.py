from .actor_manager import FaultTolerantActorManager
from .episodes import SingleAgentEpisode, episodes_to_batch
from .gae import compute_gae, vtrace

__all__ = [
    "FaultTolerantActorManager",
    "SingleAgentEpisode",
    "episodes_to_batch",
    "compute_gae",
    "vtrace",
]
