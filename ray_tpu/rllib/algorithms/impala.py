"""IMPALA: asynchronous sampling with v-trace off-policy correction.

Parity: reference rllib/algorithms/impala/impala.py (async aggregation +
learner thread, `make_learner_thread` :512, broadcast_interval :130). The
TPU shape of it: env-runner actors keep sample futures permanently in
flight; the driver drains whichever is ready (`ray_tpu.wait`), feeds the
jitted v-trace update, and re-arms the runner — weights broadcast every
`broadcast_interval` updates, so sampling is off-policy by a bounded lag
exactly as in the reference (no learner thread needed: the jitted update IS
the learner, and dispatch overhead is one wait()).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from ..utils.episodes import episodes_to_batch, pad_batch_to_buckets
from ..utils.gae import vtrace


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or IMPALA)
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.clip_rho_threshold: float = 1.0
        self.clip_c_threshold: float = 1.0
        self.broadcast_interval: int = 1
        self.updates_per_step: int = 4  # learner updates per training_step
        self.num_epochs = 1  # v-trace assumes fresh-ish behavior policy


class IMPALALearner(JaxLearner):
    def __init__(self, module, cfg: IMPALAConfig, **kw):
        self.cfg = cfg
        super().__init__(module, lr=cfg.lr, grad_clip=cfg.grad_clip, **kw)

    def loss(self, params, batch, rng):
        cfg = self.cfg
        B, T = batch["rewards"].shape
        obs = batch["obs"].reshape((B * T,) + batch["obs"].shape[2:])
        out = self.module.forward(params, obs)
        logits = out["logits"].reshape(B, T, -1)
        values = out["vf"].reshape(B, T)

        dist = self.module.action_dist(logits)
        target_logp = dist.logp(batch["actions"])
        entropy = dist.entropy()

        vs, pg_adv = vtrace(
            batch["logp"], target_logp, batch["rewards"],
            values, batch["dones"], batch["bootstrap_value"],
            gamma=cfg.gamma,
            clip_rho=cfg.clip_rho_threshold,
            clip_c=cfg.clip_c_threshold,
        )
        vs = jax.lax.stop_gradient(vs)
        pg_adv = jax.lax.stop_gradient(pg_adv)

        mask = batch["mask"]
        msum = jnp.maximum(mask.sum(), 1.0)
        pi_loss = -(target_logp * pg_adv * mask).sum() / msum
        vf_loss = (((values - vs) ** 2) * mask).sum() / msum
        ent = (entropy * mask).sum() / msum
        total = (pi_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * ent)
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": ent,
        }


class IMPALA(Algorithm):
    config_cls = IMPALAConfig

    def setup(self, config: Dict[str, Any]) -> None:
        super().setup(config)
        self._inflight: Dict[Any, int] = {}  # sample ref -> actor id
        self._updates_since_broadcast = 0

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()
        mesh = cfg.learner_mesh

        def factory():
            return IMPALALearner(module_factory(), cfg, mesh=mesh,
                                 seed=cfg.seed)

        return factory

    # ------------------------------------------------------------- async sample

    def _arm(self, manager, actor_ids: List[int], fragment: int) -> None:
        for i in actor_ids:
            try:
                ref = manager.actor(i).sample.remote(fragment)
                self._inflight[ref] = i
            except Exception:
                manager._healthy[i] = False

    def _heal_and_arm(self, manager, cfg) -> None:
        """Every step: restore what can be restored and (re)arm any healthy
        runner with no in-flight sample. This is the ONLY reliable recovery
        trigger — a runner that died outside the drain path (e.g. during a
        weight broadcast) has no pending ref to error and would otherwise
        silently drop out of the rotation forever."""
        manager.restore_unhealthy()
        armed = set(self._inflight.values())
        idle = [i for i in manager.healthy_actor_ids() if i not in armed]
        if idle:
            # Unarmed runners may be fresh restores: give them weights first.
            weights = self.learner_group.get_weights()
            ok = {i for i, _ in manager.foreach_actor(
                "set_weights", weights, actor_ids=idle)}
            # Per-env fragment semantics: EnvRunner.sample counts timesteps
            # across all vector envs, so scale by num_envs (matches the
            # synchronous path and reference per-env fragment semantics).
            self._arm(manager, [i for i in idle if i in ok],
                      cfg.rollout_fragment_length
                      * cfg.num_envs_per_env_runner)

    def _update_from_episodes(self, episodes) -> Dict[str, float]:
        cfg = self._algo_config
        self._record_episodes(episodes)
        episodes = self._connect_episodes(episodes)
        max_t = min(cfg.max_episode_len, max(len(e) for e in episodes))
        # gamma folds the bootstrap into the last valid reward and marks it
        # done: the v-trace reverse scan then can't pull V(padded-zero-obs)
        # into valid steps, and the bootstrap lands at the true last step.
        batch = pad_batch_to_buckets(
            episodes_to_batch(episodes, max_t, gamma=cfg.gamma))
        metrics = self.learner_group.update(batch, num_epochs=1,
                                            shuffle=False)
        self._updates_since_broadcast += 1
        return metrics

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        manager = self.env_runner_group._manager
        metrics: Dict[str, float] = {}

        if manager is None:
            # Synchronous degenerate mode (local runner): still exercises the
            # v-trace math, lag = 0.
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
            for _ in range(cfg.updates_per_step):
                episodes = self.env_runner_group.sample(
                    cfg.rollout_fragment_length
                    * cfg.num_envs_per_env_runner)
                metrics = self._update_from_episodes(episodes)
            return self._result(metrics)

        # Async path: keep every healthy runner armed with one in-flight
        # sample; drain ready futures and update.
        self._heal_and_arm(manager, cfg)
        done_updates = 0
        while done_updates < cfg.updates_per_step and self._inflight:
            ready, _ = ray_tpu.wait(list(self._inflight.keys()),
                                    num_returns=1, timeout=60.0)
            if not ready:
                break
            ref = ready[0]
            actor_id = self._inflight.pop(ref)
            try:
                episodes = ray_tpu.get(ref)
            except Exception:
                # Don't re-arm the dead handle here (busy-loop on
                # ActorDiedError once past the restart budget); the
                # _heal_and_arm pass at the next training_step restores
                # and re-arms whatever is restorable.
                manager._healthy[actor_id] = False
                self._heal_and_arm(manager, cfg)
                continue
            metrics = self._update_from_episodes(episodes)
            done_updates += 1
            if self._updates_since_broadcast >= cfg.broadcast_interval:
                # Fleet-wide broadcast: syncing only the just-drained runner
                # would leave the others' policy lag unbounded.
                weights = self.learner_group.get_weights()
                manager.foreach_actor("set_weights", weights)
                self._updates_since_broadcast = 0
            if manager._healthy.get(actor_id):
                self._arm(manager, [actor_id],
                          cfg.rollout_fragment_length
                          * cfg.num_envs_per_env_runner)
        return self._result(metrics)

    def _result(self, metrics: Dict[str, float]) -> Dict[str, Any]:
        out = dict(metrics)
        out["episode_return_mean"] = self.episode_return_mean
        out["timesteps_total"] = self._timesteps_total
        return out
