"""PPO on the jax learner stack.

Parity: reference rllib/algorithms/ppo/ppo.py:395 (training_step :421 —
synchronous_parallel_sample → learner update → weight broadcast) and the
postprocessing pipeline (evaluation/postprocessing.py compute_advantages +
standardize_fields): GAE runs once per rollout on [B,T] columns, valid
transitions flatten to a transition batch, and the learner minibatch-SGDs
over timesteps — the learner update is ONE jitted program whose gradient
all-reduce rides the mesh's `data` axis.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from ..utils.episodes import _next_pow2, episodes_to_batch
from ..utils.gae import compute_gae


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or PPO)
        self.clip_param: float = 0.2
        self.vf_clip_param: float = 10.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.0
        self.lambda_: float = 0.95


class PPOLearner(JaxLearner):
    """Loss over a FLAT transition batch: obs [N,...], actions/logp/
    advantages/value_targets/mask [N]."""

    def __init__(self, module, cfg: PPOConfig, **kw):
        self.cfg = cfg
        super().__init__(module, lr=cfg.lr, grad_clip=cfg.grad_clip, **kw)

    def loss(self, params, batch, rng):
        cfg = self.cfg
        out = self.module.forward(params, batch["obs"])
        dist = self.module.action_dist(out["logits"])
        logp = dist.logp(batch["actions"])
        entropy = dist.entropy()
        vf = out["vf"]

        mask = batch["mask"]
        msum = jnp.maximum(mask.sum(), 1.0)
        adv = batch["advantages"]

        ratio = jnp.exp(logp - batch["logp"])
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv)
        pi_loss = -(surr * mask).sum() / msum

        vf_err = jnp.clip((vf - batch["value_targets"]) ** 2,
                          0.0, cfg.vf_clip_param ** 2)
        vf_loss = (vf_err * mask).sum() / msum

        ent = (entropy * mask).sum() / msum
        total = (pi_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * ent)

        approx_kl = ((batch["logp"] - logp) * mask).sum() / msum
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": ent,
            "approx_kl": approx_kl,
        }


def postprocess_episodes(
    episodes, *, gamma: float, lam: float, max_t: int,
    standardize: bool = True,
) -> Dict[str, np.ndarray]:
    """Episodes -> flat transition batch with GAE advantages (reference
    compute_advantages + standardize_fields). N is padded to a power of two
    (mask 0) so the jitted loss sees few distinct shapes."""
    # gamma folds each row's bootstrap into its last reward, so GAE is exact
    # per row regardless of padding (see episodes_to_batch docstring).
    bt = episodes_to_batch(episodes, max_t, gamma=gamma)
    # Pow2-bucket [B, T] so the jitted GAE compiles a handful of shapes
    # total instead of one per (num_episodes, max_len) the sampler emits.
    from ..utils.episodes import pad_batch_to_buckets

    bt = pad_batch_to_buckets(bt)
    adv, vtarg = compute_gae(
        bt["rewards"], bt["vf_preds"], bt["dones"], bt["bootstrap_value"],
        gamma=gamma, lam=lam)
    adv = np.asarray(adv)
    vtarg = np.asarray(vtarg)
    valid = bt["mask"] > 0
    if standardize:
        a = adv[valid]
        adv = (adv - a.mean()) / (a.std() + 1e-8)
    flat = {
        "obs": bt["obs"][valid],
        "actions": bt["actions"][valid],
        "logp": bt["logp"][valid],
        "advantages": adv[valid].astype(np.float32),
        "value_targets": vtarg[valid].astype(np.float32),
    }
    n = flat["actions"].shape[0]
    n2 = _next_pow2(n)
    out = {}
    for k, v in flat.items():
        pad = [(0, n2 - n)] + [(0, 0)] * (v.ndim - 1)
        out[k] = np.pad(v, pad)
    out["mask"] = np.zeros(n2, np.float32)
    out["mask"][:n] = 1.0
    return out


class PPO(Algorithm):
    config_cls = PPOConfig

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()
        mesh = cfg.learner_mesh

        def factory():
            return PPOLearner(module_factory(), cfg, mesh=mesh,
                              seed=cfg.seed)

        return factory

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        # 1. broadcast current weights to the sampling fleet
        weights = self.learner_group.get_weights()
        self.env_runner_group.sync_weights(weights)
        if cfg.use_fragments:
            return self._training_step_fragments(cfg)
        # Legacy episode-based path (kept for comparison/debug; the
        # fragment path is the throughput-oriented default).
        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        self._record_episodes(episodes)
        episodes = self._connect_episodes(episodes)
        max_t = min(cfg.max_episode_len, max(len(e) for e in episodes))
        batch = postprocess_episodes(
            episodes, gamma=cfg.gamma, lam=cfg.lambda_, max_t=max_t)
        metrics = self.learner_group.update(
            batch,
            minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs,
            shuffle=True,
        )
        out = dict(metrics)
        out["episode_return_mean"] = self.episode_return_mean
        out["num_episodes"] = len(episodes)
        out["env_steps_this_iter"] = int(sum(len(e) for e in episodes))
        return out

    def _training_step_fragments(self, cfg) -> Dict[str, Any]:
        """Fragment path: [T, N] columns from every runner, vectorized GAE,
        minibatch SGD over the flat (masked) transition batch."""
        from ..utils.rollout import fragments_to_ppo_batch

        frags = self.env_runner_group.sample_fragments(
            cfg.rollout_fragment_length)
        if self._learner_connector is not None:
            frags = [self._learner_connector(f) for f in frags]
        n_eps = 0
        n_steps = 0
        for f in frags:
            rets = f.get("episode_returns") or []
            n_eps += len(rets)
            n_steps += int(f["valid"].sum())
            self._recent_returns.extend(float(r) for r in rets)
        self._episodes_total += n_eps
        self._timesteps_total += n_steps
        window = cfg.metrics_num_episodes_for_smoothing
        self._recent_returns = self._recent_returns[-window:]
        batch = fragments_to_ppo_batch(
            frags, gamma=cfg.gamma, lam=cfg.lambda_)
        metrics = self.learner_group.update(
            batch,
            minibatch_size=cfg.minibatch_size,
            num_epochs=cfg.num_epochs,
            shuffle=True,
        )
        out = dict(metrics)
        out["episode_return_mean"] = self.episode_return_mean
        out["num_episodes"] = n_eps
        out["env_steps_this_iter"] = int(batch["mask"].sum())
        return out
