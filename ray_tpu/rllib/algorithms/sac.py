"""SAC (Soft Actor-Critic) on the jax learner stack — the continuous-
control algorithm of the suite.

Parity: reference rllib/algorithms/sac/ (sac.py training_step: rollout ->
replay buffer -> off-policy updates; squashed-Gaussian policy from
torch_distributions, twin Q networks, polyak-averaged targets, learnable
entropy temperature against a target entropy of -|A|).

TPU-native shape: one jitted program per update step carries all three
losses (critic, actor, temperature) over ONE combined params pytree with a
single optimizer; gradient isolation between the heads uses
``stop_gradient`` on the param SUBTREES (stopping dQ/dtheta_Q in the actor
term while the action path dQ/da stays differentiable), so there is no
multi-optimizer bookkeeping to keep functional. The polyak target update
is a second tiny jitted map fused onto the step.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from ..core.rl_module import RLModule, _dense, _dense_init
from ..utils.replay_buffers import PrioritizedReplayBuffer, make_buffer

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or SAC)
        self.replay_buffer_capacity: int = 100_000
        self.replay_buffer_config: dict = {"type": "uniform"}
        self.learning_starts: int = 500
        self.num_updates_per_iter: int = 32
        self.gamma: float = 0.99
        self.tau: float = 0.005           # polyak target coefficient
        self.initial_alpha: float = 1.0
        # None -> -|A| (reference heuristic).
        self.target_entropy: Optional[float] = None


def _mlp(rng, sizes, out_dim, out_scale=1.0):
    n = len(sizes) - 1
    keys = jax.random.split(rng, n + 1)
    layers = [_dense_init(keys[i], sizes[i], sizes[i + 1]) for i in range(n)]
    layers.append(_dense_init(keys[-1], sizes[-1], out_dim, scale=out_scale))
    return layers


def _apply(layers, x):
    h = x.astype(jnp.float32)
    for layer in layers[:-1]:
        h = jnp.tanh(_dense(layer, h))
    return _dense(layers[-1], h)


class SACModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics.

    Actions live in [-1, 1] module-side and are affinely mapped to the
    env's Box bounds (the mapping is part of the module so stored
    transitions hold MODULE actions and the critics see a consistent
    space — reference: action squashing in SquashedGaussian)."""

    def __init__(self, obs_dim: int, act_dim: int,
                 low: np.ndarray, high: np.ndarray, hiddens=(256, 256)):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hiddens = tuple(hiddens)
        self._scale = jnp.asarray((high - low) / 2.0, jnp.float32)
        self._center = jnp.asarray((high + low) / 2.0, jnp.float32)

    def init(self, rng: jax.Array):
        k_actor, k_q1, k_q2 = jax.random.split(rng, 3)
        sizes = (self.obs_dim,) + self.hiddens
        q_sizes = (self.obs_dim + self.act_dim,) + self.hiddens
        return {
            "actor": _mlp(k_actor, sizes, 2 * self.act_dim, out_scale=0.01),
            "q1": _mlp(k_q1, q_sizes, 1),
            "q2": _mlp(k_q2, q_sizes, 1),
            "log_alpha": jnp.asarray(0.0, jnp.float32),
        }

    # ------------------------------------------------------------- policy

    def _dist(self, params, obs):
        out = _apply(params["actor"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
        return mu, log_std

    def sample_action(self, params, obs, rng):
        """Reparameterized squashed sample -> (action, log_prob)."""
        mu, log_std = self._dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mu.shape)
        pre = mu + std * eps
        act = jnp.tanh(pre)
        # log N(pre) - log |d tanh/d pre|, summed over action dims
        # (squash correction in its numerically-stable softplus form).
        logp_gauss = -0.5 * (eps**2 + 2 * log_std
                             + jnp.log(2 * jnp.pi)).sum(-1)
        corr = (2 * (jnp.log(2.0) - pre
                     - jax.nn.softplus(-2 * pre))).sum(-1)
        return act, logp_gauss - corr

    def q_values(self, params, obs, act):
        x = jnp.concatenate([obs.astype(jnp.float32), act], axis=-1)
        q1 = _apply(params["q1"], x)[..., 0]
        q2 = _apply(params["q2"], x)[..., 0]
        return q1, q2

    def to_env(self, act: jax.Array) -> jax.Array:
        return act * self._scale + self._center

    # ------------------------------------- runner protocol (RLModule API)

    def forward(self, params, obs):
        mu, _ = self._dist(params, obs)
        det = jnp.tanh(mu)
        q1, q2 = self.q_values(params, obs, det)
        return {"logits": mu, "vf": jnp.minimum(q1, q2)}

    def forward_exploration(self, params, obs, rng):
        act, logp = self.sample_action(params, obs, rng)
        q1, q2 = self.q_values(params, obs, act)
        return self.to_env(act), logp, jnp.minimum(q1, q2)


class SACLearner(JaxLearner):
    def __init__(self, module: SACModule, cfg: SACConfig, **kw):
        self.cfg = cfg
        self._target_entropy = (
            cfg.target_entropy if cfg.target_entropy is not None
            else -float(module.act_dim))
        super().__init__(module, lr=cfg.lr, grad_clip=cfg.grad_clip, **kw)
        if cfg.initial_alpha != 1.0:
            self.params["log_alpha"] = jnp.asarray(
                np.log(cfg.initial_alpha), jnp.float32)
        # REAL copies, not aliases: the update donates params while the
        # targets ride the batch pytree — an aliased buffer appearing as
        # both donated argument and input is an XLA error (`f(donate(a),
        # a)`), and after donation the old buffer is dead anyway.
        self._target_q = {
            "q1": jax.tree.map(jnp.copy, self.params["q1"]),
            "q2": jax.tree.map(jnp.copy, self.params["q2"]),
        }
        tau = cfg.tau
        self._jit_polyak = jax.jit(
            lambda tgt, src: jax.tree.map(
                lambda t, s: (1.0 - tau) * t + tau * s, tgt, src))

    def loss(self, params, batch, rng):
        cfg = self.cfg
        m: SACModule = self.module
        obs, next_obs = batch["obs"], batch["next_obs"]
        # Stored actions are MODULE actions (pre-scaling): map env actions
        # back (runner records to_env outputs).
        act = (batch["actions"] - m._center) / m._scale
        act = jnp.clip(act, -0.999, 0.999)
        alpha = jnp.exp(params["log_alpha"])
        r_next, r_pi = jax.random.split(rng)

        # --- critic: y = r + gamma (1-d) [min tQ(s',a') - a log pi(a'|s')]
        next_act, next_logp = m.sample_action(params, next_obs, r_next)
        tq = {"q1": batch["target_q1"], "q2": batch["target_q2"],
              "log_alpha": params["log_alpha"], "actor": params["actor"]}
        tq1, tq2 = m.q_values(tq, next_obs, next_act)
        y = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * (
            jnp.minimum(tq1, tq2)
            - jax.lax.stop_gradient(alpha) * next_logp)
        y = jax.lax.stop_gradient(y)
        q1, q2 = m.q_values(params, obs, act)
        critic_err = (q1 - y) ** 2 + (q2 - y) ** 2
        td_abs = jax.lax.stop_gradient(jnp.abs(jnp.minimum(q1, q2) - y))
        if "weights" in batch:
            critic_loss = 0.5 * jnp.mean(batch["weights"] * critic_err)
        else:
            critic_loss = 0.5 * jnp.mean(critic_err)

        # --- actor: a log pi - min Q  (Q params frozen: stop_gradient on
        # the SUBTREE keeps dQ/da while killing dQ/dtheta_Q)
        pi_act, pi_logp = m.sample_action(params, obs, r_pi)
        frozen = {"q1": jax.lax.stop_gradient(params["q1"]),
                  "q2": jax.lax.stop_gradient(params["q2"]),
                  "actor": params["actor"],
                  "log_alpha": params["log_alpha"]}
        fq1, fq2 = m.q_values(frozen, obs, pi_act)
        actor_loss = jnp.mean(
            jax.lax.stop_gradient(alpha) * pi_logp - jnp.minimum(fq1, fq2))

        # --- temperature: drive E[-log pi] toward the target entropy
        alpha_loss = -jnp.mean(
            params["log_alpha"]
            * jax.lax.stop_gradient(pi_logp + self._target_entropy))

        loss = critic_loss + actor_loss + alpha_loss
        return loss, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha_loss": alpha_loss,
            "alpha": alpha,
            "mean_q": jnp.mean(q1),
            "entropy": -jnp.mean(pi_logp),
            # Per-row priority signal for prioritized replay — rides the
            # update's aux output so no second forward pass is needed.
            "td_abs": td_abs,
        }

    def update_sac(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        dev = self._shard_batch(batch)
        dev["target_q1"] = self._target_q["q1"]
        dev["target_q2"] = self._target_q["q2"]
        self.params, self.opt_state, metrics = self._jit_update(
            self.params, self.opt_state, dev, self._consume_rng())
        self._target_q = self._jit_polyak(
            self._target_q,
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self._last_td_abs = np.asarray(metrics.pop("td_abs"))
        return {k: float(v) for k, v in metrics.items()}

    def take_td_errors(self) -> np.ndarray:
        """|TD errors| of the LAST update_sac batch (prioritized replay)."""
        return getattr(self, "_last_td_abs", np.zeros(0, np.float32))

    def td_errors(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """|min-Q TD error| for prioritized replay."""
        if not hasattr(self, "_jit_td"):
            def _td(params, batch, rng):
                m = self.module
                act = jnp.clip(
                    (batch["actions"] - m._center) / m._scale, -0.999, 0.999)
                next_act, next_logp = m.sample_action(
                    params, batch["next_obs"], rng)
                tq = {"q1": batch["target_q1"], "q2": batch["target_q2"],
                      "log_alpha": params["log_alpha"],
                      "actor": params["actor"]}
                tq1, tq2 = m.q_values(tq, batch["next_obs"], next_act)
                alpha = jnp.exp(params["log_alpha"])
                y = batch["rewards"] + self.cfg.gamma * (
                    1.0 - batch["dones"]) * (
                    jnp.minimum(tq1, tq2) - alpha * next_logp)
                q1, q2 = m.q_values(params, batch["obs"], act)
                return jnp.abs(jnp.minimum(q1, q2) - y)

            self._jit_td = jax.jit(_td)
        dev = self._shard_batch(
            {k: v for k, v in batch.items() if k != "weights"})
        dev["target_q1"] = self._target_q["q1"]
        dev["target_q2"] = self._target_q["q2"]
        return np.asarray(self._jit_td(self.params, dev, self._consume_rng()))


class SAC(Algorithm):
    config_cls = SACConfig

    def _spaces(self) -> Tuple[Tuple[int, ...], int, np.ndarray, np.ndarray]:
        cfg = self._algo_config
        env = cfg.make_env_creator()()
        try:
            obs_shape = env.observation_space.shape
            space = env.action_space
            low = np.asarray(space.low, np.float32)
            high = np.asarray(space.high, np.float32)
            return obs_shape, int(np.prod(space.shape)), low, high
        finally:
            env.close()

    def _module_factory(self):
        cfg = self._algo_config
        obs_shape, act_dim, low, high = self._spaces()
        obs_dim = int(np.prod(obs_shape))
        hiddens = tuple(cfg.model.get("fcnet_hiddens", (256, 256)))

        def factory():
            return SACModule(obs_dim, act_dim, low, high, hiddens)

        return factory

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()

        def factory():
            return SACLearner(module_factory(), cfg, mesh=cfg.learner_mesh,
                              seed=cfg.seed)

        return factory

    def _setup_extra(self) -> None:
        cfg = self._algo_config
        obs_shape, act_dim, _, _ = self._spaces()
        self._buffer = make_buffer(
            cfg.replay_buffer_config, cfg.replay_buffer_capacity, obs_shape,
            action_shape=(act_dim,), action_dtype=np.float32)
        self._np_rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        if not hasattr(self, "_buffer"):
            self._setup_extra()
        weights = self.learner_group.get_weights()
        self.env_runner_group.sync_weights(weights)

        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        self._record_episodes(episodes)
        episodes = self._connect_episodes(episodes)
        added = self._buffer.add_episodes(episodes)

        metrics: Dict[str, Any] = {}
        if self._buffer.size >= cfg.learning_starts:
            prioritized = isinstance(self._buffer, PrioritizedReplayBuffer)
            for _ in range(cfg.num_updates_per_iter):
                batch = self._buffer.sample(cfg.minibatch_size, self._np_rng)
                idx = batch.pop("idx", None)
                metrics = self.learner_group.call("update_sac", batch)
                if prioritized and idx is not None:
                    td = self.learner_group.call("take_td_errors")
                    if len(td):
                        self._buffer.update_priorities(idx, td)

        out = dict(metrics)
        out["buffer_size"] = self._buffer.size
        out["episode_return_mean"] = self.episode_return_mean
        out["num_episodes"] = len(episodes)
        out["env_steps_this_iter"] = added
        return out
