from .dqn import DQN, DQNConfig
from .sac import SAC, SACConfig
from .appo import APPO, APPOConfig
from .impala import IMPALA, IMPALAConfig
from .ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "DQN",
           "DQNConfig", "SAC", "SACConfig", "APPO", "APPOConfig"]
