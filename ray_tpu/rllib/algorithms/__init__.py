from .dqn import DQN, DQNConfig
from .impala import IMPALA, IMPALAConfig
from .ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "DQN", "DQNConfig"]
