from .ppo import PPO, PPOConfig
from .impala import IMPALA, IMPALAConfig

__all__ = ["PPO", "PPOConfig", "IMPALA", "IMPALAConfig"]
