"""APPO: asynchronous PPO — IMPALA's pipeline with the clipped surrogate.

Parity: reference rllib/algorithms/appo/ (appo.py: "APPO is an
asynchronous variant of PPO based on the IMPALA architecture" — v-trace
corrected advantages consumed by PPO's clipped-ratio objective plus a KL
penalty against the behavior policy). Everything asynchronous (permanently
in-flight sample futures, bounded-lag weight broadcast, runner healing) is
inherited from IMPALA unchanged; only the loss differs, and it stays one
jitted program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.gae import vtrace
from .impala import IMPALA, IMPALAConfig, IMPALALearner


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or APPO)
        self.clip_param: float = 0.2      # PPO surrogate clip
        self.kl_coeff: float = 0.2        # behavior-KL penalty weight
        self.use_kl_loss: bool = True


class APPOLearner(IMPALALearner):
    def loss(self, params, batch, rng):
        cfg = self.cfg
        B, T = batch["rewards"].shape
        obs = batch["obs"].reshape((B * T,) + batch["obs"].shape[2:])
        out = self.module.forward(params, obs)
        logits = out["logits"].reshape(B, T, -1)
        values = out["vf"].reshape(B, T)

        dist = self.module.action_dist(logits)
        target_logp = dist.logp(batch["actions"])
        entropy = dist.entropy()

        vs, pg_adv = vtrace(
            batch["logp"], target_logp, batch["rewards"],
            values, batch["dones"], batch["bootstrap_value"],
            gamma=cfg.gamma,
            clip_rho=cfg.clip_rho_threshold,
            clip_c=cfg.clip_c_threshold,
        )
        vs = jax.lax.stop_gradient(vs)
        pg_adv = jax.lax.stop_gradient(pg_adv)

        mask = batch["mask"]
        msum = jnp.maximum(mask.sum(), 1.0)
        # PPO clipped surrogate on the v-trace advantages (the APPO
        # difference vs IMPALA's plain policy gradient).
        ratio = jnp.exp(target_logp - batch["logp"])
        clipped = jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param)
        surrogate = jnp.minimum(ratio * pg_adv, clipped * pg_adv)
        pi_loss = -(surrogate * mask).sum() / msum
        vf_loss = (((values - vs) ** 2) * mask).sum() / msum
        ent = (entropy * mask).sum() / msum
        # KL(behavior || target) estimated from logp samples keeps the
        # async policy from drifting past the clip's trust region.
        kl = ((batch["logp"] - target_logp) * mask).sum() / msum
        total = (pi_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * ent)
        if cfg.use_kl_loss:
            total = total + cfg.kl_coeff * jnp.abs(kl)
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": ent,
            "kl": kl,
            "mean_ratio": (ratio * mask).sum() / msum,
        }


class APPO(IMPALA):
    config_cls = APPOConfig

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()
        mesh = cfg.learner_mesh

        def factory():
            return APPOLearner(module_factory(), cfg, mesh=mesh,
                               seed=cfg.seed)

        return factory
