"""DQN on the jax learner stack.

Parity: reference rllib/algorithms/dqn/ (training_step: rollout ->
replay-buffer add -> TD updates with a periodically synced target network;
epsilon-greedy exploration). TPU-native shape: the TD update is one jitted
program; the target params ride along in the batch pytree so the update
stays functional; epsilon lives IN the weights so the existing
sync_weights broadcast carries the schedule to every env runner.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from ..core.rl_module import MLPModule, RLModule
from ..utils.episodes import SingleAgentEpisode


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DQN)
        self.replay_buffer_capacity: int = 50_000
        self.learning_starts: int = 1_000
        self.target_network_update_freq: int = 500  # in sampled env-steps
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 10_000
        self.num_td_updates_per_iter: int = 32
        self.gamma: float = 0.99
        # Reference replay_buffer_config dicts: {"type": "uniform" |
        # "prioritized", "alpha": 0.6, "beta": 0.4}.
        self.replay_buffer_config: dict = {"type": "uniform"}


class DQNModule(RLModule):
    """Q-network wrapper: logits ARE Q-values; exploration is
    epsilon-greedy with epsilon carried in the params pytree."""

    def __init__(self, obs_dim: int, num_actions: int, hiddens=(64, 64)):
        self._mlp = MLPModule(obs_dim, num_actions, hiddens)
        self.num_actions = num_actions

    def init(self, rng: jax.Array):
        params = self._mlp.init(rng)
        params["epsilon"] = jnp.asarray(1.0, jnp.float32)
        return params

    def forward(self, params, obs):
        out = self._mlp.forward(params, obs)
        # vf = max-Q: gives the runners a value estimate for logging.
        out["vf"] = jnp.max(out["logits"], axis=-1)
        return out

    def forward_exploration(self, params, obs, rng):
        out = self.forward(params, obs)
        q = out["logits"]
        greedy = jnp.argmax(q, axis=-1)
        r1, r2 = jax.random.split(rng)
        rand_a = jax.random.randint(r1, greedy.shape, 0, self.num_actions)
        explore = jax.random.uniform(r2, greedy.shape) < params["epsilon"]
        action = jnp.where(explore, rand_a, greedy)
        # logp is not meaningful for epsilon-greedy; report 0 (unused).
        return action, jnp.zeros_like(q[..., 0]), out["vf"]


class DQNLearner(JaxLearner):
    def __init__(self, module, cfg: DQNConfig, **kw):
        self.cfg = cfg
        super().__init__(module, lr=cfg.lr, grad_clip=cfg.grad_clip, **kw)
        # jnp.copy, not identity: the update donates params while the
        # target rides the batch pytree (aliased donated buffers are an
        # XLA error; the old buffer dies with the donation).
        self._target_params = jax.tree.map(jnp.copy, self.params)

    def loss(self, params, batch, rng):
        cfg = self.cfg
        q = self.module.forward(params, batch["obs"])["logits"]
        q_sa = jnp.take_along_axis(
            q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
        q_next = self.module.forward(
            batch["target_params"], batch["next_obs"])["logits"]
        target = batch["rewards"] + cfg.gamma * (
            1.0 - batch["dones"]) * jnp.max(q_next, axis=-1)
        target = jax.lax.stop_gradient(target)
        err = q_sa - target
        # Huber loss (reference default), importance-weighted when the
        # batch came from a prioritized buffer (weights key is static per
        # compiled variant — uniform and prioritized batches trace apart).
        huber = jnp.where(jnp.abs(err) < 1.0, 0.5 * err**2,
                          jnp.abs(err) - 0.5)
        if "weights" in batch:
            loss = jnp.mean(batch["weights"] * huber)
        else:
            loss = jnp.mean(huber)
        # Per-row |err| rides the aux output so prioritized replay gets
        # its priority signal from THIS update — no second forward pass.
        return loss, {"td_loss": loss, "mean_q": jnp.mean(q_sa),
                      "td_abs": jax.lax.stop_gradient(jnp.abs(err))}

    def td_errors(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """|TD error| per row on CURRENT params — the prioritized buffer's
        priority signal (reference: prioritized buffer update after each
        train batch)."""
        if not hasattr(self, "_jit_td_errors"):
            def _td(params, batch):
                cfg = self.cfg
                q = self.module.forward(params, batch["obs"])["logits"]
                q_sa = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32),
                    axis=1)[:, 0]
                q_next = self.module.forward(
                    batch["target_params"], batch["next_obs"])["logits"]
                target = batch["rewards"] + cfg.gamma * (
                    1.0 - batch["dones"]) * jnp.max(q_next, axis=-1)
                return jnp.abs(q_sa - target)

            self._jit_td_errors = jax.jit(_td)
        dev = self._shard_batch(
            {k: v for k, v in batch.items() if k != "weights"})
        dev["target_params"] = self._target_params
        return np.asarray(self._jit_td_errors(self.params, dev))

    def sync_target(self) -> None:
        """Copy current params into the target network — called only at
        target_network_update_freq, so the big pytree never rides the
        per-update RPC."""
        self._target_params = jax.tree.map(jnp.copy, self.params)

    def update_td(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        # One full-batch jitted TD step. The target params join the batch
        # pytree directly (no row indexing may touch them).
        dev = self._shard_batch(batch)
        dev["target_params"] = self._target_params
        self.params, self.opt_state, metrics = self._jit_update(
            self.params, self.opt_state, dev, self._consume_rng())
        self._last_td_abs = np.asarray(metrics.pop("td_abs"))
        return {k: float(v) for k, v in metrics.items()}

    def take_td_errors(self) -> np.ndarray:
        """|TD errors| of the LAST update_td batch (prioritized replay)."""
        return getattr(self, "_last_td_abs", np.zeros(0, np.float32))


# The buffer implementation moved to the shared suite (uniform +
# prioritized, discrete + continuous actions); DQN consumes it via
# make_buffer and this re-export keeps the old import path working.
from ..utils.replay_buffers import (  # noqa: E402
    PrioritizedReplayBuffer, ReplayBuffer, make_buffer)


class DQN(Algorithm):
    config_cls = DQNConfig

    def _module_factory(self):
        cfg = self._algo_config
        creator = cfg.make_env_creator()
        connector_factory = cfg.env_to_module_connector

        def factory():
            env = creator()
            try:
                shape = env.observation_space.shape
                if connector_factory is not None:
                    shape = tuple(connector_factory().output_shape(shape))
                obs_dim = int(np.prod(shape))
                return DQNModule(obs_dim, env.action_space.n,
                                 tuple(cfg.model.get("fcnet_hiddens",
                                                     (64, 64))))
            finally:
                env.close()

        return factory

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()

        def factory():
            return DQNLearner(module_factory(), cfg, mesh=cfg.learner_mesh,
                              seed=cfg.seed)

        return factory

    def _setup_extra(self) -> None:
        cfg = self._algo_config
        env = cfg.make_env_creator()()
        try:
            obs_shape = env.observation_space.shape
        finally:
            env.close()
        if cfg.env_to_module_connector is not None:
            # The buffer stores CONNECTED observations (what the module sees).
            obs_shape = tuple(
                cfg.env_to_module_connector().output_shape(obs_shape))
        self._buffer = make_buffer(getattr(cfg, "replay_buffer_config", None),
                                   cfg.replay_buffer_capacity, obs_shape)
        self.learner_group.call("sync_target")
        self._steps_since_target_sync = 0
        self._np_rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        if not hasattr(self, "_buffer"):
            self._setup_extra()
        weights = self.learner_group.get_weights()
        # Epsilon schedule, carried inside the weights.
        frac = min(1.0, self._timesteps_total / max(1, cfg.epsilon_timesteps))
        eps = cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)
        weights["epsilon"] = np.float32(eps)
        self.learner_group.set_weights(weights)
        self.env_runner_group.sync_weights(weights)

        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        self._record_episodes(episodes)
        # Learner connector before replay insertion: TD targets must see
        # the transformed (e.g. clipped) rewards.
        episodes = self._connect_episodes(episodes)
        added = self._buffer.add_episodes(episodes)
        self._steps_since_target_sync += added

        metrics: Dict[str, Any] = {}
        if self._buffer.size >= cfg.learning_starts:
            prioritized = isinstance(self._buffer, PrioritizedReplayBuffer)
            for _ in range(cfg.num_td_updates_per_iter):
                batch = self._buffer.sample(cfg.minibatch_size, self._np_rng)
                idx = batch.pop("idx", None)
                metrics = self.learner_group.call("update_td", batch)
                if prioritized and idx is not None:
                    td = self.learner_group.call("take_td_errors")
                    if len(td):
                        self._buffer.update_priorities(idx, td)
            if self._steps_since_target_sync >= cfg.target_network_update_freq:
                self.learner_group.call("sync_target")
                self._steps_since_target_sync = 0

        out = dict(metrics)
        out["epsilon"] = float(eps)
        out["buffer_size"] = self._buffer.size
        out["episode_return_mean"] = self.episode_return_mean
        out["num_episodes"] = len(episodes)
        out["env_steps_this_iter"] = added
        return out
