"""DQN on the jax learner stack.

Parity: reference rllib/algorithms/dqn/ (training_step: rollout ->
replay-buffer add -> TD updates with a periodically synced target network;
epsilon-greedy exploration). TPU-native shape: the TD update is one jitted
program; the target params ride along in the batch pytree so the update
stays functional; epsilon lives IN the weights so the existing
sync_weights broadcast carries the schedule to every env runner.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from ..core.rl_module import MLPModule, RLModule
from ..utils.episodes import SingleAgentEpisode


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or DQN)
        self.replay_buffer_capacity: int = 50_000
        self.learning_starts: int = 1_000
        self.target_network_update_freq: int = 500  # in sampled env-steps
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 10_000
        self.num_td_updates_per_iter: int = 32
        self.gamma: float = 0.99


class DQNModule(RLModule):
    """Q-network wrapper: logits ARE Q-values; exploration is
    epsilon-greedy with epsilon carried in the params pytree."""

    def __init__(self, obs_dim: int, num_actions: int, hiddens=(64, 64)):
        self._mlp = MLPModule(obs_dim, num_actions, hiddens)
        self.num_actions = num_actions

    def init(self, rng: jax.Array):
        params = self._mlp.init(rng)
        params["epsilon"] = jnp.asarray(1.0, jnp.float32)
        return params

    def forward(self, params, obs):
        out = self._mlp.forward(params, obs)
        # vf = max-Q: gives the runners a value estimate for logging.
        out["vf"] = jnp.max(out["logits"], axis=-1)
        return out

    def forward_exploration(self, params, obs, rng):
        out = self.forward(params, obs)
        q = out["logits"]
        greedy = jnp.argmax(q, axis=-1)
        r1, r2 = jax.random.split(rng)
        rand_a = jax.random.randint(r1, greedy.shape, 0, self.num_actions)
        explore = jax.random.uniform(r2, greedy.shape) < params["epsilon"]
        action = jnp.where(explore, rand_a, greedy)
        # logp is not meaningful for epsilon-greedy; report 0 (unused).
        return action, jnp.zeros_like(q[..., 0]), out["vf"]


class DQNLearner(JaxLearner):
    def __init__(self, module, cfg: DQNConfig, **kw):
        self.cfg = cfg
        super().__init__(module, lr=cfg.lr, grad_clip=cfg.grad_clip, **kw)
        self._target_params = jax.tree.map(lambda x: x, self.params)

    def loss(self, params, batch, rng):
        cfg = self.cfg
        q = self.module.forward(params, batch["obs"])["logits"]
        q_sa = jnp.take_along_axis(
            q, batch["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
        q_next = self.module.forward(
            batch["target_params"], batch["next_obs"])["logits"]
        target = batch["rewards"] + cfg.gamma * (
            1.0 - batch["dones"]) * jnp.max(q_next, axis=-1)
        target = jax.lax.stop_gradient(target)
        err = q_sa - target
        # Huber loss (reference default).
        huber = jnp.where(jnp.abs(err) < 1.0, 0.5 * err**2,
                          jnp.abs(err) - 0.5)
        loss = jnp.mean(huber)
        return loss, {"td_loss": loss, "mean_q": jnp.mean(q_sa)}

    def sync_target(self) -> None:
        """Copy current params into the target network — called only at
        target_network_update_freq, so the big pytree never rides the
        per-update RPC."""
        self._target_params = jax.tree.map(lambda x: x, self.params)

    def update_td(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        # One full-batch jitted TD step. The target params join the batch
        # pytree directly (no row indexing may touch them).
        dev = self._shard_batch(batch)
        dev["target_params"] = self._target_params
        self.params, self.opt_state, metrics = self._jit_update(
            self.params, self.opt_state, dev, self._consume_rng())
        return {k: float(v) for k, v in metrics.items()}


class ReplayBuffer:
    """Uniform FIFO transition buffer (reference:
    utils/replay_buffers/replay_buffer.py)."""

    def __init__(self, capacity: int, obs_shape: Tuple[int, ...]):
        self.capacity = capacity
        self.size = 0
        self.pos = 0
        self.obs = np.zeros((capacity, *obs_shape), np.float32)
        self.next_obs = np.zeros((capacity, *obs_shape), np.float32)
        self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)

    def add_episodes(self, episodes: List[SingleAgentEpisode]) -> int:
        n = 0
        for ep in episodes:
            T = len(ep.actions)
            for t in range(T):
                nxt = ep.observations[t + 1] if t + 1 < len(ep.observations) \
                    else ep.observations[t]
                done = float(ep.terminated and t == T - 1)
                i = self.pos
                self.obs[i] = ep.observations[t]
                self.next_obs[i] = nxt
                self.actions[i] = ep.actions[t]
                self.rewards[i] = ep.rewards[t]
                self.dones[i] = done
                self.pos = (self.pos + 1) % self.capacity
                self.size = min(self.size + 1, self.capacity)
                n += 1
        return n

    def sample(self, batch_size: int, rng: np.random.Generator):
        idx = rng.integers(0, self.size, batch_size)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }


class DQN(Algorithm):
    config_cls = DQNConfig

    def _module_factory(self):
        cfg = self._algo_config
        creator = cfg.make_env_creator()
        connector_factory = cfg.env_to_module_connector

        def factory():
            env = creator()
            try:
                shape = env.observation_space.shape
                if connector_factory is not None:
                    shape = tuple(connector_factory().output_shape(shape))
                obs_dim = int(np.prod(shape))
                return DQNModule(obs_dim, env.action_space.n,
                                 tuple(cfg.model.get("fcnet_hiddens",
                                                     (64, 64))))
            finally:
                env.close()

        return factory

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()

        def factory():
            return DQNLearner(module_factory(), cfg, mesh=cfg.learner_mesh,
                              seed=cfg.seed)

        return factory

    def _setup_extra(self) -> None:
        cfg = self._algo_config
        env = cfg.make_env_creator()()
        try:
            obs_shape = env.observation_space.shape
        finally:
            env.close()
        if cfg.env_to_module_connector is not None:
            # The buffer stores CONNECTED observations (what the module sees).
            obs_shape = tuple(
                cfg.env_to_module_connector().output_shape(obs_shape))
        self._buffer = ReplayBuffer(cfg.replay_buffer_capacity, obs_shape)
        self.learner_group.call("sync_target")
        self._steps_since_target_sync = 0
        self._np_rng = np.random.default_rng(cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        if not hasattr(self, "_buffer"):
            self._setup_extra()
        weights = self.learner_group.get_weights()
        # Epsilon schedule, carried inside the weights.
        frac = min(1.0, self._timesteps_total / max(1, cfg.epsilon_timesteps))
        eps = cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)
        weights["epsilon"] = np.float32(eps)
        self.learner_group.set_weights(weights)
        self.env_runner_group.sync_weights(weights)

        episodes = self.env_runner_group.sample(cfg.train_batch_size)
        self._record_episodes(episodes)
        # Learner connector before replay insertion: TD targets must see
        # the transformed (e.g. clipped) rewards.
        episodes = self._connect_episodes(episodes)
        added = self._buffer.add_episodes(episodes)
        self._steps_since_target_sync += added

        metrics: Dict[str, Any] = {}
        if self._buffer.size >= cfg.learning_starts:
            for _ in range(cfg.num_td_updates_per_iter):
                batch = self._buffer.sample(cfg.minibatch_size, self._np_rng)
                metrics = self.learner_group.call("update_td", batch)
            if self._steps_since_target_sync >= cfg.target_network_update_freq:
                self.learner_group.call("sync_target")
                self._steps_since_target_sync = 0

        out = dict(metrics)
        out["epsilon"] = float(eps)
        out["buffer_size"] = self._buffer.size
        out["episode_return_mean"] = self.episode_return_mean
        out["num_episodes"] = len(episodes)
        out["env_steps_this_iter"] = added
        return out
