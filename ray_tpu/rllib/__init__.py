"""RL library: Algorithm/Config over env-runner actors + jax learners.

Parity map (reference rllib/, SURVEY.md §2.7):
- Algorithm(Trainable) + fluent AlgorithmConfig  -> algorithm.py, algorithm_config.py
- RLModule + catalog                             -> core/rl_module.py, core/catalog.py
- Learner/LearnerGroup (torch DDP -> jax mesh)   -> core/learner.py, core/learner_group.py
- SingleAgentEnvRunner/EnvRunnerGroup            -> env/
- FaultTolerantActorManager                      -> utils/actor_manager.py
- GAE / v-trace                                  -> utils/gae.py
- PPO / IMPALA                                   -> algorithms/
"""
from .algorithm import Algorithm
from .algorithm_config import AlgorithmConfig
from .algorithms import (APPO, APPOConfig, IMPALA, IMPALAConfig, PPO,
                         PPOConfig, SAC, SACConfig)
from .core import JaxLearner, LearnerGroup, MLPModule, RLModule
from .env import EnvRunnerGroup, SingleAgentEnvRunner
from .env.multi_agent_env import (MultiAgentBatchedEnv, MultiAgentEnv,
                                  make_multi_agent_creator)
from .offline import BC, BCConfig, MARWIL, MARWILConfig
from .utils import (FaultTolerantActorManager, SingleAgentEpisode,
                    compute_gae, episodes_to_batch, vtrace)

__all__ = [
    "MultiAgentBatchedEnv",
    "MultiAgentEnv",
    "make_multi_agent_creator",
    "Algorithm",
    "AlgorithmConfig",
    "APPO",
    "APPOConfig",
    "PPO",
    "SAC",
    "SACConfig",
    "PPOConfig",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "IMPALA",
    "IMPALAConfig",
    "RLModule",
    "MLPModule",
    "JaxLearner",
    "LearnerGroup",
    "EnvRunnerGroup",
    "SingleAgentEnvRunner",
    "FaultTolerantActorManager",
    "SingleAgentEpisode",
    "episodes_to_batch",
    "compute_gae",
    "vtrace",
]
