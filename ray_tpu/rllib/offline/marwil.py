"""MARWIL: Monotonic Advantage Re-Weighted Imitation Learning.

Parity: reference rllib/algorithms/marwil — offline imitation where each
logged action's log-likelihood is weighted by exp(beta * advantage), with
the advantage = (Monte-Carlo return - V(s)) and a trained value head. At
beta=0 this degrades to plain BC (the reference documents the same limit);
larger beta biases the policy toward better-than-average logged actions,
letting it exceed the behavior policy.

Data layout: the same transition shards BC/CQL read (offline/io.py), with
Monte-Carlo returns computed once at corpus load by segmenting on `dones`
and discounted-suffix-summing inside each episode — a lax-free O(n) numpy
pass, since it happens on the host before batches ship to the learner.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from .io import iter_offline_batches, load_columns


def monte_carlo_returns(rewards: np.ndarray, dones: np.ndarray,
                        gamma: float) -> np.ndarray:
    """Discounted suffix sums per episode (episodes delimited by dones;
    a trailing partial episode is treated as ending at the array end —
    its returns are biased low, matching the reference's truncation
    behavior for incomplete logged episodes).

    Assumes transitions of an episode are CONTIGUOUS in time order — the
    write_transitions layout. Fragment shards (write_fragments) interleave
    vectorized envs when N>1; for such corpora write a precomputed
    "returns" column instead (training_step uses it verbatim if present).
    """
    n = len(rewards)
    out = np.zeros(n, dtype=np.float32)
    if n == 0:
        return out
    r = rewards.astype(np.float64)
    if gamma == 0.0:
        return r.astype(np.float32)
    starts = np.concatenate(([0], np.flatnonzero(dones[:-1]) + 1))
    ends = np.concatenate((starts[1:], [n]))
    lengths = ends - starts
    # Scaled-cumsum trick: within an episode,
    #   G[i] = sum_{j>=i} gamma^(j-i) r[j] = suffix-cumsum(r * w)[i] / w[i]
    # with w = gamma^position. Valid only while gamma^position stays well
    # above underflow — cap position at B so the weight never drops below
    # ~1e-12 (beyond that the division amplifies rounding into garbage,
    # and past ~gamma^-700 it underflows to 0/0 = NaN outright).
    B = n if gamma >= 1.0 else max(1, min(n, int(-27.6 / np.log(gamma))))
    # Vectorized path for every episode of length <= B at once: ONE global
    # cumsum; per-element suffix sums via the episode-end cumsum value.
    # (A bandit corpus of millions of 1-step episodes takes this path with
    # zero interpreter iterations.)
    pos = np.arange(n) - np.repeat(starts, lengths)
    short_el = np.repeat(lengths <= B, lengths)
    w = gamma ** np.minimum(pos, B)  # clamp: long-episode tails unused
    z = np.where(short_el, r * w, 0.0)
    C = np.cumsum(z)
    ce = np.repeat(C[ends - 1], lengths)
    with np.errstate(invalid="ignore"):
        G = (ce - C + z) / w
    out[short_el] = G[short_el].astype(np.float32)
    # Long episodes: chunked scaled cumsum from the episode end, carrying
    # the bootstrap return across chunks — O(L/B) numpy ops per episode,
    # no underflow because positions restart each chunk.
    for s, e in zip(starts[lengths > B], ends[lengths > B]):
        acc = 0.0
        for ce_ in range(e, s, -B):
            cs = max(s, ce_ - B)
            seg = r[cs:ce_]
            k = np.arange(len(seg))
            wk = gamma ** k
            Gc = np.cumsum((seg * wk)[::-1])[::-1] / wk \
                + acc * gamma ** (len(seg) - k)
            out[cs:ce_] = Gc.astype(np.float32)
            acc = Gc[0]
    return out


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or MARWIL)
        self.input_path: str = ""
        self.steps_per_iteration: int = 32
        self.beta: float = 1.0
        self.vf_coeff: float = 1.0
        # Clip on the exp() weights (reference marwil.py caps the
        # advantage exponent so one lucky trajectory can't dominate).
        self.max_weight: float = 20.0

    def offline_data(self, *, input_path: str,
                     steps_per_iteration: int = None) -> "MARWILConfig":
        self.input_path = input_path
        if steps_per_iteration is not None:
            self.steps_per_iteration = steps_per_iteration
        return self

    def marwil(self, *, beta: float = None, vf_coeff: float = None,
               max_weight: float = None) -> "MARWILConfig":
        if beta is not None:
            self.beta = beta
        if vf_coeff is not None:
            self.vf_coeff = vf_coeff
        if max_weight is not None:
            self.max_weight = max_weight
        return self


class MARWILLearner(JaxLearner):
    """exp(beta * normalized advantage)-weighted NLL + value regression.

    The advantage is normalized by the batch RMS (the reference keeps a
    running average of the squared advantage for the same purpose:
    marwil's `moving_average_sqd_adv_norm`); the weight is detached so the
    value head is trained only by its own regression term.
    """

    def __init__(self, module, *, beta: float, vf_coeff: float,
                 max_weight: float, **kw):
        self.beta = beta
        self.vf_coeff = vf_coeff
        self.max_weight = max_weight
        super().__init__(module, **kw)

    def loss(self, params, batch, rng):
        out = self.module.forward(params, batch["obs"])
        dist = self.module.action_dist(out["logits"])
        logp = dist.logp(batch["actions"])
        returns = batch["returns"]
        vf = out["vf"]
        adv = returns - vf
        vf_loss = 0.5 * jnp.mean(adv ** 2)
        # Weight from the DETACHED advantage: the exp must not backprop
        # into the value head (reference torch impl detaches the same way).
        adv_sg = jax.lax.stop_gradient(adv)
        rms = jnp.sqrt(jnp.mean(adv_sg ** 2) + 1e-8)
        w = jnp.exp(jnp.clip(self.beta * adv_sg / rms,
                             max=jnp.log(self.max_weight)))
        policy_loss = -jnp.mean(w * logp)
        total = policy_loss + self.vf_coeff * vf_loss
        return total, {"marwil_loss": total, "policy_loss": policy_loss,
                       "vf_loss": vf_loss, "mean_weight": w.mean(),
                       "entropy": dist.entropy().mean()}


class MARWIL(Algorithm):
    config_cls = MARWILConfig

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()
        mesh = cfg.learner_mesh

        def factory():
            return MARWILLearner(
                module_factory(), beta=cfg.beta, vf_coeff=cfg.vf_coeff,
                max_weight=cfg.max_weight, lr=cfg.lr,
                grad_clip=cfg.grad_clip, mesh=mesh, seed=cfg.seed)

        return factory

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        if not cfg.input_path:
            raise ValueError("MARWIL requires offline_data(input_path=...)")
        cache = getattr(self, "_offline_columns", None)
        if cache is None:
            cache = load_columns(cfg.input_path)
            if "returns" not in cache:
                if not {"rewards", "dones"} <= set(cache):
                    raise ValueError(
                        "MARWIL needs rewards+dones (or precomputed "
                        "returns) columns in the offline shards")
                cache["returns"] = monte_carlo_returns(
                    cache["rewards"], cache["dones"], cfg.gamma)
            self._offline_columns = cache
        metrics: Dict[str, Any] = {}
        steps = 0
        for batch in iter_offline_batches(
                cache, cfg.minibatch_size or 128,
                seed=cfg.seed + self._iteration):
            metrics = self.learner_group.update(dict(batch))
            steps += 1
            if steps >= cfg.steps_per_iteration:
                break
        out = dict(metrics)
        out["sgd_steps_this_iter"] = steps
        out["env_steps_this_iter"] = 0
        return out
