"""Offline experience IO: write sampled fragments, read them for training.

Parity: reference rllib/offline/ (json_writer.py / json_reader.py and the
OfflineData datasets path): env runners write experiences to files; offline
algorithms train from those files without touching an environment. The
TPU-native shape stores transitions as columnar .npz shards (dense arrays,
mmap-friendly) and reads them through ray_tpu.data so the same streaming
pipeline that feeds batch inference feeds offline RL.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class JsonWriter:
    """Append transition columns of sampled fragments to .npz shards
    (name kept for reference-API familiarity; payload is npz, with a
    sidecar manifest.jsonl describing the shards, one JSON line each)."""

    def __init__(self, path: str, *, max_rows_per_shard: int = 100_000):
        self.path = path
        self.max_rows = max_rows_per_shard
        os.makedirs(path, exist_ok=True)
        self._shard = 0

    def write(self, columns: Dict[str, np.ndarray]) -> str:
        n = len(next(iter(columns.values())))
        # uuid suffix: two writers (or two write calls in one second) must
        # never collide on a shard name — an overwrite is silent data loss.
        fname = os.path.join(
            self.path,
            f"experiences-{int(time.time())}-{self._shard:05d}-"
            f"{uuid.uuid4().hex[:8]}.npz")
        self._shard += 1
        np.savez_compressed(fname, **columns)
        # Append-only JSONL manifest: O_APPEND single-line writes survive
        # concurrent writers (a read-modify-write JSON doc loses entries
        # when two env runners race) and a truncated tail line from a crash
        # corrupts only itself, not the whole manifest.
        entry = {"file": os.path.basename(fname), "rows": int(n),
                 "columns": sorted(columns)}
        with open(os.path.join(self.path, "manifest.jsonl"), "a") as f:
            f.write(json.dumps(entry) + "\n")
        return fname


def write_fragments(frags: Sequence[Dict[str, Any]], path: str) -> str:
    """Flatten [T,N] rollout fragments (utils/rollout.py layout) into
    transition columns and append them as one shard. Invalid (autoreset)
    rows are dropped at write time so readers see only real transitions."""
    cols: Dict[str, List[np.ndarray]] = {
        "obs": [], "actions": [], "rewards": [], "dones": [], "logp": []}
    for f in frags:
        T, N = f["actions"].shape
        valid = f["valid"].reshape(T * N) > 0

        def flat(x):
            return x.reshape(T * N, *x.shape[2:])[valid]

        cols["obs"].append(flat(f["obs"]))
        cols["actions"].append(flat(f["actions"]))
        cols["rewards"].append(flat(f["rewards"]))
        cols["dones"].append(flat(f["dones"]))
        cols["logp"].append(flat(f["logp"]))
    merged = {k: np.concatenate(v) for k, v in cols.items()}
    return JsonWriter(path).write(merged)


def write_transitions(columns: Dict[str, np.ndarray], path: str) -> str:
    """Append one shard of FLAT transition columns (offline continuous-RL
    data: obs/actions/rewards/next_obs/dones — the (s, a, r, s', d) tuples
    CQL/SAC-style learners consume, vs write_fragments' [T,N] on-policy
    rollout layout). All columns must share the leading length."""
    n = {k: len(v) for k, v in columns.items()}
    if len(set(n.values())) != 1:
        raise ValueError(f"ragged transition columns: {n}")
    return JsonWriter(path).write(dict(columns))


def read_experiences(path: str):
    """Offline dataset of transitions as a ray_tpu.data Dataset (the
    reference's OfflineData-on-ray.data design, rllib/offline/offline_data.py)."""
    import glob as globlib

    from ray_tpu import data as rd

    files = sorted(globlib.glob(os.path.join(path, "experiences-*.npz")))
    if not files:
        raise FileNotFoundError(f"no experience shards under {path!r}")
    blocks = []
    for fn in files:
        with np.load(fn) as z:
            blocks.append({k: z[k] for k in z.files})
    return rd.from_blocks(blocks)


def load_columns(path: str) -> Dict[str, np.ndarray]:
    """All shards concatenated into one columnar dict (cacheable)."""
    ds = read_experiences(path)
    cols: Dict[str, List[np.ndarray]] = {}
    for batch in ds.iter_batches(batch_format="numpy"):
        for k, v in batch.items():
            cols.setdefault(k, []).append(v)
    return {k: np.concatenate(v) for k, v in cols.items()}


def iter_offline_batches(path_or_columns, batch_size: int, *,
                         epochs: int = 1, seed: int = 0
                         ) -> Iterator[Dict[str, np.ndarray]]:
    """Shuffled minibatches over all shards. Accepts a path (loads every
    call) or a pre-loaded load_columns() dict (the cached fast path).
    A dataset smaller than batch_size yields ONE undersized batch rather
    than silently yielding nothing."""
    full = (path_or_columns if isinstance(path_or_columns, dict)
            else load_columns(path_or_columns))
    n = len(full["actions"])
    rng = np.random.default_rng(seed)
    for _ in range(epochs):
        order = rng.permutation(n)
        starts = list(range(0, max(n - batch_size + 1, 1), batch_size))
        for s in starts:
            idx = order[s:s + batch_size]
            yield {k: v[idx] for k, v in full.items()}
