"""Behavior Cloning: the offline-RL baseline algorithm.

Parity: reference rllib/algorithms/bc (trains the policy head to imitate
logged actions from offline data; the env is used only for the module's
spaces and optional evaluation). Data comes from experience shards written
by offline.io (the output side of the reference's offline_data pipeline).
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from ..algorithm import Algorithm
from ..algorithm_config import AlgorithmConfig
from ..core.learner import JaxLearner
from .io import iter_offline_batches, load_columns


class BCConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or BC)
        self.input_path: str = ""
        self.steps_per_iteration: int = 32

    def offline_data(self, *, input_path: str,
                     steps_per_iteration: int = None) -> "BCConfig":
        self.input_path = input_path
        if steps_per_iteration is not None:
            self.steps_per_iteration = steps_per_iteration
        return self


class BCLearner(JaxLearner):
    """Negative log-likelihood of the logged actions (policy head only)."""

    def loss(self, params, batch, rng):
        out = self.module.forward(params, batch["obs"])
        dist = self.module.action_dist(out["logits"])
        logp = dist.logp(batch["actions"])
        nll = -logp.mean()
        return nll, {"bc_nll": nll, "entropy": dist.entropy().mean()}


class BC(Algorithm):
    config_cls = BCConfig

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()
        mesh = cfg.learner_mesh

        def factory():
            return BCLearner(module_factory(), lr=cfg.lr,
                             grad_clip=cfg.grad_clip, mesh=mesh,
                             seed=cfg.seed)

        return factory

    def training_step(self) -> Dict[str, Any]:
        cfg = self._algo_config
        if not cfg.input_path:
            raise ValueError("BC requires offline_data(input_path=...)")
        # Load the corpus once; only the shuffle varies per iteration.
        cache = getattr(self, "_offline_columns", None)
        if cache is None:
            cache = self._offline_columns = load_columns(cfg.input_path)
        it = iter_offline_batches(
            cache, cfg.minibatch_size or 128,
            seed=cfg.seed + self._iteration)
        metrics: Dict[str, Any] = {}
        steps = 0
        for batch in it:
            batch = dict(batch)
            batch.setdefault(
                "mask", jnp.ones(len(batch["actions"]), jnp.float32))
            metrics = self.learner_group.update(batch)
            steps += 1
            if steps >= cfg.steps_per_iteration:
                break
        out = dict(metrics)
        out["sgd_steps_this_iter"] = steps
        out["env_steps_this_iter"] = 0
        return out
