"""CQL (Conservative Q-Learning): offline continuous control.

Parity: reference rllib/algorithms/cql/ — SAC's losses plus the
conservative regularizer that penalizes Q-values of out-of-distribution
actions, trained purely from logged transitions (no env interaction; the
env supplies only the spaces).

The penalty per critic is

    alpha_cql * E_s[ logsumexp_a Q(s, a) - Q(s, a_data) ]

with the logsumexp estimated over a mix of uniform-random and
current-policy actions (importance-corrected, Kumar et al. 2020 eq. 4 as
implemented by the reference). Everything rides SACLearner's single jitted
update — the penalty is just more terms in the same loss — so the TPU
story is unchanged: one program, one optimizer, stop_gradient isolation.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..algorithms.sac import SAC, SACConfig, SACLearner, SACModule
from .io import iter_offline_batches, load_columns


class CQLConfig(SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class=algo_class or CQL)
        self.input_path: str = ""
        self.steps_per_iteration: int = 32
        self.cql_alpha: float = 1.0
        self.cql_n_actions: int = 4

    def offline_data(self, *, input_path: str,
                     steps_per_iteration: int = None) -> "CQLConfig":
        self.input_path = input_path
        if steps_per_iteration is not None:
            self.steps_per_iteration = steps_per_iteration
        return self


class CQLLearner(SACLearner):
    def loss(self, params, batch, rng):
        base_loss, metrics = super().loss(params, batch, rng)
        cfg = self.cfg
        m: SACModule = self.module
        obs = batch["obs"]
        B = obs.shape[0]
        N = cfg.cql_n_actions
        r_unif, r_pi = jax.random.split(jax.random.fold_in(rng, 7))

        # Q over N uniform + N policy actions per state: tile obs to
        # [B*N, ...] so the critics run ONE batched matmul per set.
        rep = jnp.repeat(obs, N, axis=0)
        unif = jax.random.uniform(r_unif, (B * N, m.act_dim),
                                  minval=-1.0, maxval=1.0)
        pi_act, pi_logp = m.sample_action(params, rep, r_pi)
        q1_u, q2_u = m.q_values(params, rep, unif)
        q1_p, q2_p = m.q_values(params, rep, pi_act)
        # Importance correction: uniform proposals have log-density
        # -act_dim*log(2); policy proposals use their own logp.
        log_u = float(np.log(0.5)) * m.act_dim
        cat1 = jnp.concatenate([
            q1_u.reshape(B, N) - log_u,
            q1_p.reshape(B, N) - jax.lax.stop_gradient(
                pi_logp.reshape(B, N))], axis=1)
        cat2 = jnp.concatenate([
            q2_u.reshape(B, N) - log_u,
            q2_p.reshape(B, N) - jax.lax.stop_gradient(
                pi_logp.reshape(B, N))], axis=1)
        lse1 = jax.scipy.special.logsumexp(cat1, axis=1) - jnp.log(2 * N)
        lse2 = jax.scipy.special.logsumexp(cat2, axis=1) - jnp.log(2 * N)

        data_act = jnp.clip((batch["actions"] - m._center) / m._scale,
                            -0.999, 0.999)
        q1_d, q2_d = m.q_values(params, obs, data_act)
        penalty = ((lse1 - q1_d).mean() + (lse2 - q2_d).mean())
        loss = base_loss + cfg.cql_alpha * penalty
        metrics = dict(metrics)
        metrics["cql_penalty"] = penalty
        return loss, metrics


class CQL(SAC):
    config_cls = CQLConfig

    def _learner_factory(self):
        cfg = self._algo_config
        module_factory = self._module_factory()

        def factory():
            return CQLLearner(module_factory(), cfg, mesh=cfg.learner_mesh,
                              seed=cfg.seed)

        return factory

    def training_step(self) -> Dict[str, Any]:
        """Pure offline: shuffled minibatches of logged transitions into
        SAC's update (reference cql.py training_step over OfflineData)."""
        cfg = self._algo_config
        if not cfg.input_path:
            raise ValueError("CQL requires offline_data(input_path=...)")
        cache = getattr(self, "_offline_columns", None)
        if cache is None:
            cache = self._offline_columns = load_columns(cfg.input_path)
            need = {"obs", "actions", "rewards", "next_obs", "dones"}
            missing = need - set(cache)
            if missing:
                raise ValueError(
                    f"CQL shards lack transition columns: {sorted(missing)}")
        metrics: Dict[str, Any] = {}
        steps = 0
        for batch in iter_offline_batches(
                cache, cfg.minibatch_size or 256,
                seed=cfg.seed + self._iteration):
            metrics = self.learner_group.call("update_sac", {
                k: batch[k] for k in
                ("obs", "actions", "rewards", "next_obs", "dones")})
            steps += 1
            if steps >= cfg.steps_per_iteration:
                break
        out = dict(metrics)
        out["sgd_steps_this_iter"] = steps
        out["env_steps_this_iter"] = 0
        return out
