from .io import (JsonWriter, read_experiences, write_fragments,
                 write_transitions)
from .bc import BC, BCConfig
from .cql import CQL, CQLConfig
from .marwil import MARWIL, MARWILConfig

__all__ = ["BC", "BCConfig", "CQL", "CQLConfig", "MARWIL", "MARWILConfig",
           "JsonWriter", "read_experiences", "write_fragments",
           "write_transitions"]
