from .io import JsonWriter, read_experiences, write_fragments
from .bc import BC, BCConfig

__all__ = ["BC", "BCConfig", "JsonWriter", "read_experiences",
           "write_fragments"]
