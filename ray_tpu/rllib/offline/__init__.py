from .io import (JsonWriter, read_experiences, write_fragments,
                 write_transitions)
from .bc import BC, BCConfig
from .cql import CQL, CQLConfig

__all__ = ["BC", "BCConfig", "CQL", "CQLConfig", "JsonWriter",
           "read_experiences", "write_fragments", "write_transitions"]
