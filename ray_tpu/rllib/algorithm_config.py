"""Fluent AlgorithmConfig.

Parity: reference rllib/algorithms/algorithm_config.py:117 (fluent
`.environment() .env_runners() .training() .learners() .evaluation()`
:1216). Resource knobs speak TPU: a learner mesh spec instead of
num_gpus_per_learner.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, Optional, Type


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[Type] = None):
        self.algo_class = algo_class
        # environment()
        self.env: Optional[str] = None
        self.env_creator: Optional[Callable[[], Any]] = None
        self.env_config: Dict[str, Any] = {}
        # env_runners()
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        self.env_to_module_connector: Optional[Any] = None
        # Zero-arg factory -> ConnectorV2 applied to ACTIONS before
        # env.step (reference module_to_env pipeline).
        self.module_to_env_connector: Optional[Any] = None
        # Zero-arg factory -> LearnerConnector applied to fragments before
        # advantage estimation (reference learner pipeline; set via
        # .training(learner_connector=...)).
        self.learner_connector: Optional[Any] = None
        # Fragment sampling ([T,N] columns, utils/rollout.py) is the
        # throughput default for PPO; False restores the episode-based
        # sampler (comparison/debug).
        self.use_fragments: bool = True
        # "sync" | "async": gym vector env backend (async = subprocess per
        # env, for CPU-heavy env steps on many-core hosts).
        self.vectorize_mode: str = "sync"
        # training()
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 4000
        self.minibatch_size: Optional[int] = 128
        self.num_epochs: int = 4
        self.grad_clip: Optional[float] = 0.5
        self.model: Dict[str, Any] = {}
        self.max_episode_len: int = 512
        # learners()
        self.num_learners: int = 0
        self.learner_mesh: Optional[Any] = None  # parallel.MeshSpec or Mesh
        # evaluation()
        self.evaluation_interval: int = 0
        self.evaluation_num_episodes: int = 3
        # reporting
        self.metrics_num_episodes_for_smoothing: int = 100
        # debugging()
        self.seed: int = 0
        # algo-specific extras live in subclass __init__.

    # ------------------------------------------------------------- builders

    def environment(self, env: Optional[str] = None, *,
                    env_creator: Optional[Callable[[], Any]] = None,
                    env_config: Optional[Dict[str, Any]] = None
                    ) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_creator is not None:
            self.env_creator = env_creator
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    env_to_module_connector: Optional[Any] = None,
                    module_to_env_connector: Optional[Any] = None,
                    use_fragments: Optional[bool] = None,
                    vectorize_mode: Optional[str] = None,
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if use_fragments is not None:
            self.use_fragments = use_fragments
        if vectorize_mode is not None:
            self.vectorize_mode = vectorize_mode
        if env_to_module_connector is not None:
            # Zero-arg factory returning a ConnectorV2 / ConnectorPipeline
            # (reference: config.env_runners(env_to_module_connector=...)).
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 learner_mesh: Optional[Any] = None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if learner_mesh is not None:
            self.learner_mesh = learner_mesh
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_num_episodes: Optional[int] = None
                   ) -> "AlgorithmConfig":
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_episodes is not None:
            self.evaluation_num_episodes = evaluation_num_episodes
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    # ------------------------------------------------------------------ misc

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def make_env_creator(self) -> Callable[[], Any]:
        if self.env_creator is not None:
            return self.env_creator
        if self.env is None:
            raise ValueError("config.environment(env=...) not set")
        env_id, env_cfg = self.env, dict(self.env_config)

        def creator():
            import gymnasium as gym

            return gym.make(env_id, **env_cfg)

        return creator

    def build_algo(self):
        if self.algo_class is None:
            raise ValueError("no algo_class bound to this config")
        return self.algo_class(self)

    # legacy alias (reference .build())
    build = build_algo
