"""LearnerGroup: local or actor-hosted learners.

Parity: reference rllib/core/learner/learner_group.py:69 (update_from_batch
:219, remote learner actors via FaultTolerantActorManager :178). The torch
multi-learner design (N GPU actors + DDP among them) maps to TPU as ONE
learner process per host driving the whole mesh — data-parallel gradient
reduction happens inside the jitted update over the `data` mesh axis, so
"num_learners" here controls actor placement (off-driver training), not a
second collective system.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import ray_tpu


class _LearnerActor:
    """Hosts a JaxLearner inside a (TPU) actor process."""

    def __init__(self, learner_factory):
        self.learner = learner_factory()

    def update(self, batch, **kw):
        return self.learner.update(batch, **kw)

    def call(self, method, *args, **kw):
        return getattr(self.learner, method)(*args, **kw)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, w):
        self.learner.set_weights(w)

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, s):
        self.learner.set_state(s)


class LearnerGroup:
    def __init__(
        self,
        learner_factory: Callable[[], Any],
        *,
        num_learners: int = 0,
        learner_resources: Optional[Dict[str, float]] = None,
    ):
        """num_learners=0 — learner lives in the driver process (the common
        single-host TPU case; the mesh does the scaling). num_learners=1 —
        learner hosted in a dedicated actor (e.g. pinned to the TPU host
        while the driver runs elsewhere)."""
        self._remote = num_learners > 0
        if self._remote:
            opts = dict(learner_resources or {"num_cpus": 1})
            cls = ray_tpu.remote(_LearnerActor).options(**opts)
            self._actor = cls.remote(learner_factory)
            # Fail fast if the learner can't construct.
            ray_tpu.get(self._actor.get_weights.remote())
            self._learner = None
        else:
            self._learner = learner_factory()
            self._actor = None

    def update(self, batch, **kw) -> Dict[str, float]:
        if self._remote:
            return ray_tpu.get(self._actor.update.remote(batch, **kw))
        return self._learner.update(batch, **kw)

    def call(self, method: str, *args, **kw) -> Any:
        """Invoke an algorithm-specific learner method (e.g. DQN's
        update_td) in whichever process hosts the learner."""
        if self._remote:
            return ray_tpu.get(self._actor.call.remote(method, *args, **kw))
        return getattr(self._learner, method)(*args, **kw)

    def get_weights(self) -> Any:
        if self._remote:
            return ray_tpu.get(self._actor.get_weights.remote())
        return self._learner.get_weights()

    def set_weights(self, w) -> None:
        if self._remote:
            ray_tpu.get(self._actor.set_weights.remote(w))
        else:
            self._learner.set_weights(w)

    def get_state(self) -> Dict[str, Any]:
        if self._remote:
            return ray_tpu.get(self._actor.get_state.remote())
        return self._learner.get_state()

    def set_state(self, state) -> None:
        if self._remote:
            ray_tpu.get(self._actor.set_state.remote(state))
        else:
            self._learner.set_state(state)

    def shutdown(self) -> None:
        if self._actor is not None:
            try:
                ray_tpu.kill(self._actor)
            except Exception:
                pass
