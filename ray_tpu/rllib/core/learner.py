"""JaxLearner: the TPU training half of the RL stack.

Parity: reference rllib/core/learner/learner.py + torch_learner.py — but the
GPU/DDP path (TorchDDPRLModule wrapping, per-learner NCCL) is replaced by
ONE jitted update over a device mesh: gradients reduce over the `data` mesh
axis inside the compiled program (pjit inserts the psum), minibatch SGD
epochs run as a host loop over device-resident shards. The learner is
framework-complete for policy-gradient losses; algorithms subclass and
implement `loss(params, batch, rng)`.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.rl_module import RLModule


class JaxLearner:
    def __init__(
        self,
        module: RLModule,
        *,
        lr: float = 3e-4,
        grad_clip: Optional[float] = 0.5,
        optimizer: Optional[optax.GradientTransformation] = None,
        mesh: Optional[Mesh] = None,
        seed: int = 0,
    ):
        self.module = module
        self.mesh = mesh
        tx = optimizer or optax.adam(lr)
        if grad_clip is not None:
            tx = optax.chain(optax.clip_by_global_norm(grad_clip), tx)
        self.optimizer = tx
        self._rng = jax.random.key(seed)
        self.params = self.module.init(jax.random.key(seed))
        self.opt_state = self.optimizer.init(self.params)
        if mesh is not None:
            # Params replicated over the mesh; batches shard over `data`.
            rep = NamedSharding(mesh, P())
            self.params = jax.device_put(self.params, rep)
            self.opt_state = jax.device_put(self.opt_state, rep)
        self._jit_update = jax.jit(self._update, donate_argnums=(0, 1))

    # ------------------------------------------------------------------ loss

    def loss(self, params, batch: Dict[str, jax.Array], rng: jax.Array
             ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Return (scalar loss, metrics). Implemented by the algorithm."""
        raise NotImplementedError

    # ---------------------------------------------------------------- update

    def _update(self, params, opt_state, batch, rng):
        (loss, metrics), grads = jax.value_and_grad(
            self.loss, has_aux=True)(params, batch, rng)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def _shard_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        if self.mesh is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        axes = tuple(a for a in ("data", "fsdp")
                     if a in self.mesh.axis_names and self.mesh.shape[a] > 1)

        def put(v):
            if np.ndim(v) == 0 or not axes:
                return jax.device_put(v, NamedSharding(self.mesh, P()))
            spec = P(axes, *([None] * (np.ndim(v) - 1)))
            return jax.device_put(v, NamedSharding(self.mesh, spec))

        return {k: put(v) for k, v in batch.items()}

    def update(
        self,
        batch: Dict[str, np.ndarray],
        *,
        minibatch_size: Optional[int] = None,
        num_epochs: int = 1,
        shuffle: bool = True,
    ) -> Dict[str, float]:
        """Minibatch-SGD over the batch; returns averaged metrics."""
        n = next(iter(batch.values())).shape[0]
        # Clamp: a requested minibatch larger than the batch must still run
        # ONE full-batch step, not silently zero (range below would be
        # empty). Tail rows that don't fill a minibatch are dropped, as in
        # the reference's minibatch iterator.
        mb = min(minibatch_size or n, n)
        all_metrics: list = []
        rng_np = np.random.default_rng(int(jax.random.randint(
            self._consume_rng(), (), 0, 2**31 - 1)))
        for _ in range(num_epochs):
            idx = rng_np.permutation(n) if shuffle else np.arange(n)
            for start in range(0, n - mb + 1, mb):
                rows = idx[start:start + mb]
                sub = {k: v[rows] for k, v in batch.items()}
                dev_batch = self._shard_batch(sub)
                self.params, self.opt_state, metrics = self._jit_update(
                    self.params, self.opt_state, dev_batch,
                    self._consume_rng())
                all_metrics.append(metrics)
        if not all_metrics:
            return {}
        out: Dict[str, float] = {}
        for k in all_metrics[0]:
            out[k] = float(np.mean([float(m[k]) for m in all_metrics]))
        return out

    def _consume_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ----------------------------------------------------------- state/ckpt

    def get_weights(self) -> Any:
        return jax.device_get(self.params)

    def set_weights(self, weights: Any) -> None:
        if self.mesh is not None:
            weights = jax.device_put(
                weights, NamedSharding(self.mesh, P()))
        self.params = weights

    def get_state(self) -> Dict[str, Any]:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        self.set_weights(state["params"])
        self.opt_state = state["opt_state"]
        if self.mesh is not None:
            self.opt_state = jax.device_put(
                self.opt_state, NamedSharding(self.mesh, P()))
