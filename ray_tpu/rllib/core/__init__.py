from .rl_module import MLPModule, RLModule
from .learner import JaxLearner
from .learner_group import LearnerGroup

__all__ = ["RLModule", "MLPModule", "JaxLearner", "LearnerGroup"]
