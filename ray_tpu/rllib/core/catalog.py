"""Default model catalog: obs/action space -> RLModule.

Parity: reference rllib/core/models/catalog.py (1.1k LoC of framework
branching collapses here: one MLP family, one Nature-CNN family for pixels,
both plain jax). Conv layers use lax.conv_general_dilated in NHWC — XLA
lowers these onto the MXU directly.
"""
from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .rl_module import MLPModule, Params, RLModule, _dense, _dense_init

# (out_channels, kernel, stride) — the Nature DQN/IMPALA-shallow stack.
NATURE_CONV = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


class CNNModule(RLModule):
    """Pixel policy: shared conv trunk + separate pi/vf heads (reference
    catalog's conv defaults for Atari)."""

    def __init__(self, obs_shape: Tuple[int, int, int], num_actions: int,
                 conv: Sequence[Tuple[int, int, int]] = NATURE_CONV,
                 hidden: int = 512):
        self.obs_shape = obs_shape  # (H, W, C)
        self.num_actions = num_actions
        self.conv = tuple(conv)
        self.hidden = hidden

    def _conv_out_dim(self) -> int:
        h, w, _ = self.obs_shape
        for _, k, s in self.conv:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
        return h * w * self.conv[-1][0]

    def init(self, rng: jax.Array) -> Params:
        keys = jax.random.split(rng, len(self.conv) + 3)
        convs = []
        c_in = self.obs_shape[-1]
        for i, (c_out, k, _) in enumerate(self.conv):
            fan_in = k * k * c_in
            w = jax.random.normal(keys[i], (k, k, c_in, c_out)) * np.sqrt(
                2.0 / fan_in)
            convs.append({"w": w.astype(jnp.float32),
                          "b": jnp.zeros((c_out,), jnp.float32)})
            c_in = c_out
        flat = self._conv_out_dim()
        return {
            "convs": convs,
            "trunk": _dense_init(keys[-3], flat, self.hidden),
            "pi": _dense_init(keys[-2], self.hidden, self.num_actions,
                              scale=0.01),
            "vf": _dense_init(keys[-1], self.hidden, 1, scale=1.0),
        }

    def forward(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        x = obs.astype(jnp.float32)
        if x.dtype != jnp.float32 or obs.dtype == jnp.uint8:
            x = x / 255.0
        for p, (_, _, stride) in zip(params["convs"], self.conv):
            x = jax.lax.conv_general_dilated(
                x, p["w"], (stride, stride), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + p["b"])
        x = x.reshape(x.shape[0], -1)
        h = jax.nn.relu(_dense(params["trunk"], x))
        logits = _dense(params["pi"], h)
        vf = _dense(params["vf"], h)[..., 0]
        return {"logits": logits, "vf": vf}


def module_for_space(obs_space, act_space, model_config: Dict[str, Any]) -> RLModule:
    """gymnasium spaces -> default RLModule."""
    import gymnasium as gym

    if not isinstance(act_space, gym.spaces.Discrete):
        raise NotImplementedError(
            f"only Discrete action spaces supported, got {act_space}")
    shape = obs_space.shape
    if len(shape) == 3:
        return CNNModule(shape, int(act_space.n),
                         conv=model_config.get("conv", NATURE_CONV),
                         hidden=model_config.get("hidden", 512))
    if len(shape) == 1:
        return MLPModule(shape[0], int(act_space.n),
                         hiddens=model_config.get("fcnet_hiddens", (64, 64)))
    raise NotImplementedError(f"unsupported obs shape {shape}")
