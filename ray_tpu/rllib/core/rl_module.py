"""RLModule: the model abstraction of the RL stack.

Parity: reference rllib/core/rl_module/rl_module.py (forward_inference /
forward_exploration / forward_train) — but functional: params are an
explicit pytree (works under pjit/pmap and donates cleanly), and the module
object holds only architecture. The default MLPModule covers the CartPole/
classic-control family; CNNModule (atari) in catalog.py.
"""
from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Any


class RLModule:
    """Interface. forward returns {"logits": [B, A], "vf": [B]}."""

    def init(self, rng: jax.Array) -> Params:
        raise NotImplementedError

    def forward(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        raise NotImplementedError

    # ------------------------------------------------------- action sampling

    def action_dist(self, logits: jax.Array):
        return CategoricalDist(logits)

    def forward_inference(self, params: Params, obs: jax.Array) -> jax.Array:
        """Greedy action."""
        out = self.forward(params, obs)
        return jnp.argmax(out["logits"], axis=-1)

    def forward_exploration(
        self, params: Params, obs: jax.Array, rng: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Sampled action, its logp, and the value estimate."""
        out = self.forward(params, obs)
        dist = self.action_dist(out["logits"])
        action = dist.sample(rng)
        return action, dist.logp(action), out["vf"]


class CategoricalDist:
    def __init__(self, logits: jax.Array):
        self.logits = logits

    def sample(self, rng: jax.Array) -> jax.Array:
        return jax.random.categorical(rng, self.logits, axis=-1)

    def logp(self, action: jax.Array) -> jax.Array:
        logp_all = jax.nn.log_softmax(self.logits, axis=-1)
        return jnp.take_along_axis(
            logp_all, action[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self) -> jax.Array:
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def _dense_init(rng, n_in, n_out, scale=np.sqrt(2.0)):
    w = jax.random.orthogonal(rng, max(n_in, n_out))[:n_in, :n_out] * scale
    return {"w": w.astype(jnp.float32), "b": jnp.zeros((n_out,), jnp.float32)}


def _dense(p, x):
    return x @ p["w"] + p["b"]


class MLPModule(RLModule):
    """Separate policy/value MLP trunks (reference models/catalog.py default
    fcnet); orthogonal init, tanh activations — the classic PPO recipe."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(hiddens)

    def init(self, rng: jax.Array) -> Params:
        sizes = (self.obs_dim,) + self.hiddens
        n = len(self.hiddens)
        keys = jax.random.split(rng, 2 * n + 2)
        pi = [_dense_init(keys[i], sizes[i], sizes[i + 1]) for i in range(n)]
        vf = [_dense_init(keys[n + i], sizes[i], sizes[i + 1])
              for i in range(n)]
        pi.append(_dense_init(keys[-2], sizes[-1], self.num_actions,
                              scale=0.01))
        vf.append(_dense_init(keys[-1], sizes[-1], 1, scale=1.0))
        return {"pi": pi, "vf": vf}

    def forward(self, params: Params, obs: jax.Array) -> Dict[str, jax.Array]:
        x = obs.astype(jnp.float32)
        h = x
        for layer in params["pi"][:-1]:
            h = jnp.tanh(_dense(layer, h))
        logits = _dense(params["pi"][-1], h)
        h = x
        for layer in params["vf"][:-1]:
            h = jnp.tanh(_dense(layer, h))
        vf = _dense(params["vf"][-1], h)[..., 0]
        return {"logits": logits, "vf": vf}
