from .connector import (ConnectorPipeline, ConnectorV2, FlattenObs,
                        FrameStack, NormalizeObs)

__all__ = ["ConnectorV2", "ConnectorPipeline", "FlattenObs", "NormalizeObs",
           "FrameStack"]
