from .connector import (ClipActions, ClipRewards, ConnectorPipeline,
                        ConnectorV2, FlattenObs, FrameStack, GrayScale,
                        LearnerConnector, LearnerConnectorPipeline,
                        NormalizeObs, ResizeImage, ScaleObs,
                        UnsquashActions, atari_preprocessor)

__all__ = ["ConnectorV2", "ConnectorPipeline", "FlattenObs", "NormalizeObs",
           "FrameStack", "GrayScale", "ResizeImage", "ScaleObs",
           "atari_preprocessor", "ClipActions", "UnsquashActions",
           "LearnerConnector", "LearnerConnectorPipeline", "ClipRewards"]
