"""Connectors: composable observation transforms between env and module.

Parity: reference rllib/connectors/connector_v2.py (ConnectorV2 pipelines on
the env-to-module path) — the round-2 verdict called out that transforms
were hard-wired into episodes_to_batch. A ConnectorPipeline runs inside the
env runner on the raw vectorized observations before the (jitted) policy
forward, and the same pipeline is applied when replaying episodes into
training batches, so the module always sees identically transformed
observations in sampling and learning.

Connectors are plain objects with numpy __call__ (the env side is CPU
work); stateful ones (FrameStack) keep per-env state and are reset on
episode boundaries.
"""
from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ConnectorV2:
    """One transform stage: obs batch [N, ...] -> obs batch [N, ...]."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset(self, env_index: Optional[int] = None) -> None:
        """Clear per-env state (episode boundary); None = all envs."""

    def output_shape(self, input_shape: Sequence[int]) -> Sequence[int]:
        """Shape of one transformed observation (for module sizing)."""
        return input_shape


class ConnectorPipeline(ConnectorV2):
    def __init__(self, connectors: Sequence[ConnectorV2]):
        self.connectors = list(connectors)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c(obs)
        return obs

    def reset(self, env_index: Optional[int] = None) -> None:
        for c in self.connectors:
            c.reset(env_index)

    def output_shape(self, input_shape):
        for c in self.connectors:
            input_shape = c.output_shape(input_shape)
        return input_shape


class FlattenObs(ConnectorV2):
    """[N, *dims] -> [N, prod(dims)] (reference FlattenObservations)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(obs).reshape(len(obs), -1)

    def output_shape(self, input_shape):
        return (int(np.prod(input_shape)),)


class NormalizeObs(ConnectorV2):
    """Running mean/std normalization (reference MeanStdFilter)."""

    def __init__(self, clip: float = 10.0, epsilon: float = 1e-8):
        self.clip = clip
        self.epsilon = epsilon
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.ones(obs.shape[1:], np.float64)
        for row in obs:  # Welford update per observation
            self._count += 1.0
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        std = np.sqrt(self._m2 / max(1.0, self._count - 1)) + self.epsilon
        out = (obs - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)


class FrameStack(ConnectorV2):
    """Stack the last k observations per env along the last axis
    (reference FrameStackingEnvToModule)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: Dict[int, "collections.deque"] = {}

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs)
        out = []
        for i, row in enumerate(obs):
            dq = self._frames.get(i)
            if dq is None or not dq:
                dq = collections.deque([row] * self.k, maxlen=self.k)
                self._frames[i] = dq
            else:
                dq.append(row)
            out.append(np.concatenate(list(dq), axis=-1))
        return np.stack(out)

    def reset(self, env_index: Optional[int] = None) -> None:
        if env_index is None:
            self._frames.clear()
        else:
            self._frames.pop(env_index, None)

    def output_shape(self, input_shape):
        shape = list(input_shape)
        shape[-1] = shape[-1] * self.k
        return tuple(shape)
