"""Connectors: the three composable transform pipelines around the module.

Parity: reference rllib/connectors/ (connector_v2.py + env_to_module/,
module_to_env/, learner/ pipeline packages):

- **env-to-module** (`ConnectorV2` here): raw vector observations ->
  module inputs, run inside the env runner before the (jitted) policy
  forward. Image preprocessing (GrayScale/ResizeImage/ScaleObs/FrameStack)
  lives on this path — the Atari chain of the reference's
  FrameStackingEnvToModule + gym wrappers.
- **module-to-env** (also `ConnectorV2`, applied to ACTIONS): module action
  outputs -> env actions (clip/unsquash for continuous spaces; reference
  module_to_env/unsquash_and_clip_actions). Buffers record the MODULE's
  actions; only the env sees the transformed ones.
- **learner** (`LearnerConnector`): [T, N] fragment columns -> fragment
  columns, applied by the algorithm BEFORE advantage estimation (the
  reference puts GAE itself in this pipeline; here GAE stays a jitted
  function and the connector handles the data transforms around it, e.g.
  Atari reward clipping).

Connectors are plain objects with numpy __call__ (the env side is CPU
work); stateful ones (FrameStack) keep per-env state and are reset on
episode boundaries.
"""
from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ConnectorV2:
    """One transform stage: obs batch [N, ...] -> obs batch [N, ...]."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset(self, env_index: Optional[int] = None) -> None:
        """Clear per-env state (episode boundary); None = all envs."""

    def output_shape(self, input_shape: Sequence[int]) -> Sequence[int]:
        """Shape of one transformed observation (for module sizing)."""
        return input_shape


class ConnectorPipeline(ConnectorV2):
    def __init__(self, connectors: Sequence[ConnectorV2]):
        self.connectors = list(connectors)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c(obs)
        return obs

    def reset(self, env_index: Optional[int] = None) -> None:
        for c in self.connectors:
            c.reset(env_index)

    def output_shape(self, input_shape):
        for c in self.connectors:
            input_shape = c.output_shape(input_shape)
        return input_shape


class FlattenObs(ConnectorV2):
    """[N, *dims] -> [N, prod(dims)] (reference FlattenObservations)."""

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(obs).reshape(len(obs), -1)

    def output_shape(self, input_shape):
        return (int(np.prod(input_shape)),)


class NormalizeObs(ConnectorV2):
    """Running mean/std normalization (reference MeanStdFilter)."""

    def __init__(self, clip: float = 10.0, epsilon: float = 1e-8):
        self.clip = clip
        self.epsilon = epsilon
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        if self._mean is None:
            self._mean = np.zeros(obs.shape[1:], np.float64)
            self._m2 = np.ones(obs.shape[1:], np.float64)
        for row in obs:  # Welford update per observation
            self._count += 1.0
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        std = np.sqrt(self._m2 / max(1.0, self._count - 1)) + self.epsilon
        out = (obs - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)


class FrameStack(ConnectorV2):
    """Stack the last k observations per env along the last axis
    (reference FrameStackingEnvToModule)."""

    def __init__(self, k: int = 4):
        self.k = k
        self._frames: Dict[int, "collections.deque"] = {}

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs)
        out = []
        for i, row in enumerate(obs):
            dq = self._frames.get(i)
            if dq is None or not dq:
                dq = collections.deque([row] * self.k, maxlen=self.k)
                self._frames[i] = dq
            else:
                dq.append(row)
            out.append(np.concatenate(list(dq), axis=-1))
        return np.stack(out)

    def reset(self, env_index: Optional[int] = None) -> None:
        if env_index is None:
            self._frames.clear()
        else:
            self._frames.pop(env_index, None)

    def output_shape(self, input_shape):
        shape = list(input_shape)
        shape[-1] = shape[-1] * self.k
        return tuple(shape)


# --------------------------------------------------------- image transforms


class GrayScale(ConnectorV2):
    """[N, H, W, C>=3] RGB -> [N, H, W, 1] luma; dtype preserved
    (reference: gym AtariPreprocessing grayscale_obs)."""

    _LUMA = np.array([0.299, 0.587, 0.114], np.float32)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs)
        gray = np.tensordot(obs[..., :3].astype(np.float32), self._LUMA,
                            axes=([-1], [0]))
        if np.issubdtype(obs.dtype, np.integer):
            gray = np.clip(np.rint(gray), 0, 255)
        return gray.astype(obs.dtype)[..., None]

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (1,)


class ResizeImage(ConnectorV2):
    """[N, H, W, C] -> [N, h, w, C]: block-mean ("area") when the source
    divides evenly, nearest-neighbor index maps otherwise (210x160 -> 84x84
    takes the nearest path); dtype preserved. Pure numpy — no cv2/PIL in
    this image."""

    def __init__(self, height: int = 84, width: int = 84):
        self.h, self.w = int(height), int(width)
        self._idx: Dict[Any, Any] = {}

    def _maps(self, H: int, W: int):
        key = (H, W)
        got = self._idx.get(key)
        if got is None:
            if H % self.h == 0 and W % self.w == 0:
                got = ("area", H // self.h, W // self.w)
            else:
                ri = np.minimum((np.arange(self.h) + 0.5) * H / self.h,
                                H - 1).astype(np.int64)
                ci = np.minimum((np.arange(self.w) + 0.5) * W / self.w,
                                W - 1).astype(np.int64)
                got = ("nearest", ri, ci)
            self._idx[key] = got
        return got

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs)
        N, H, W = obs.shape[:3]
        kind, a, b = self._maps(H, W)
        if kind == "area":
            out = obs.reshape(N, self.h, a, self.w, b, *obs.shape[3:])
            out = out.mean(axis=(2, 4))
            if np.issubdtype(obs.dtype, np.integer):
                out = np.rint(out)
            return out.astype(obs.dtype)
        return obs[:, a][:, :, b]

    def output_shape(self, input_shape):
        return (self.h, self.w) + tuple(input_shape[2:])


class ScaleObs(ConnectorV2):
    """uint8 pixels -> float32 in [0, 1] (reference: normalize_images)."""

    def __init__(self, scale: float = 1.0 / 255.0):
        self.scale = float(scale)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(obs, np.float32) * self.scale


def atari_preprocessor(k: int = 4, size: int = 84) -> ConnectorPipeline:
    """The standard Atari chain: gray -> resize -> scale -> stack-k.
    Pass the FUNCTION as env_to_module_connector (it is the factory).
    FrameStack concatenates along the channel axis, so the module sees
    [size, size, k] — the DQN-lineage CNN input layout."""
    return ConnectorPipeline(
        [GrayScale(), ResizeImage(size, size), ScaleObs(), FrameStack(k)])


# ------------------------------------------------- module-to-env (actions)


class ClipActions(ConnectorV2):
    """Clip continuous module actions into the env's bounds — scalars or
    per-dimension Box arrays (space.low/space.high), as in reference
    module_to_env clip_actions. No-op for integer/discrete arrays."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, actions: np.ndarray) -> np.ndarray:
        actions = np.asarray(actions)
        if np.issubdtype(actions.dtype, np.integer):
            return actions
        return np.clip(actions, self.low, self.high)


class UnsquashActions(ConnectorV2):
    """Map tanh-squashed module outputs in [-1, 1] onto [low, high]
    (scalar or per-dimension array bounds; reference module_to_env
    unsquash_actions)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, actions: np.ndarray) -> np.ndarray:
        actions = np.asarray(actions, np.float32)
        return self.low + (np.clip(actions, -1.0, 1.0) + 1.0) * 0.5 * (
            self.high - self.low)


# ------------------------------------------------------ learner connectors


class LearnerConnector:
    """One transform over a fragment dict of [T, N] columns (obs, actions,
    rewards, dones, truncs, valid, ...), applied before advantage
    estimation. Mutating a COPY keeps runner-side buffers intact."""

    def __call__(self, frag: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class LearnerConnectorPipeline(LearnerConnector):
    def __init__(self, connectors: Sequence[LearnerConnector]):
        self.connectors = list(connectors)

    def __call__(self, frag):
        for c in self.connectors:
            frag = c(frag)
        return frag


class ClipRewards(LearnerConnector):
    """Clip (or sign-compress) rewards before GAE/v-trace — the Atari
    convention (reference: learner pipeline reward clipping / the classic
    DQN sign(r))."""

    def __init__(self, bound: float = 1.0, sign: bool = False):
        self.bound = float(bound)
        self.sign = sign

    def __call__(self, frag):
        frag = dict(frag)
        r = np.asarray(frag["rewards"])
        frag["rewards"] = (np.sign(r) if self.sign
                           else np.clip(r, -self.bound, self.bound))
        return frag
