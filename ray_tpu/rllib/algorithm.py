"""Algorithm: the Tune-trainable RL loop.

Parity: reference rllib/algorithms/algorithm.py:213 (Algorithm(Trainable),
step :818, training_step :1586, save/restore). Builds the EnvRunnerGroup +
LearnerGroup from an AlgorithmConfig; `train()` = one training_step with
metric bookkeeping; checkpoints carry learner state (params+optimizer).
"""
from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.tune.trainable import Trainable

from .algorithm_config import AlgorithmConfig
from .core.learner_group import LearnerGroup
from .env.env_runner_group import EnvRunnerGroup


class Algorithm(Trainable):
    config_cls = AlgorithmConfig

    def __init__(self, config=None, **kwargs):
        if isinstance(config, AlgorithmConfig):
            self._algo_config = config
        elif isinstance(config, dict) or config is None:
            # From Tune: a plain dict of overrides onto the default config.
            base = self.get_default_config()
            for k, v in (config or {}).items():
                setattr(base, k, v)
            self._algo_config = base
        else:
            raise TypeError(f"bad config {type(config)}")
        super().__init__(config={}, **kwargs)

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls.config_cls(algo_class=cls)

    # ----------------------------------------------------------------- setup

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = self._algo_config
        self.env_runner_group = EnvRunnerGroup(
            cfg.make_env_creator(),
            self._module_factory(),
            num_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_env_runner,
            seed=cfg.seed,
            connector_factory=cfg.env_to_module_connector,
            action_connector_factory=cfg.module_to_env_connector,
            vectorize_mode=cfg.vectorize_mode,
        )
        self.learner_group = LearnerGroup(
            self._learner_factory(), num_learners=cfg.num_learners)
        # Learner-connector pipeline: sampled data passes through it before
        # advantage estimation (reference learner connector position). The
        # fragment path hands it [T, N] columns; the episode paths hand it
        # per-episode [T] columns via _connect_episodes.
        self._learner_connector = (cfg.learner_connector()
                                   if cfg.learner_connector else None)
        self._timesteps_total = 0
        self._episodes_total = 0
        self._recent_returns: list = []

    # -------------------------------------------------- algorithm interface

    def _module_factory(self):
        """Returns a zero-arg callable building the RLModule (must be
        cloudpickle-able: called inside env-runner actors)."""
        cfg = self._algo_config
        creator = cfg.make_env_creator()
        model_config = dict(cfg.model)
        connector_factory = cfg.env_to_module_connector

        def factory():
            import gymnasium as gym
            import numpy as np

            from .core.catalog import module_for_space

            # Batched-env factories (vector_env.BatchedEnv protocol, incl.
            # multi-agent wrappers) take a column count and expose
            # single_* spaces; plain creators build one gym env.
            if getattr(creator, "makes_batched_env", False):
                env = creator(1)
            else:
                env = creator()
            try:
                # Space access inside try: a space property that raises
                # must not leak the constructed env (subprocess/socket
                # envs stay open otherwise).
                if getattr(creator, "makes_batched_env", False):
                    obs_space = env.single_observation_space
                    action_space = env.single_action_space
                else:
                    obs_space = env.observation_space
                    action_space = env.action_space
                if connector_factory is not None:
                    # The module sees connector OUTPUT shapes.
                    shape = tuple(
                        connector_factory().output_shape(obs_space.shape))
                    obs_space = gym.spaces.Box(
                        low=-np.inf, high=np.inf, shape=shape,
                        dtype=np.float32)
                return module_for_space(obs_space, action_space,
                                        model_config)
            finally:
                env.close()

        return factory

    def _learner_factory(self):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # ------------------------------------------------------------ Trainable

    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        cfg = self._algo_config
        if (cfg.evaluation_interval
                and self._iteration % cfg.evaluation_interval == 0):
            result["evaluation_return_mean"] = self.env_runner_group.evaluate(
                cfg.evaluation_num_episodes)
        result.setdefault("timesteps_total", self._timesteps_total)
        result.setdefault("episodes_total", self._episodes_total)
        result["time_this_iter_s"] = time.time() - t0
        return result

    def _connect_episodes(self, episodes):
        """Apply the learner-connector pipeline on the episode-based paths
        (PPO use_fragments=False, IMPALA, DQN): each episode's columns pass
        through as a [T]-shaped dict BEFORE batch assembly / advantage
        estimation, mirroring the fragment path's position. Elementwise
        connectors (ClipRewards) work identically on both."""
        lc = self._learner_connector
        if lc is None:
            return episodes
        for ep in episodes:
            cols = {
                "rewards": np.asarray(ep.rewards, np.float32),
                "actions": np.asarray(ep.actions),
                "logp": np.asarray(ep.logp, np.float32),
                "vf_preds": np.asarray(ep.vf_preds, np.float32),
            }
            out = lc(cols)
            ep.rewards = [float(r) for r in out["rewards"]]
            if out["actions"] is not cols["actions"]:
                ep.actions = list(out["actions"])
            if out["logp"] is not cols["logp"]:
                ep.logp = [float(x) for x in out["logp"]]
            if out["vf_preds"] is not cols["vf_preds"]:
                ep.vf_preds = [float(x) for x in out["vf_preds"]]
        return episodes

    def _record_episodes(self, episodes) -> None:
        done = [e for e in episodes if e.is_done]
        self._episodes_total += len(done)
        self._timesteps_total += sum(len(e) for e in episodes)
        self._recent_returns.extend(e.total_reward() for e in done)
        window = self._algo_config.metrics_num_episodes_for_smoothing
        self._recent_returns = self._recent_returns[-window:]

    @property
    def episode_return_mean(self) -> float:
        if not self._recent_returns:
            return float("nan")
        return float(np.mean(self._recent_returns))

    # ---------------------------------------------------------- checkpoints

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        state = {
            "learner": self.learner_group.get_state(),
            "timesteps_total": self._timesteps_total,
            "episodes_total": self._episodes_total,
        }
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self._timesteps_total = state["timesteps_total"]
        self._episodes_total = state["episodes_total"]

    def cleanup(self) -> None:
        self.env_runner_group.stop()
        self.learner_group.shutdown()
