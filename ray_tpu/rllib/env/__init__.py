from .env_runner import SingleAgentEnvRunner
from .env_runner_group import EnvRunnerGroup

__all__ = ["SingleAgentEnvRunner", "EnvRunnerGroup"]
