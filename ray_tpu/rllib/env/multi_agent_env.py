"""Multi-agent environments with shared-policy training.

Parity: reference rllib/env/multi_agent_env.py (the dict-keyed
reset/step protocol with the "__all__" done key). The TPU-native training
integration is ``MultiAgentBatchedEnv``: each (env instance, agent) pair
becomes one COLUMN of the batched-env protocol (vector_env.BatchedEnv), so
the fragment sampler and PPO train a parameter-shared policy over all
agents with zero new sampling machinery — one batched forward covers every
agent of every env instance (the reference's shared-policy / parameter
sharing setup, its most common multi-agent configuration).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .vector_env import BatchedEnv


class MultiAgentEnv:
    """Dict-keyed protocol (reference multi_agent_env.py):

    - ``possible_agents``: fixed agent-id list (defines column order).
    - ``reset() -> obs_dict`` with one entry per (live) agent.
    - ``step(action_dict) -> (obs, rewards, terminations, truncations)``
      dicts; terminations/truncations may carry "__all__".
    Agents absent from an obs dict are done until the next reset.
    """

    possible_agents: Sequence[Any] = ()
    single_observation_space: Any = None
    single_action_space: Any = None

    def reset(self, seed: Optional[int] = None) -> Dict[Any, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[Any, Any]):
        raise NotImplementedError


class MultiAgentBatchedEnv(BatchedEnv):
    """num_instances copies of a MultiAgentEnv flattened to columns.

    Column layout: instance-major, agent-minor — column
    ``i * n_agents + j`` is agent j of instance i. An agent done before
    "__all__" keeps emitting zero-reward done=False rows that are MASKED
    (valid=0) until its episode resets, so fragment GAE never mixes a dead
    agent's padding into the learning signal.
    """

    autoreset_mode = "same_step"

    def __init__(self, env_creator: Callable[[], MultiAgentEnv],
                 num_instances: int, seed: int = 0):
        self.envs: List[MultiAgentEnv] = [env_creator()
                                          for _ in range(num_instances)]
        proto = self.envs[0]
        self.agents = list(proto.possible_agents)
        if not self.agents:
            raise ValueError("MultiAgentEnv.possible_agents must be set")
        self.n_agents = len(self.agents)
        self.num_envs = num_instances * self.n_agents
        self.single_observation_space = proto.single_observation_space
        self.single_action_space = proto.single_action_space
        self._seed = seed
        self._episode = 0  # rollover seeds must differ every episode
        self._obs: Optional[np.ndarray] = None
        self._dead = np.zeros(self.num_envs, bool)

    # BatchedEnv extension: the sampler masks these columns (dead agents
    # waiting for their instance's episode to finish).
    def dead_mask(self) -> np.ndarray:
        return self._dead.copy()

    def _col(self, i: int, agent) -> int:
        return i * self.n_agents + self.agents.index(agent)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        base = self._seed if seed is None else seed
        obs_shape = None
        rows = []
        for i, env in enumerate(self.envs):
            od = env.reset(seed=base + i)
            for a in self.agents:
                rows.append(np.asarray(od[a]))
                obs_shape = rows[-1].shape
        self._obs = np.stack(rows)
        self._dead[:] = False
        return self._obs

    def step(self, actions: np.ndarray):
        N = self.num_envs
        obs = self._obs.copy()
        rew = np.zeros(N, np.float32)
        term = np.zeros(N, bool)
        trunc = np.zeros(N, bool)
        for i, env in enumerate(self.envs):
            live = [a for a in self.agents
                    if not self._dead[self._col(i, a)]]
            act = {a: actions[self._col(i, a)] for a in live}
            od, rd, td, ud = env.step(act)
            term_all = bool(td.get("__all__", False))
            trunc_all = bool(ud.get("__all__", False))
            all_done = term_all or trunc_all
            for a in live:
                c = self._col(i, a)
                rew[c] = float(rd.get(a, 0.0))
                # "__all__" truncation must stay a truncation per agent —
                # conflating it with termination would zero the GAE
                # bootstrap on every time-limit episode.
                a_term = bool(td.get(a, False)) or term_all
                a_trunc = (bool(ud.get(a, False)) or trunc_all)
                term[c] = a_term
                trunc[c] = a_trunc and not a_term
                if a in od:
                    obs[c] = np.asarray(od[a])
                if (a_term or a_trunc) and not all_done:
                    self._dead[c] = True
            if all_done:
                # Advancing seed: a constant here would make seed-respecting
                # envs replay the same episode forever.
                self._episode += 1
                od = env.reset(
                    seed=self._seed + i + 7919 * self._episode)
                for a in self.agents:
                    c = self._col(i, a)
                    obs[c] = np.asarray(od[a])
                    self._dead[c] = False
        self._obs = obs
        return obs, rew, term, trunc

    def close(self) -> None:
        for env in self.envs:
            close = getattr(env, "close", None)
            if close:
                close()


def make_multi_agent_creator(env_creator: Callable[[], MultiAgentEnv],
                             seed: int = 0):
    """Adapter for AlgorithmConfig.environment(env_creator=...): the
    runner sees a batched-env factory whose `num_envs` means ENV INSTANCES
    x AGENTS columns."""

    def make(num_columns: int):
        proto = env_creator()
        n_agents = len(proto.possible_agents)
        close = getattr(proto, "close", None)
        if close:
            close()
        # Round UP: the runner sizes its buffers off the built env's
        # num_envs, and short-building would leave phantom columns.
        instances = max(1, -(-num_columns // n_agents))
        return MultiAgentBatchedEnv(env_creator, instances, seed=seed)

    make.makes_batched_env = True
    return make
