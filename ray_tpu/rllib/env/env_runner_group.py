"""EnvRunnerGroup: the sampling fleet.

Parity: reference rllib/env/env_runner_group.py + the
`synchronous_parallel_sample` train-op (ppo.py:435): N env-runner actors on
CPU hosts, weight sync before sampling, fault-tolerant fan-out via
FaultTolerantActorManager. num_runners=0 runs a local (in-driver) runner —
the debug/test path, like the reference's local worker.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ..utils.actor_manager import FaultTolerantActorManager
from ..utils.episodes import SingleAgentEpisode
from .env_runner import SingleAgentEnvRunner


class EnvRunnerGroup:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        module_factory: Callable[[], Any],
        *,
        num_runners: int = 0,
        num_envs_per_runner: int = 1,
        seed: int = 0,
        runner_resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 3,
        connector_factory: Optional[Callable[[], Any]] = None,
        action_connector_factory: Optional[Callable[[], Any]] = None,
        vectorize_mode: str = "sync",
    ):
        self.num_runners = num_runners
        if num_runners == 0:
            self._local = SingleAgentEnvRunner(
                env_creator, module_factory,
                num_envs=num_envs_per_runner, seed=seed, worker_index=0,
                connector_factory=connector_factory,
                action_connector_factory=action_connector_factory,
                vectorize_mode=vectorize_mode)
            self._manager = None
        else:
            self._local = None
            opts = dict(runner_resources or {"num_cpus": 1})
            cls = ray_tpu.remote(SingleAgentEnvRunner).options(**opts)

            def factory(i: int):
                return cls.remote(
                    env_creator, module_factory,
                    num_envs=num_envs_per_runner, seed=seed,
                    worker_index=i + 1,
                    connector_factory=connector_factory,
                    action_connector_factory=action_connector_factory,
                    vectorize_mode=vectorize_mode)

            self._manager = FaultTolerantActorManager(
                factory, num_runners, max_restarts=max_restarts)

    # -------------------------------------------------------------- sampling

    def sync_weights(self, weights: Any) -> None:
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            self._manager.foreach_actor("set_weights", weights)

    def sample_fragments(self, fragment_len: int) -> List[Dict[str, Any]]:
        """One fixed-length [T, N] fragment per healthy runner (the
        high-throughput path; utils/rollout.py)."""
        if self._local is not None:
            return [self._local.sample_fragment(fragment_len)]
        results = self._manager.foreach_actor("sample_fragment", fragment_len)
        self._manager.restore_unhealthy()
        return [frag for _, frag in results]

    def sample(self, total_timesteps: int) -> List[SingleAgentEpisode]:
        """Synchronous parallel sample of ~total_timesteps across runners."""
        if self._local is not None:
            return self._local.sample(total_timesteps)
        n = max(1, len(self._manager.healthy_actor_ids()))
        per = max(1, total_timesteps // n)
        results = self._manager.foreach_actor("sample", per)
        episodes: List[SingleAgentEpisode] = []
        for _, eps in results:
            episodes.extend(eps)
        # Heal for the next round; freshly restored runners get weights at
        # the next sync_weights call.
        self._manager.restore_unhealthy()
        return episodes

    def evaluate(self, num_episodes: int = 1) -> float:
        """Mean greedy-policy episode return."""
        if self._local is not None:
            rets = [self._local.sample_episode_greedy()
                    for _ in range(num_episodes)]
            return sum(rets) / len(rets)
        ids = self._manager.healthy_actor_ids()[:num_episodes] or []
        results = self._manager.foreach_actor(
            "sample_episode_greedy", actor_ids=ids)
        if not results:
            return float("nan")
        return sum(r for _, r in results) / len(results)

    def stop(self) -> None:
        if self._local is not None:
            self._local.stop()
        if self._manager is not None:
            self._manager.shutdown()
