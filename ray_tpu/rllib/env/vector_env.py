"""Batched environment layer for high-throughput sampling.

Parity: reference rllib/env/single_agent_env_runner.py:701 builds
gym.vector envs (sync or async/subprocess); the reference's 1M env-steps/s
IMPALA numbers rest on many vectorized envs per runner with ONE policy
forward per vector step. This module defines the batched-env protocol the
fragment sampler (env_runner.sample_fragment) consumes and three backends:

- GymVecEnv: gymnasium sync/async vector envs (NEXT_STEP autoreset — the
  step after a done returns the reset observation and ignores its action,
  which the sampler records as an invalid row).
- CnnRolloutBenchEnv: a pure-numpy Atari-shaped synthetic env whose whole
  batch steps in a few vector ops (SAME_STEP autoreset). It exists to
  measure the sampler+policy-inference ceiling without ALE in the image;
  it is NOT a real game (RL_PERF.json labels it as overhead probe).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import numpy as np


class BatchedEnv:
    """Protocol: step the WHOLE batch with arrays, no per-env Python.

    autoreset_mode:
    - "next_step": gymnasium semantics — done step returns the FINAL
      observation; the following step ignores its action and returns the
      reset observation (an invalid transition the sampler masks).
    - "same_step": done step returns the final reward/flags but the
      returned observation is already the reset observation of the next
      episode (no invalid rows; truncation bootstrap unavailable — only
      suitable for termination-only envs).
    """

    num_envs: int
    autoreset_mode: str = "next_step"
    single_observation_space: Any = None
    single_action_space: Any = None

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """-> (obs [N,...], rewards [N] f32, terminations [N] bool,
        truncations [N] bool)"""
        raise NotImplementedError

    def close(self) -> None:
        pass


class GymVecEnv(BatchedEnv):
    """gymnasium vector env adapter; mode="sync" (one process) or
    "async" (subprocess per env — reference's remote envs / envpool idea
    for CPU-heavy env steps)."""

    def __init__(self, env_creator: Callable[[], Any], num_envs: int,
                 mode: str = "sync"):
        import gymnasium as gym

        self.num_envs = num_envs
        if mode == "async":
            self.envs = gym.vector.AsyncVectorEnv(
                [env_creator for _ in range(num_envs)])
        elif mode == "sync":
            self.envs = gym.vector.SyncVectorEnv(
                [env_creator for _ in range(num_envs)])
        else:
            raise ValueError(
                f"unknown vectorize mode {mode!r} (want 'sync' or 'async')")
        self.autoreset_mode = "next_step"
        self.single_observation_space = self.envs.single_observation_space
        self.single_action_space = self.envs.single_action_space

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        obs, _ = self.envs.reset(seed=seed)
        return obs

    def step(self, actions):
        obs, rew, term, trunc, _ = self.envs.step(actions)
        return obs, np.asarray(rew, np.float32), term, trunc

    def close(self) -> None:
        self.envs.close()


class CnnRolloutBenchEnv(BatchedEnv):
    """Atari-shaped throughput probe: [84, 84, 4] uint8 observations drawn
    from a pre-generated bank, reward = f(action), geometric episode ends.
    The entire batch steps in O(3) numpy ops — what remains in the profile
    is the sampler's own overhead plus policy inference."""

    autoreset_mode = "same_step"

    def __init__(self, num_envs: int, obs_shape=(84, 84, 4),
                 num_actions: int = 6, mean_len: int = 1000, seed: int = 0):
        import gymnasium as gym

        self.num_envs = num_envs
        self.obs_shape = tuple(obs_shape)
        self._rng = np.random.default_rng(seed)
        # 64-frame bank; each env walks it at its own stride.
        self._bank = self._rng.integers(
            0, 255, (64, *self.obs_shape), dtype=np.uint8)
        self._pos = self._rng.integers(0, 64, num_envs)
        self._stride = 1 + self._rng.integers(0, 3, num_envs)
        self._p_done = 1.0 / float(mean_len)
        self.single_observation_space = gym.spaces.Box(
            0, 255, self.obs_shape, np.uint8)
        self.single_action_space = gym.spaces.Discrete(num_actions)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.integers(0, 64, self.num_envs)
        return self._bank[self._pos % 64]

    def step(self, actions):
        self._pos = self._pos + self._stride
        obs = self._bank[self._pos % 64]
        rew = (np.asarray(actions) % 3).astype(np.float32) * 0.1
        term = self._rng.random(self.num_envs) < self._p_done
        # SAME_STEP autoreset: obs is already the next episode's start for
        # done envs (the bank walk just continues).
        trunc = np.zeros(self.num_envs, bool)
        return obs, rew, term, trunc


class CartPoleBatchedEnv(BatchedEnv):
    """Vectorized CartPole-v1: the WHOLE batch integrates in ~6 numpy ops.

    Same dynamics, reward and termination thresholds as gymnasium's
    CartPole-v1 (Euler integration, tau=0.02, 500-step truncation) — but
    no per-env Python objects, so a single core steps hundreds of
    thousands of env-steps/s instead of ~10k. This is the envpool-style
    answer the reference reaches for at its 1M env-steps/s scale: the env
    batch is array state, policy inference is one batched forward, and
    nothing in the sampling loop is O(num_envs) Python.

    SAME_STEP autoreset: terminated/truncated columns return the reset
    observation immediately (CartPole is termination-heavy; the masked
    invalid rows of NEXT_STEP would waste ~1/200 of throughput)."""

    autoreset_mode = "same_step"

    GRAVITY, MASSCART, MASSPOLE = 9.8, 1.0, 0.1
    LENGTH, FORCE_MAG, TAU = 0.5, 10.0, 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, num_envs: int, seed: int = 0):
        import gymnasium as gym

        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float64)
        self._t = np.zeros(num_envs, np.int64)
        self.single_observation_space = gym.spaces.Box(
            -np.inf, np.inf, (4,), np.float32)
        self.single_action_space = gym.spaces.Discrete(2)

    def _reset_rows(self, rows: np.ndarray) -> None:
        n = int(rows.sum()) if rows.dtype == bool else len(rows)
        if n:
            self._state[rows] = self._rng.uniform(-0.05, 0.05, (n, 4))
            self._t[rows] = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._reset_rows(np.ones(self.num_envs, bool))
        return self._state.astype(np.float32)

    def step(self, actions):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(np.asarray(actions) == 1, self.FORCE_MAG,
                         -self.FORCE_MAG)
        cos, sin = np.cos(theta), np.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot**2 * sin) / total_mass
        theta_acc = (self.GRAVITY * sin - cos * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * cos**2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * cos / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._t += 1

        term = (np.abs(x) > self.X_LIMIT) | (np.abs(theta) > self.THETA_LIMIT)
        trunc = (self._t >= self.MAX_STEPS) & ~term
        rew = np.ones(self.num_envs, np.float32)
        done = term | trunc
        if done.any():
            self._reset_rows(done)  # SAME_STEP: fresh obs ride this return
        return self._state.astype(np.float32), rew, term, trunc
