"""SingleAgentEnvRunner: CPU sampling actor.

Parity: reference rllib/env/single_agent_env_runner.py:49 (`sample` :127,
gym.vector envs :701): owns a vectorized gymnasium env, steps it with the
current policy (jitted CPU forward — env runners never touch the TPU), and
returns completed/truncated episode chunks carrying logp and value
predictions for GAE/v-trace.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.episodes import SingleAgentEpisode


class SingleAgentEnvRunner:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        module_factory: Callable[[], Any],
        *,
        num_envs: int = 1,
        seed: int = 0,
        worker_index: int = 0,
        connector_factory: Optional[Callable[[], Any]] = None,
        action_connector_factory: Optional[Callable[[], Any]] = None,
        vectorize_mode: str = "sync",
        device: str = "cpu",
    ):
        from .vector_env import GymVecEnv

        # Sampling policy inference defaults to CPU (env runners on CPU
        # hosts never grab the accelerator); device="tpu" opts a runner
        # into batched device inference — one forward per vector step
        # across many envs (the reference's GPU-inference env runners).
        from ray_tpu.util.jaxenv import ensure_platform

        if device == "cpu":
            ensure_platform("cpu")
        import jax

        self._jax = jax
        if getattr(env_creator, "makes_batched_env", False):
            # The creator builds a whole BatchedEnv itself (vector_env.py
            # protocol) — e.g. the CNN rollout bench or an envpool-style
            # native vector env.
            self.batched = env_creator(num_envs)
            self.envs = None
            # A batched factory may round the column count (e.g. up to a
            # multiple of the agent count) — its word is final.
            num_envs = self.batched.num_envs
        else:
            self.batched = GymVecEnv(env_creator, num_envs,
                                     mode=vectorize_mode)
            self.envs = self.batched.envs  # legacy episode-based sampler
        self.num_envs = num_envs
        self.module = module_factory()
        self.params = None
        # env-to-module connector pipeline (reference ConnectorV2): runs on
        # the raw vector observations BEFORE the policy forward; episodes
        # record the transformed obs so the learner sees the same view.
        self._connector_factory = connector_factory
        self.connector = connector_factory() if connector_factory else None
        # module-to-env pipeline (reference module_to_env connectors):
        # transforms the MODULE's actions into env actions; recorded
        # buffers keep the module's view (the learner must see what the
        # policy actually emitted). Stateful ones reset on episode
        # boundaries like the obs pipeline.
        self._action_connector_factory = action_connector_factory
        self.action_connector = (action_connector_factory()
                                 if action_connector_factory else None)
        self._rng = jax.random.key(seed * 10_007 + worker_index)
        self._explore_fn = jax.jit(self.module.forward_exploration)
        self._value_fn = jax.jit(
            lambda p, o: self.module.forward(p, o)["vf"])
        seed_val = int(seed * 65_537 + worker_index)
        raw_obs = self.batched.reset(seed=seed_val)
        self._obs = self._connect(raw_obs)
        self._episodes = [SingleAgentEpisode() for _ in range(num_envs)]
        for i in range(num_envs):
            self._episodes[i].observations.append(self._obs[i].copy())
        # gymnasium >=1.0 vector envs autoreset on the step AFTER done
        # (AutoresetMode.NEXT_STEP): that step's action is ignored, so no
        # transition must be recorded for it.
        self._needs_reset = np.zeros(num_envs, dtype=bool)
        # Fragment-path state (sample_fragment): reusable buffers + running
        # per-env return accumulators, all vectorized.
        self._frag_buffers: Optional[Dict[str, np.ndarray]] = None
        self._ep_return = np.zeros(num_envs, np.float64)
        self._completed_returns: List[float] = []

    # ----------------------------------------------------------------- state

    def _connect(self, raw_obs):
        return self.connector(raw_obs) if self.connector is not None else raw_obs

    def _reset_pipelines(self, env_index: int) -> None:
        """Episode boundary: clear per-env state in BOTH pipelines."""
        if self.connector is not None:
            self.connector.reset(env_index)
        if self.action_connector is not None:
            self.action_connector.reset(env_index)

    def set_weights(self, weights) -> None:
        self.params = weights

    def ping(self) -> str:
        return "ok"

    # ---------------------------------------------------------------- sample

    def sample_fragment(self, num_steps: int) -> Dict[str, Any]:
        """Fixed-length rollout fragment: [T, N] arrays, zero per-env
        Python in the hot loop (reference single_agent_env_runner.py:127
        vector sampling; see utils/rollout.py for the layout).

        One policy forward per vector step over all N envs; env stepping
        and bookkeeping are whole-batch numpy ops. This is the
        high-throughput path PPO/IMPALA train from.
        """
        assert self.params is not None, "set_weights before sample"
        jax = self._jax
        T, N = num_steps, self.num_envs
        bufs = self._frag_buffers
        if bufs is None or bufs["actions"].shape[0] != T:
            obs_shape = self._obs.shape[1:]
            bufs = self._frag_buffers = {
                "obs": np.empty((T, N, *obs_shape), self._obs.dtype),
                "actions": np.empty((T, N), np.int64),
                "logp": np.empty((T, N), np.float32),
                "vf": np.empty((T, N), np.float32),
                "rewards": np.empty((T, N), np.float32),
                "dones": np.empty((T, N), bool),
                "truncs": np.empty((T, N), bool),
                "valid": np.empty((T, N), np.float32),
            }
        next_step_mode = self.batched.autoreset_mode == "next_step"
        # Multi-agent batched envs expose dead columns (agents done before
        # their instance's episode): their rows are masked like autoreset
        # rows (env/multi_agent_env.py).
        dead_fn = getattr(self.batched, "dead_mask", None)
        for t in range(T):
            self._rng, sub = jax.random.split(self._rng)
            actions, logp, vf = self._explore_fn(self.params, self._obs, sub)
            actions = np.asarray(actions)
            bufs["obs"][t] = self._obs
            bufs["actions"][t] = actions
            bufs["logp"][t] = logp
            bufs["vf"][t] = vf
            invalid = (self._needs_reset.copy() if next_step_mode
                       else np.zeros(N, bool))
            if dead_fn is not None:
                invalid |= dead_fn()
            bufs["valid"][t] = 1.0 - invalid.astype(np.float32)
            env_actions = (self.action_connector(actions)
                           if self.action_connector is not None else actions)
            raw_next, rewards, terms, truncs = self.batched.step(env_actions)
            bufs["rewards"][t] = rewards
            done = terms | truncs
            bufs["dones"][t] = done & ~invalid
            bufs["truncs"][t] = truncs & ~terms
            # Vectorized episode-return tracking (only completed episodes
            # surface; the loop below is over DONE envs only — rare).
            live = ~invalid
            self._ep_return += np.where(live, rewards, 0.0)
            finished = done & live
            if finished.any():
                self._completed_returns.extend(
                    self._ep_return[finished].tolist())
                self._ep_return[finished] = 0.0
            if next_step_mode:
                self._needs_reset = done
                # NEXT_STEP: raw_next at a done step is the FINAL obs —
                # connect it with the old stack (its value is the
                # truncation bootstrap), THEN reset; the reset state
                # applies to the reset obs arriving next step.
                self._obs = self._connect(raw_next)
                if finished.any():
                    for i in np.nonzero(finished)[0]:
                        self._reset_pipelines(int(i))
            else:
                # SAME_STEP: raw_next is already the new episode's start —
                # reset the connector before it passes through.
                if finished.any():
                    for i in np.nonzero(finished)[0]:
                        self._reset_pipelines(int(i))
                self._obs = self._connect(raw_next)
        bootstrap = np.asarray(self._value_fn(self.params, self._obs))
        returns, self._completed_returns = self._completed_returns, []
        return {
            **{k: v.copy() for k, v in bufs.items()},
            "bootstrap": bootstrap.astype(np.float32),
            "episode_returns": returns,
        }

    def sample(self, num_timesteps: int) -> List[SingleAgentEpisode]:
        """Step the vector env ~num_timesteps (per runner, across its envs);
        returns episode CHUNKS (done or truncated-by-horizon or cut at the
        end of the rollout, with bootstrap values for the cut ones)."""
        assert self.params is not None, "set_weights before sample"
        if self.envs is None:
            raise RuntimeError(
                "episode-based sample() requires a gym env; this runner "
                "wraps a native BatchedEnv — use sample_fragment()")
        jax = self._jax
        out: List[SingleAgentEpisode] = []
        steps = 0
        while steps < num_timesteps:
            self._rng, sub = jax.random.split(self._rng)
            actions, logp, vf = self._explore_fn(
                self.params, self._obs, sub)
            actions = np.asarray(actions)
            logp = np.asarray(logp)
            vf = np.asarray(vf)
            env_actions = (self.action_connector(actions)
                           if self.action_connector is not None else actions)
            raw_next, rewards, terms, truncs, _ = self.envs.step(env_actions)
            next_obs = self._connect(raw_next)
            vf_next: Optional[np.ndarray] = None  # lazy V(next_obs)
            for i in range(self.num_envs):
                if self._needs_reset[i]:
                    # Autoreset step: the env ignored our action and returned
                    # the reset observation — start the new episode here.
                    self._needs_reset[i] = False
                    fresh = SingleAgentEpisode()
                    fresh.observations.append(next_obs[i].copy())
                    self._episodes[i] = fresh
                    continue
                ep = self._episodes[i]
                ep.actions.append(actions[i])
                ep.rewards.append(float(rewards[i]))
                ep.logp.append(float(logp[i]))
                ep.vf_preds.append(float(vf[i]))
                steps += 1
                if terms[i] or truncs[i]:
                    ep.terminated = bool(terms[i])
                    ep.truncated = bool(truncs[i])
                    # NEXT_STEP autoreset: next_obs[i] IS the final obs.
                    ep.observations.append(next_obs[i].copy())
                    if truncs[i] and not terms[i]:
                        if vf_next is None:
                            vf_next = np.asarray(
                                self._value_fn(self.params, next_obs))
                        ep.bootstrap_value = float(vf_next[i])
                    out.append(ep)
                    self._episodes[i] = SingleAgentEpisode()
                    self._needs_reset[i] = True
                    # Stateful connectors (frame stacks) restart with the
                    # new episode.
                    self._reset_pipelines(i)
                else:
                    ep.observations.append(next_obs[i].copy())
            self._obs = next_obs
        # Cut the in-flight episodes: hand them out with a bootstrap value
        # and start fresh chunks that continue from the same env state.
        live_idx = [i for i in range(self.num_envs)
                    if len(self._episodes[i]) > 0]
        if live_idx:
            vf_last = np.asarray(self._value_fn(self.params, self._obs))
            for i in live_idx:
                ep = self._episodes[i]
                ep.bootstrap_value = float(vf_last[i])
                out.append(ep)
                cont = SingleAgentEpisode()
                cont.observations.append(self._obs[i].copy())
                self._episodes[i] = cont
        return out

    def sample_episode_greedy(self, max_steps: int = 10_000) -> float:
        """One full greedy-policy episode on a fresh env; returns its return
        (evaluation path, reference Algorithm.evaluate)."""
        import gymnasium as gym

        env = self.envs.env_fns[0]()
        jax = self._jax
        # Evaluation gets its own connector instances: sharing the sampling
        # pipelines' per-env state would corrupt in-flight frame stacks.
        conn = (self._connector_factory()
                if self._connector_factory is not None else None)
        act_conn = (self._action_connector_factory()
                    if self._action_connector_factory is not None else None)

        def trans(o):
            return conn(np.asarray(o)[None]) if conn is not None \
                else np.asarray(o)[None]

        obs, _ = env.reset()
        total = 0.0
        for _ in range(max_steps):
            action = self.module.forward_inference(self.params, trans(obs))
            act = np.asarray(action)
            if act_conn is not None:
                act = act_conn(act)
            obs, r, term, trunc, _ = env.step(int(act[0]))
            total += float(r)
            if term or trunc:
                break
        env.close()
        return total

    def stop(self) -> None:
        self.batched.close()
