"""Operator CLI: start/stop nodes, inspect state, submit jobs.

Parity: reference python/ray/scripts/scripts.py (`ray start --head`,
`ray start --address`, `ray stop`, `ray status`, `ray summary`, `ray
timeline`) + `ray job submit/status/logs/list/stop` (dashboard job CLI).

Usage (no console-script install needed):

    python -m ray_tpu.cli start --head [--port 6380] [--num-cpus N]
    python -m ray_tpu.cli start --address HOST:PORT [--num-cpus N]
    python -m ray_tpu.cli status  [--address HOST:PORT]
    python -m ray_tpu.cli summary [--address HOST:PORT]
    python -m ray_tpu.cli logs [NAME] [--task-id ID] [--follow|--tail N]
    python -m ray_tpu.cli timeline --out trace.json
    python -m ray_tpu.cli job submit -- python my_script.py
    python -m ray_tpu.cli job logs <job_id>
    python -m ray_tpu.cli stop
"""
from __future__ import annotations

from ray_tpu import flags

import argparse
import json
import os
import signal
import sys
import tempfile
import time

_PIDFILE = os.path.join(tempfile.gettempdir(), "rtpu_head.pid")
_ADDRFILE = os.path.join(tempfile.gettempdir(), "rtpu_head.addr")


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or flags.get("RTPU_ADDRESS")
    if not addr and os.path.exists(_ADDRFILE):
        addr = open(_ADDRFILE).read().strip()
    if not addr:
        sys.exit("no cluster address: pass --address, set RTPU_ADDRESS, or "
                 "start a head with `python -m ray_tpu.cli start --head`")
    return addr


def cmd_start(args) -> int:
    if args.head:
        import asyncio

        if getattr(args, "state_path", None):
            flags.set_env("RTPU_STATE_PATH", args.state_path)

        from ray_tpu.core.controller import Controller

        async def run_head():
            controller = Controller(port=args.port)
            host, port = await controller.start()
            from ray_tpu.util.accelerators import (
                detect_node_accelerator_resources,
            )

            res = {"CPU": float(args.num_cpus or os.cpu_count() or 1)}
            # Same vendor-agnostic autodetection as api.init(): accelerator
            # counts plus pod-scoped custom resources — a CLI-started head
            # must schedule identically to an init()-started one.
            res.update(detect_node_accelerator_resources())
            if args.resources:
                res.update(json.loads(args.resources))
            # ensure_head_node: a restart with --state-path reuses the
            # persisted head-node identity so surviving workers of the
            # previous controller can reconnect under their node id.
            controller.ensure_head_node(res, labels={"head": "1"})
            addr = f"{host}:{port}"
            with open(_ADDRFILE, "w") as f:
                f.write(addr)
            with open(_PIDFILE, "w") as f:
                f.write(str(os.getpid()))
            print(f"rtpu head started at {addr}")
            print(f"  connect with: ray_tpu.init(address={addr!r})")
            print(f"  metrics:      http://{host}:{controller.metrics_port}/metrics")
            stop = asyncio.Event()
            loop = asyncio.get_running_loop()
            for s in (signal.SIGTERM, signal.SIGINT):
                try:
                    loop.add_signal_handler(s, stop.set)
                except NotImplementedError:
                    pass
            await stop.wait()
            await controller.shutdown()
            # Only OUR files: a newer head may have overwritten them, and
            # removing its address would strand its clients (compare
            # content before unlink, reference `ray stop` semantics).
            for path, mine in ((_ADDRFILE, addr),
                               (_PIDFILE, str(os.getpid()))):
                try:
                    if open(path).read().strip() == mine:
                        os.unlink(path)
                except OSError:
                    pass

        asyncio.run(run_head())
        return 0
    # worker node: join an existing cluster as a host agent
    address = _resolve_address(args)
    from ray_tpu.core.host_agent import _amain

    class A:
        controller = address
        resources = json.dumps(
            {"CPU": float(args.num_cpus or os.cpu_count() or 1)})
        labels = ""
        host_id = ""
        port = 0

    import asyncio

    return asyncio.run(_amain(A()))


def cmd_stop(args) -> int:
    if not os.path.exists(_PIDFILE):
        print("no head pidfile; nothing to stop")
        return 0
    pid = int(open(_PIDFILE).read().strip() or 0)
    try:
        os.kill(pid, signal.SIGTERM)
        print(f"sent SIGTERM to head (pid {pid})")
    except ProcessLookupError:
        print("head already gone")
    for f in (_PIDFILE, _ADDRFILE):
        try:
            os.unlink(f)
        except OSError:
            pass
    return 0


def _connect(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args))
    return ray_tpu


def cmd_status(args) -> int:
    rt = _connect(args)
    from ray_tpu.core import context as ctx

    state = ctx.get_worker_context().client.request({"kind": "cluster_state"})
    # Per-node utilization table (reference: the `ray status` node
    # report): the controller already holds host CPU%/mem% from agent
    # heartbeats — surface them instead of burying them in the JSON.
    # Human output goes to stderr: stdout stays pure JSON so
    # `rtpu status | jq` keeps working.
    nodes = state.get("nodes") or []
    if nodes:
        print(f"{'NODE':14} {'STATE':10} {'CPU%':>6} {'MEM%':>6} "
              f"{'WORKERS':>8} {'STORE':>13} {'SPILL':>9}  RESOURCES",
              file=sys.stderr)
        for n in sorted(nodes, key=lambda n: n.get("index", 0)):
            st = n.get("state", "alive" if n.get("alive") else "dead")
            if st in ("draining", "drained") and n.get("drain_reason"):
                st = f"{st[:4]}:{n['drain_reason'][:5]}"
            # Object-store occupancy: arena used/capacity + spilled bytes
            # on disk (the census tiers, per node).
            arena = n.get("arena") or {}
            store = (f"{_fmt_bytes(arena.get('used', 0))}"
                     f"/{_fmt_bytes(arena.get('capacity', 0))}"
                     if arena.get("capacity") else "-")
            spill = n.get("spill") or {}
            spill_s = (_fmt_bytes(spill.get("bytes", 0))
                       if spill.get("bytes") else "-")
            print(f"{n['node_id'][:12]:14} {st:10} "
                  f"{n.get('cpu_percent') or 0.0:>6.1f} "
                  f"{(n.get('mem_fraction') or 0.0) * 100:>6.1f} "
                  f"{n.get('num_workers', 0):>8} {store:>13} "
                  f"{spill_s:>9}  "
                  f"{json.dumps(n.get('resources', {}))}", file=sys.stderr)
        print(file=sys.stderr)
    # Compiled DAGs with live channel plans: their steady-state dispatch
    # bypasses the controller, so this registry is the only place an
    # operator can see which pipelines hold resident actor loops.
    dags = state.get("compiled_dags") or {}
    if dags:
        print(f"{'COMPILED DAG':14} {'STAGES':>6} {'DEPTH':>6} "
              f"{'RECOV':>6}  EDGES", file=sys.stderr)
        for did, d in sorted(dags.items()):
            kinds = d.get("edges") or {}
            summary = ",".join(
                f"{eid}:{kind}" for eid, kind in sorted(kinds.items()))
            recov = str(d.get("recoveries", 0))
            if d.get("recovering"):
                recov += "*"  # a recovery is in flight right now
            print(f"{did[:12]:14} {d.get('stages', 0):>6} "
                  f"{d.get('depth', 0):>6} {recov:>6}  {summary}",
                  file=sys.stderr)
        print(file=sys.stderr)
    print(json.dumps(state, indent=1, default=str))
    # Quote recent hang/straggler findings: the watchdog's whole point is
    # that a silently hung step shows up where operators already look.
    try:
        from ray_tpu.util import state as state_api

        hangs = state_api.list_events(
            kind=["TASK_HUNG", "TASK_STRAGGLER"], limit=5)
        if hangs:
            print("\nrecent hang/straggler events "
                  "(`rtpu events --kind TASK_HUNG` for stacks):",
                  file=sys.stderr)
            for ev in hangs:
                print(f"  {_fmt_event(ev)}", file=sys.stderr)
    except Exception:
        pass
    rt.shutdown()
    return 0


def _fmt_event(ev, stacks: bool = False) -> str:
    """One human line per cluster event (the `rtpu events` row shape)."""
    t = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
    ids = " ".join(
        f"{k.split('_')[0]}={ev[k][:12]}"
        for k in ("task_id", "actor_id", "worker_id", "node_id")
        if ev.get(k))
    line = (f"[{t}] {ev.get('severity', 'INFO'):7} "
            f"{ev.get('kind', '?'):22} {ev.get('message', '')}"
            + (f"  ({ids})" if ids else ""))
    # DAG recoveries carry the structured cause (`rtpu events --kind
    # DAG_RECOVERED` answers "what killed it last time" directly).
    cause = (ev.get("data") or {}).get("cause")
    if cause:
        line += f"  cause={cause}"
    stack = (ev.get("data") or {}).get("stack")
    if stacks and stack:
        indented = "\n".join("    " + ln for ln in stack.splitlines())
        line += f"\n{indented}"
    return line


def cmd_events(args) -> int:
    """`rtpu events` (reference: `ray list cluster-events`): the cluster
    event feed — node/actor/task lifecycle, autoscaler decisions, and the
    hang watchdog's TASK_HUNG/TASK_STRAGGLER findings (--stacks prints
    their captured all-thread stacks). --follow streams new events."""
    rt = _connect(args)
    from ray_tpu.util import state

    sel = dict(severity=args.severity, kind=args.kind or None,
               task_id=args.task_id, actor_id=args.actor_id,
               node_id=args.node, worker_id=args.worker_id)
    # With an id filter the stacks are usually what you came for.
    stacks = args.stacks or bool(args.task_id or args.actor_id)
    try:
        if args.follow:
            try:
                for ev in state.follow_events(**sel):
                    print(_fmt_event(ev, stacks=stacks), flush=True)
            except KeyboardInterrupt:
                pass
            return 0
        since = time.time() - args.since if args.since else None
        events = state.list_events(**sel, since=since, limit=args.limit)
        for ev in events:
            print(_fmt_event(ev, stacks=stacks))
        if not events:
            print("no matching events")
        return 0
    finally:
        rt.shutdown()


def cmd_stack(args) -> int:
    """`rtpu stack` (reference: `ray stack`): on-demand all-thread stack
    dump from live workers, over the same profile_workers fan-out the
    dashboard and the hang watchdog use. Filter with --worker-id / --node
    (id prefixes)."""
    rt = _connect(args)
    from ray_tpu.util import state

    try:
        res = state.profile_workers(timeout=args.timeout)
        workers = res.get("workers", {})
        if args.node:
            rows = state.list_workers()
            on_node = {w["worker_id"] for w in rows
                       if (w.get("node_id") or "").startswith(args.node)}
            workers = {w: t for w, t in workers.items() if w in on_node}
        if args.worker_id:
            workers = {w: t for w, t in workers.items()
                       if w.startswith(args.worker_id)}
        for wid, text in sorted(workers.items()):
            print(f"=== worker {wid} ===")
            print(text)
        print(f"{len(workers)} worker(s) answered "
              f"({res.get('requested', 0)} asked; busy-in-native-code "
              f"workers miss the window)")
        return 0
    finally:
        rt.shutdown()


_SPARK_BARS = "▁▂▃▄▅▆▇█"


def _spark(vals) -> str:
    """Unicode sparkline over a value series (the `rtpu top` history
    cells)."""
    vals = list(vals)
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK_BARS[int((v - lo) / span * (len(_SPARK_BARS) - 1))]
        for v in vals)


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _top_frame(window: float = 120.0, spark_points: int = 30) -> str:
    """One `rtpu top` frame: cluster header, node table, per-label task
    rates + exec p99 with history sparklines, object-store bytes, firing
    alerts, event tail — all from cluster_state + the telemetry ring
    (query_metrics), zero external services."""
    from ray_tpu.core import context as ctx
    from ray_tpu.util import state as state_api

    cs = ctx.get_worker_context().client.request({"kind": "cluster_state"})
    since = time.time() - window
    lines = []
    nodes = cs.get("nodes") or []
    alive = sum(1 for n in nodes if n.get("alive"))
    lines.append(
        f"ray_tpu top — uptime {cs.get('uptime_s', 0):.0f}s · "
        f"nodes {alive}/{len(nodes)} alive · "
        f"workers {cs.get('num_workers', 0)} · "
        f"actors {len(cs.get('actors') or {})} · "
        f"pending {cs.get('pending_tasks', 0)}")
    try:
        firing = state_api.list_alerts().get("firing") or []
    except Exception:
        firing = []
    for a in firing:
        tags = ",".join(f"{k}={v}" for k, v in sorted(a["tags"].items()))
        lines.append(f"!! ALERT FIRING: {a['alert']}"
                     + (f" {{{tags}}}" if tags else "")
                     + f" value={a.get('value', 0):.4g}")
    lines.append("")
    lines.append(f"{'NODE':14} {'STATE':10} {'CPU%':>6} {'MEM%':>6} "
                 f"{'WORKERS':>8} {'TPU':>5}")
    for n in sorted(nodes, key=lambda n: n.get("index", 0)):
        st = n.get("state", "alive" if n.get("alive") else "dead")
        tpu = (n.get("resources") or {}).get("TPU", 0)
        lines.append(
            f"{n['node_id'][:12]:14} {st:10} "
            f"{n.get('cpu_percent') or 0.0:>6.1f} "
            f"{(n.get('mem_fraction') or 0.0) * 100:>6.1f} "
            f"{n.get('num_workers', 0):>8} {tpu:>5.0f}")

    def q(**kw):
        try:
            resp = state_api.query_metrics(since=since, **kw)
            return resp.get("series", []) if resp.get("enabled") else None
        except Exception:
            return None

    rate = q(name="rtpu_task_exec_s", stat="rate", window_s=30.0)
    p99 = q(name="rtpu_task_exec_s", stat="p99", window_s=window)
    if rate is None:
        lines.append("")
        lines.append("telemetry disabled (RTPU_TSDB=0) — task-rate and "
                     "history views need the controller TSDB")
    else:
        p99_by_tags = {tuple(sorted(s["tags"].items())): s for s in p99 or []}
        lines.append("")
        lines.append(f"{'TASK LABEL':24} {'RATE/S':>8} {'EXEC P99':>10}  "
                     f"HISTORY (rate, {window:.0f}s)")
        for ser in sorted(rate, key=lambda s: str(s["tags"])):
            label = ser["tags"].get("label", "?")
            pts = [v for _, v in ser["points"]]
            cur = pts[-1] if pts else 0.0
            pser = p99_by_tags.get(tuple(sorted(ser["tags"].items())))
            pv = (pser["points"][-1][1]
                  if pser and pser["points"] else 0.0)
            lines.append(f"{label[:24]:24} {cur:>8.1f} {pv:>9.4f}s  "
                         f"{_spark(pts[-spark_points:])}")
        if not rate:
            lines.append("  (no task history yet)")
        arena = q(name="rtpu_arena_used_bytes") or []
        for ser in arena:
            pts = [v for _, v in ser["points"]]
            if pts:
                lines.append("")
                lines.append(
                    f"object store  used {_fmt_bytes(pts[-1]):>10}  "
                    f"{_spark(pts[-spark_points:])}")
    # Serve plane: per-deployment pools with the controller's polled
    # signals (queue depth, occupancy) + telemetry TTFT/token rates.
    try:
        import ray_tpu as _rt

        _ctrl = _rt.get_actor("SERVE_CONTROLLER")
        sstats = _rt.get(_ctrl.get_serve_stats.remote(), timeout=2.0)
    except Exception:
        sstats = None
    if sstats:
        ttft = {s["tags"].get("model"): s["points"][-1][1]
                for s in (q(name="rtpu_serve_ttft_s", stat="p99",
                            window_s=60.0) or []) if s["points"]}
        toks = {s["tags"].get("model"): s["points"][-1][1]
                for s in (q(name="rtpu_serve_decode_tokens_total") or [])
                if s["points"]}
        itl = {s["tags"].get("model"): s["points"][-1][1]
               for s in (q(name="rtpu_serve_itl_s", stat="p99",
                           window_s=60.0) or []) if s["points"]}
        # SLO miss rate = misses/s over finished-requests/s (both rate
        # stats over the same window), per deployment.
        reqr = {}
        for s in (q(name="rtpu_serve_requests_total", stat="rate",
                    window_s=60.0) or []):
            if s["points"]:
                dep = s["tags"].get("deployment")
                reqr[dep] = reqr.get(dep, 0.0) + s["points"][-1][1]
        missr = {s["tags"].get("deployment"): s["points"][-1][1]
                 for s in (q(name="rtpu_serve_slo_miss_total",
                             stat="rate", window_s=60.0) or [])
                 if s["points"]}
        lines.append("")
        lines.append(f"{'SERVE DEPLOYMENT':22} {'POOL':8} {'REPL':>5} "
                     f"{'DRAIN':>6} {'QUEUE':>6} {'OCC%':>6} "
                     f"{'TTFT P99':>9} {'ITL P99':>9} {'TOK/S':>7} "
                     f"{'SLO-MISS%':>9}")
        for dname in sorted(sstats):
            d = sstats[dname]
            base = dname.split("-")[0]
            tv = ttft.get(dname, ttft.get(base))
            kv = toks.get(dname, toks.get(base))
            iv = itl.get(dname, itl.get(base))
            rr = reqr.get(dname, reqr.get(base))
            mr = missr.get(dname, missr.get(base, 0.0))
            miss_pct = (min(100.0, mr / rr * 100.0)
                        if rr else (100.0 if mr else None))
            repl = f"{d.get('replicas', 0)}/{d.get('target', 0)}"
            lines.append(
                f"{dname[:22]:22} {str(d.get('pool', 'main'))[:8]:8} "
                f"{repl:>5} {d.get('draining', 0):>6} "
                f"{d.get('queue_depth', 0.0):>6.0f} "
                f"{d.get('occupancy', 0.0) * 100:>6.1f} "
                + (f"{tv:>8.3f}s" if tv is not None else f"{'-':>9}")
                + (f" {iv * 1e3:>6.1f}ms" if iv is not None
                   else f" {'-':>9}")
                + (f" {kv:>7.1f}" if kv is not None else f" {'-':>7}")
                + (f" {miss_pct:>9.1f}" if miss_pct is not None
                   else f" {'-':>9}"))
    # Data plane: per-operator throughput from the streaming executor's
    # live rtpu_data_operator_* families (Dataset.stats() is the
    # per-run report; this is the cluster-wide cumulative view).
    dblocks = q(name="rtpu_data_operator_blocks_total") or []
    if dblocks:
        def _last_by(name, **want):
            out = {}
            for s2 in q(name=name) or []:
                tg = s2["tags"]
                if s2["points"] and all(tg.get(k) == v
                                        for k, v in want.items()):
                    out[tg.get("operator", "?")] = s2["points"][-1][1]
            return out

        wall = _last_by("rtpu_data_operator_seconds_total", phase="wall")
        udf = _last_by("rtpu_data_operator_seconds_total", phase="udf")
        bp = _last_by("rtpu_data_operator_seconds_total",
                      phase="backpressure")
        byt = _last_by("rtpu_data_operator_bytes_total", dir="out")
        rws = _last_by("rtpu_data_operator_rows_total", dir="out")
        lines.append("")
        lines.append(f"{'DATA OPERATOR':24} {'BLOCKS':>8} "
                     f"{'ROWS OUT':>10} {'BYTES OUT':>10} {'WALL':>8} "
                     f"{'UDF':>8} {'BP WAIT':>8}")
        for ser in sorted(dblocks, key=lambda s: str(s["tags"])):
            op = ser["tags"].get("operator", "?")
            pts = [v for _, v in ser["points"]]
            lines.append(
                f"{op[:24]:24} {pts[-1] if pts else 0:>8.0f} "
                f"{rws.get(op, 0):>10.0f} "
                f"{_fmt_bytes(byt.get(op, 0)):>10} "
                f"{wall.get(op, 0):>7.1f}s {udf.get(op, 0):>7.1f}s "
                f"{bp.get(op, 0):>7.1f}s")
    # Channel plane: compiled DAGs whose steady-state dispatch bypasses
    # the controller entirely — steps/s, recovery state and the
    # bottleneck verdict come from the channel meter's rollup
    # (`rtpu dag stats` has the full stages×edges view).
    try:
        dag_rows = ctx.get_worker_context().client.request(
            {"kind": "list_state", "what": "dags", "limit": 100})
    except Exception:
        dag_rows = []
    if dag_rows:
        lines.append("")
        lines.append(f"{'COMPILED DAG':14} {'STAGES':>6} {'DEPTH':>6} "
                     f"{'STEPS/S':>8} {'RECOV':>6}  BOTTLENECK")
        for d in sorted(dag_rows, key=lambda d: d["dag_id"]):
            methods = {f"s{s.get('idx')}": s.get("method", "")
                       for s in d.get("stages") or ()}
            bn = d.get("bottleneck")
            verdict = (f"{bn} {methods.get(bn, '')}".strip()
                       if bn else "-")
            recov = str(d.get("recoveries", 0))
            if d.get("recovering"):
                recov += "*"
                verdict = "(recovering)"
            sps = d.get("steps_per_s")
            lines.append(
                f"{d['dag_id'][:12]:14} "
                f"{len(d.get('stages') or ()):>6} "
                f"{d.get('depth', 0):>6} "
                + (f"{sps:>8.1f}" if sps is not None else f"{'-':>8}")
                + f" {recov:>6}  {verdict}")
    lines.append("")
    try:
        events = state_api.list_events(limit=6)
    except Exception:
        events = []
    lines.append("EVENTS")
    for ev in events[-6:]:
        lines.append("  " + _fmt_event(ev))
    if not events:
        lines.append("  (none)")
    return "\n".join(lines)


def _bar(frac, width: int = 10) -> str:
    """Fixed-width busy bar for the `rtpu dag stats` phase cells."""
    frac = max(0.0, min(1.0, float(frac or 0.0)))
    n = int(round(frac * width))
    return "█" * n + "·" * (width - n)


def _render_dag_stats(rows, state_api) -> str:
    """One `rtpu dag stats` frame: per compiled DAG, a stages×phases busy
    table (recv=starved / compute / send bars from the channel meter's
    busy-fraction gauges), a per-edge ring table (items/bytes/occupancy/
    lag/writer-blocked), and THE bottleneck verdict
    (dag.meter.attribute_bottleneck, computed controller-side)."""
    if not rows:
        return ("no compiled DAGs registered "
                "(compile a pipeline with ray_tpu.dag.compile first)")
    # Per-stage steps/s from the telemetry ring; one query covers every
    # DAG (tags carry dag+stage).
    stage_rate = {}
    try:
        resp = state_api.query_metrics(name="rtpu_dag_stage_steps_total")
        for ser in (resp.get("series") or ()) if resp.get("enabled") else ():
            pts = ser.get("points") or ()
            if pts:
                tg = ser["tags"]
                stage_rate[(tg.get("dag"), tg.get("stage"))] = pts[-1][1]
    except Exception:
        pass
    lines = []
    for d in rows:
        short = d["dag_id"][:12]
        busy = d.get("stage_busy") or {}
        edges = d.get("edge_stats") or {}
        bn = d.get("bottleneck")
        methods = {f"s{s.get('idx')}": s.get("method", "")
                   for s in d.get("stages") or ()}
        recov = str(d.get("recoveries", 0))
        if d.get("recovering"):
            recov += "*"
        sps = d.get("steps_per_s")
        lines.append(
            f"DAG {short}  stages {len(d.get('stages') or ())}  "
            f"depth {d.get('depth', 0)}  recoveries {recov}  "
            + (f"steps/s {sps:.1f}" if sps is not None else "steps/s -"))
        if bn is not None:
            b = busy.get(bn) or {}
            score = b.get("compute", 0.0) + b.get("send", 0.0)
            lines.append(
                f"  bottleneck: {bn} {methods.get(bn, '')} "
                f"(compute+send {score * 100:.0f}% of wall — this stage "
                f"bounds throughput; starved stages are its victims)")
        else:
            lines.append(
                "  (no meter samples yet — RTPU_DAG_METER=0, or the "
                "pipeline has not stepped since the last metrics flush)")
        if busy:
            lines.append(f"  {'STAGE':6} {'METHOD':16} {'STEPS/S':>8}  "
                         f"{'RECV(STARVED)':16} {'COMPUTE':16} "
                         f"{'SEND':16}")
            for stage in sorted(busy):
                ph = busy[stage]
                r = stage_rate.get((short, stage))
                cells = " ".join(
                    f"{_bar(ph.get(p, 0.0))} {ph.get(p, 0.0) * 100:>3.0f}%"
                    for p in ("recv", "compute", "send"))
                mark = "  << bottleneck" if stage == bn else ""
                lines.append(
                    f"  {stage:6} {methods.get(stage, '?')[:16]:16} "
                    + (f"{r:>8.1f}" if r is not None else f"{'-':>8}")
                    + f"  {cells}{mark}")
        if edges:
            kinds = d.get("edges") or {}
            lines.append(f"  {'EDGE':6} {'KIND':7} {'ITEMS':>10} "
                         f"{'BYTES':>10} {'OCC':>5} {'LAG':>5}  "
                         f"WRITER-BLOCKED")
            for eid in sorted(edges):
                e = edges[eid]
                bf = e.get("blocked_fraction", 0.0)
                lines.append(
                    f"  {eid:6} {str(kinds.get(eid, '?'))[:7]:7} "
                    f"{e.get('items', 0):>10.0f} "
                    f"{_fmt_bytes(e.get('bytes', 0)):>10} "
                    f"{e.get('occupancy', 0):>5.0f} "
                    f"{e.get('lag', 0):>5.0f}  {_bar(bf)} {bf * 100:.0f}%")
        lines.append("")
    return "\n".join(lines).rstrip()


def cmd_dag(args) -> int:
    """`rtpu dag stats [DAG] [--watch]` / `rtpu dag timeline`: the
    channel-meter consumers. Stats renders the stages×edges busy view
    with the bottleneck verdict; timeline writes the per-step chrome
    trace (state.dag_timeline) for chrome://tracing / Perfetto."""
    rt = _connect(args)
    from ray_tpu.util import state as state_api

    try:
        if args.dag_cmd == "timeline":
            state_api.dag_timeline(args.out, dag=args.dag)
            print(f"wrote {args.out} (open in chrome://tracing or "
                  f"ui.perfetto.dev)")
            return 0

        def frame() -> str:
            rows = state_api.list_compiled_dags()
            if args.dag:
                rows = [r for r in rows
                        if r["dag_id"].startswith(args.dag)]
                if not rows:
                    return f"no compiled DAG matches {args.dag!r}"
            return _render_dag_stats(rows, state_api)

        if args.watch:
            while True:
                sys.stdout.write("\x1b[2J\x1b[H" + frame() + "\n")
                sys.stdout.flush()
                time.sleep(args.interval)
        print(frame())
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        rt.shutdown()


def cmd_top(args) -> int:
    """`rtpu top` (reference: the dashboard's live cluster view / `htop`
    for the cluster): a refreshing terminal view of nodes, per-label task
    rates + exec p99 with sparkline history, object-store bytes, firing
    alerts, and the event tail — served entirely from the controller's
    in-process telemetry ring."""
    rt = _connect(args)
    try:
        if args.once:
            print(_top_frame(window=args.window))
            return 0
        while True:
            frame = _top_frame(window=args.window)
            # Clear + home; one write so the frame never tears.
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        rt.shutdown()


def cmd_profile(args) -> int:
    """`rtpu profile` (reference: py-spy flamegraphs via the dashboard /
    `ray stack --native`): sample wall-clock stacks across the targeted
    workers for --duration seconds, merge into one cluster-wide profile,
    write a self-contained flamegraph HTML."""
    rt = _connect(args)
    from ray_tpu.util import state

    try:
        res = state.profile(
            duration=args.duration, task_id=args.task_id,
            actor_id=args.actor_id, node_id=args.node,
            worker_id=args.worker_id, hz=args.hz)
        if res.get("error"):
            print(f"profile failed: {res['error']}", file=sys.stderr)
            return 1
        stacks = res.get("stacks") or {}
        from ray_tpu.core import profiler

        meta = (f"{res.get('samples', 0)} samples over "
                f"{res.get('duration', 0):.1f}s at {res.get('hz', 0):.0f}Hz "
                f"from {len(res.get('workers') or {})} worker(s)")
        profiler.save_flamegraph(args.out, stacks,
                                 title="rtpu cluster profile", meta=meta)
        if args.collapsed_out:
            with open(args.collapsed_out, "w") as f:
                f.write(profiler.to_collapsed_text(stacks))
        print(f"{meta} -> {args.out}", file=sys.stderr)
        # The terminal gets the hot leaves (self-heavy stacks), the HTML
        # the full picture.
        top = sorted(stacks.items(), key=lambda kv: -kv[1])[:5]
        for key, n in top:
            leaf = key.rsplit(";", 1)[-1]
            print(f"  {n:>6}  {leaf}", file=sys.stderr)
        return 0
    finally:
        rt.shutdown()


def cmd_summary(args) -> int:
    rt = _connect(args)
    from ray_tpu.util import state

    if getattr(args, "breakdown", False):
        rows = state.summarize_tasks(breakdown=True)
        if not rows:
            print("no phase events recorded yet "
                  "(is RTPU_TASK_EVENTS enabled?)")
        else:
            print(f"{'LABEL':28} {'PHASE':20} {'COUNT':>7} "
                  f"{'MEAN_MS':>9} {'P50_MS':>9} {'P99_MS':>9}")
            for label in sorted(rows):
                for phase, st in rows[label].items():
                    print(f"{label[:28]:28} {phase:20} {st['count']:>7} "
                          f"{st['mean'] * 1e3:>9.2f} "
                          f"{st['p50'] * 1e3:>9.2f} "
                          f"{st['p99'] * 1e3:>9.2f}")
    else:
        print(json.dumps(state.summarize_tasks(), indent=1))
    rt.shutdown()
    return 0


def cmd_drain(args) -> int:
    """`rtpu drain NODE` (reference: `ray drain-node`): graceful node
    departure — stop scheduling, migrate actors with state, give running
    tasks the deadline, re-replicate sole-copy objects, then release the
    node. NODE may be a unique node-id prefix from `rtpu status`."""
    rt = _connect(args)
    from ray_tpu.util import state

    try:
        res = state.drain_node(args.node, reason=args.reason,
                               deadline_s=args.deadline)
        if not res.get("ok"):
            print(f"drain failed: {res.get('error', 'unknown error')}")
            return 1
        print(f"node {res['node_id']} -> {res['state']} "
              f"(reason={args.reason})")
        if args.wait:
            deadline = time.monotonic() + args.wait
            from ray_tpu.core import context as ctx

            while time.monotonic() < deadline:
                nodes = ctx.get_worker_context().client.request(
                    {"kind": "cluster_state"})["nodes"]
                row = next((n for n in nodes
                            if n["node_id"] == res["node_id"]), None)
                if row is None or row.get("state") in ("drained", "dead"):
                    print(f"node {res['node_id']} drained")
                    return 0
                time.sleep(0.3)
            print("drain still in progress (deadline not reached)")
        return 0
    finally:
        rt.shutdown()


def cmd_memory(args) -> int:
    """Cluster object census (reference: `ray memory` /
    `ray summary objects`): the object directory joined with every live
    process's ownership shard, grouped by owner/tier/node/callsite with a
    per-tier byte breakdown inside each group. Dead shards are reported
    as error lines; survivors' totals still aggregate."""
    rt = _connect(args)
    from ray_tpu.util import state as state_api

    s = state_api.summarize_objects(min_size=args.min_size,
                                    limit=args.limit)
    if not s.get("enabled", True):
        for err in s.get("errors") or ():
            print(err, file=sys.stderr)
        rt.shutdown()
        return 1
    print(f"objects: {s['num_objects']}  "
          f"total: {_fmt_bytes(s['total_bytes'])}  "
          f"shards: {s.get('shards', 0)}/{s.get('requested', 0)}")
    for err in s.get("errors") or ():
        print(f"shard error: {err}", file=sys.stderr)
    # Ground truth next to attribution: census bytes vs what the arenas
    # and spill dirs actually hold — a big gap means unattributed memory.
    for nid, st in sorted((s.get("arenas") or {}).items()):
        used, cap = st.get("used", 0), st.get("capacity", 0)
        print(f"arena {nid[:8]}: {_fmt_bytes(used)}/{_fmt_bytes(cap)} "
              f"({st.get('objects', 0)} objects)")
    for nid, st in sorted((s.get("spill") or {}).items()):
        if st and st.get("bytes"):
            print(f"spill {nid[:8]}: {_fmt_bytes(st['bytes'])} "
                  f"({st.get('files', 0)} files)")
    groups = (s.get("groups") or {}).get(args.group_by) or {}
    if groups:
        print()
        print(f"{args.group_by.upper():28} {'BYTES':>12} {'COUNT':>7}  "
              f"TIERS")
        for key, g in sorted(groups.items(),
                             key=lambda kv: -kv[1]["bytes"]):
            tiers = " ".join(
                f"{t}={_fmt_bytes(b)}"
                for t, b in sorted(g["tiers"].items(),
                                   key=lambda kv: -kv[1]))
            print(f"{str(key)[:28]:28} {_fmt_bytes(g['bytes']):>12} "
                  f"{g['count']:>7}  {tiers}")
    rows = s.get("objects") or []  # server-ranked largest-first
    if rows:
        print()
        print(f"{'OBJECT':34} {'SIZE':>10} {'TIER':8} {'NODE':10} "
              f"{'OWNER':16} {'AGE':>7}  CALLSITE")
        for o in rows:
            cs = o.get("callsite") or ""
            print(f"{o['object_id'][:32]:34} "
                  f"{_fmt_bytes(o['size']):>10} "
                  f"{(o.get('tier') or '?'):8} "
                  f"{(o.get('node_id') or '')[:8]:10} "
                  f"{(o.get('owner') or '?')[:16]:16} "
                  f"{o.get('age_s', 0):>6.0f}s  {cs[-40:]}")
    rt.shutdown()
    return 0


def cmd_logs(args) -> int:
    """`rtpu logs` (reference: the `ray logs` CLI + dashboard log API):
    list worker log files cluster-wide, fetch one file (or one task's /
    actor's attributed output) from whichever node holds it, or --follow
    a live stream of new lines."""
    rt = _connect(args)
    from ray_tpu.util import state

    try:
        sel = {"name": args.name, "node_id": args.node,
               "task_id": args.task_id, "actor_id": args.actor_id,
               "worker_id": args.worker_id}
        if not any(sel.values()):
            listing = state.list_logs()
            for nid in sorted(listing):
                print(f"node {nid}")
                for f in listing[nid]:
                    print(f"  {f['name']:<32} {f['size']:>12} bytes")
            return 0
        if args.follow:
            try:
                for chunk in state.follow_log(**sel):
                    sys.stdout.write(chunk)
                    sys.stdout.flush()
            except KeyboardInterrupt:
                pass
            return 0
        text = state.get_log_text(**sel, tail_lines=args.tail)
        sys.stdout.write(text)
        if text and not text.endswith("\n"):
            sys.stdout.write("\n")
        return 0
    finally:
        rt.shutdown()


def cmd_serve(args) -> int:
    """`rtpu serve run|status|shutdown` (reference: the `serve` CLI,
    python/ray/serve/scripts.py — run imports `module:app`, deploys it
    with the HTTP proxy, and blocks)."""
    rt = _connect(args)
    from ray_tpu import serve

    try:
        if args.serve_cmd == "run":
            import importlib

            mod_name, _, attr = args.target.partition(":")
            sys.path.insert(0, os.getcwd())
            app = getattr(importlib.import_module(mod_name), attr or "app")
            print(f"serving {args.target} on :{args.port} (ctrl-c to stop)")
            serve.run(app, _http=True, http_port=args.port, blocking=True)
            return 0
        if args.serve_cmd == "status":
            st = serve.status()
            if st is None:
                print("serve is not running")
            elif not st:
                print("serve is running with no deployments")
            else:
                print(json.dumps(st, indent=1, default=str))
            return 0
        if args.serve_cmd == "shutdown":
            serve.shutdown()
            print("serve shut down")
            return 0
        if args.serve_cmd == "requests":
            from ray_tpu.util import state

            since = (time.time() - args.since_s) if args.since_s else None
            rows = state.list_serve_requests(
                model=args.model, status=args.status,
                min_latency_s=args.min_latency_s, since=since,
                limit=args.limit)
            if not rows:
                print("no matching requests in the ledger")
                return 0
            print(f"{'REQUEST':18} {'DEPLOYMENT':16} {'PROTO':6} "
                  f"{'STATUS':9} {'WALL':>9} {'TOKENS':>6} "
                  f"{'ITL P99':>9} {'SLO':>4}  ERROR")
            for r in rows:
                wall = r.get("wall_s")
                itl = r.get("itl_p99_s")
                print(
                    f"{r['request_id'][:18]:18} "
                    f"{(r.get('deployment') or '-')[:16]:16} "
                    f"{(r.get('proto') or '-')[:6]:6} "
                    f"{(r.get('status') or '?')[:9]:9} "
                    + (f"{wall * 1e3:>8.1f}m" if wall is not None
                       else f"{'-':>9}")
                    + f" {r.get('tokens', '-'):>6}"
                    + (f" {itl * 1e3:>8.2f}m" if itl is not None
                       else f" {'-':>9}")
                    + f" {'MISS' if r.get('slo_miss') else '-':>4}"
                    + f"  {(r.get('error') or '')[:40]}")
            return 0
        if args.serve_cmd == "trace":
            from ray_tpu.util import state

            row = state.serve_trace(args.request_id)
            wall = row.get("wall_s")
            print(f"request {row['request_id']}  "
                  f"trace {row.get('trace_id') or '?'}")
            print(f"  deployment={row.get('deployment') or '-'} "
                  f"proto={row.get('proto') or '-'} "
                  f"method={row.get('method') or '-'} "
                  f"status={row.get('status')}"
                  + (f" wall={wall * 1e3:.1f}ms" if wall is not None
                     else "")
                  + (" SLO-MISS" if row.get("slo_miss") else ""))
            if row.get("tokens") is not None:
                itl50, itl99 = row.get("itl_p50_s"), row.get("itl_p99_s")
                print(f"  tokens={row['tokens']}"
                      + (f" ttft={row['ttft_s'] * 1e3:.1f}ms"
                         if row.get("ttft_s") is not None else "")
                      + (f" itl p50/p99={itl50 * 1e3:.2f}/"
                         f"{itl99 * 1e3:.2f}ms"
                         if itl50 is not None and itl99 is not None
                         else "")
                      + (f" abort={row['abort_cause']}"
                         if row.get("abort_cause") else ""))
            if row.get("error"):
                print(f"  error: {row['error']}")
            wf = row.get("waterfall") or []
            if not wf:
                print("  (no hop spans shipped yet — replicas flush on "
                      "the task-events cadence)")
                return 0
            t0 = min(e["start_ts"] for e in wf if e.get("start_ts"))
            print()
            print(f"{'HOP':44} {'START':>9} {'DWELL':>10} {'SELF':>10}"
                  f"  DETAIL")
            attributed = 0.0
            for e in wf:
                attributed += e["self_s"]
                a = e.get("attributes") or {}
                detail = " ".join(
                    f"{k}={a[k]}" for k in sorted(a)
                    if k not in ("stack",))[:48]
                nm = ("  " * e["depth"] + e["name"])[:44]
                off = ((e["start_ts"] - t0) * 1e3
                       if e.get("start_ts") else 0.0)
                print(f"{nm:44} {off:>7.1f}ms "
                      f"{e['dwell_s'] * 1e3:>8.2f}ms "
                      f"{e['self_s'] * 1e3:>8.2f}ms  {detail}")
            line = (f"hop dwell (self) total {attributed * 1e3:.2f}ms")
            if wall is not None:
                line += (f" of {wall * 1e3:.2f}ms wall "
                         f"({attributed / wall * 100:.1f}% attributed)"
                         if wall > 0 else "")
            print()
            print(line)
            return 0
        raise SystemExit(f"unknown serve subcommand {args.serve_cmd!r}")
    finally:
        if args.serve_cmd != "run":
            rt.shutdown()


def cmd_timeline(args) -> int:
    rt = _connect(args)
    from ray_tpu.util import state

    state.timeline(args.out)
    print(f"wrote {args.out} (open in chrome://tracing or ui.perfetto.dev)")
    rt.shutdown()
    return 0


def cmd_dashboard(args) -> int:
    """Serve the web dashboard against a running cluster (reference:
    the dashboard head process, dashboard/head.py)."""
    import time

    import ray_tpu
    from ray_tpu.dashboard import start_dashboard

    ray_tpu.init(address=_resolve_address(args))
    if getattr(args, "grafana_out", None):
        # Generate importable Grafana JSON from the live metric surface
        # and exit (reference: grafana_dashboard_factory.py).
        import urllib.request

        from ray_tpu.util import state as state_api
        from ray_tpu.util.grafana import write_dashboard

        addr = state_api.metrics_address()
        if not addr:
            sys.exit("controller metrics endpoint is disabled")
        with urllib.request.urlopen(f"http://{addr}/metrics",
                                    timeout=5) as resp:
            prom_text = resp.read().decode()
        dash = write_dashboard(args.grafana_out, prom_text)
        print(f"wrote {len(dash['panels'])} panels to {args.grafana_out}")
        ray_tpu.shutdown()
        return 0
    dash = start_dashboard(host=args.host, port=args.dash_port)
    print(f"dashboard at http://{args.host}:{dash.port}")
    print(f"  task timeline: http://{args.host}:{dash.port}/timeline")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dash.stop()
    return 0


def cmd_job(args) -> int:
    rt = _connect(args)
    from ray_tpu.jobs import JobSubmissionClient

    client = JobSubmissionClient()
    if args.job_cmd == "submit":
        entrypoint = " ".join(args.entrypoint)
        renv = {}
        if args.working_dir:
            renv["working_dir"] = args.working_dir
        job_id = client.submit_job(entrypoint=entrypoint,
                                   runtime_env=renv or None,
                                   max_attempts=args.max_attempts)
        print(job_id)
        if args.wait:
            status = client.wait_until_finished(job_id, timeout=args.timeout)
            print(client.get_job_logs(job_id), end="")
            print(f"job {job_id}: {status}")
            rt.shutdown()
            return 0 if status == "SUCCEEDED" else 1
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        if getattr(args, "follow", False):
            # Durable follow: the stream rides the controller's job-log
            # walker, so it rolls across supervisor failovers and keeps
            # tailing the replacement attempt mid-flight.
            try:
                for chunk in client.tail_job_logs(args.job_id,
                                                  follow=True):
                    print(chunk, end="", flush=True)
            except KeyboardInterrupt:
                pass
        else:
            print(client.get_job_logs(args.job_id), end="")
    elif args.job_cmd == "stop":
        client.stop_job(args.job_id)
        print("stopped")
    elif args.job_cmd == "list":
        for d in client.list_jobs():
            attempts = (f"{d.attempts_used}/{d.max_attempts}"
                        if d.max_attempts else "-")
            rc = "-" if d.returncode is None else str(d.returncode)
            print(f"{d.job_id}\t{d.status}\tattempts={attempts}\t"
                  f"rc={rc}\t{d.entrypoint}")
    rt.shutdown()
    return 0


def cmd_up(args) -> int:
    from ray_tpu.launcher import ClusterConfig, ClusterLauncher

    cfg = ClusterConfig.load(args.config)
    state = ClusterLauncher(cfg).up()
    print(f"cluster {cfg.cluster_name!r} is up at {state['address']} "
          f"({1 + len(state['workers'])} nodes)")
    print(f"  attach: python -m ray_tpu.cli attach {args.config}")
    print(f"  tear down: python -m ray_tpu.cli down {args.config}")
    return 0


def cmd_down(args) -> int:
    from ray_tpu.launcher import ClusterConfig, ClusterLauncher

    cfg = ClusterConfig.load(args.config)
    ClusterLauncher(cfg).down()
    print(f"cluster {cfg.cluster_name!r} is down")
    return 0


def cmd_exec(args) -> int:
    import shlex

    from ray_tpu.launcher import ClusterConfig, ClusterLauncher

    cfg = ClusterConfig.load(args.config)
    # shlex.join: the remote shell re-parses the string — plain " ".join
    # would destroy the operator's quoting (`-c 'print("a b")'`).
    out = ClusterLauncher(cfg).exec(shlex.join(args.command),
                                    timeout=args.timeout)
    sys.stdout.write(out)
    return 0


def cmd_attach(args) -> int:
    from ray_tpu.launcher import ClusterConfig, ClusterLauncher

    cfg = ClusterConfig.load(args.config)
    cmd = ClusterLauncher(cfg).attach_command()
    os.execvp(cmd[0], cmd)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rtpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("config")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down a launched cluster")
    p.add_argument("config")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("exec", help="run a command on the cluster head")
    p.add_argument("config")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("command", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("attach", help="open a shell bound to the cluster")
    p.add_argument("config")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", default=None, help="join an existing head")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--resources", default=None,
                   help='extra head-node resources, JSON (e.g. {"TPU": 4})')
    p.add_argument("--state-path", default=None,
                   help="persist controller state (KV, detached actors, "
                        "node table) across head restarts")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop the head started on this machine")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("summary", help="per-function task-event counts")
    p.add_argument("--address", default=None)
    p.add_argument("--breakdown", action="store_true",
                   help="per-label per-phase latency breakdown "
                        "(p50/p99/mean over the flight-recorder histograms: "
                        "scheduling delay, queue wait, arg fetch, execute, "
                        "result store)")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("timeline")
    p.add_argument("--address", default=None)
    p.add_argument("--out", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("logs", help="list / fetch / follow cluster worker "
                                    "logs")
    p.add_argument("name", nargs="?", default=None,
                   help="log file name (from the no-argument listing)")
    p.add_argument("--address", default=None)
    p.add_argument("--node", default=None, help="node id owning the file")
    p.add_argument("--task-id", default=None,
                   help="fetch only this task's attributed output")
    p.add_argument("--actor-id", default=None,
                   help="fetch only this actor's attributed output")
    p.add_argument("--worker-id", default=None,
                   help="resolve the file by worker id")
    p.add_argument("--follow", "-f", action="store_true",
                   help="stream new lines live (ctrl-c to stop)")
    p.add_argument("--tail", type=int, default=0,
                   help="only the last N lines")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("events", help="cluster event feed (lifecycle + "
                                      "hang-watchdog findings)")
    p.add_argument("--address", default=None)
    p.add_argument("--severity", default=None,
                   choices=["DEBUG", "INFO", "WARNING", "ERROR"],
                   help="minimum severity to show")
    p.add_argument("--kind", action="append", default=None,
                   help="event kind filter (repeatable), e.g. TASK_HUNG, "
                        "NODE_DIED, ACTOR_RESTARTING")
    p.add_argument("--task-id", default=None,
                   help="events for this task id (prefix ok)")
    p.add_argument("--actor-id", default=None,
                   help="events for this actor id (prefix ok)")
    p.add_argument("--node", default=None,
                   help="events for this node id (prefix ok)")
    p.add_argument("--worker-id", default=None,
                   help="events for this worker id (prefix ok)")
    p.add_argument("--since", type=float, default=0.0, metavar="S",
                   help="only events from the last S seconds")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--follow", "-f", action="store_true",
                   help="stream new events live (ctrl-c to stop)")
    p.add_argument("--stacks", action="store_true",
                   help="print captured stacks attached to hang events "
                        "(implied by --task-id/--actor-id)")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("stack", help="all-thread stack dump from live "
                                     "workers (`ray stack` analog)")
    p.add_argument("--address", default=None)
    p.add_argument("--worker-id", default=None,
                   help="only this worker (id prefix)")
    p.add_argument("--node", default=None,
                   help="only workers on this node (id prefix)")
    p.add_argument("--timeout", type=float, default=2.0,
                   help="seconds to wait for worker replies")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("dag", help="compiled-DAG observability: per-edge "
                                   "ring telemetry, stage phase "
                                   "accounting, bottleneck attribution")
    dsub = p.add_subparsers(dest="dag_cmd", required=True)
    ds = dsub.add_parser("stats", help="stages×edges busy/starved/blocked "
                                       "view + bottleneck verdict")
    ds.add_argument("dag", nargs="?", default=None,
                    help="dag id (or prefix); default: every compiled DAG")
    ds.add_argument("--address", default=None)
    ds.add_argument("--watch", "-w", action="store_true",
                    help="refresh in place (ctrl-c to stop)")
    ds.add_argument("--interval", type=float, default=2.0,
                    help="refresh period seconds with --watch")
    ds.set_defaults(fn=cmd_dag)
    dt = dsub.add_parser("timeline",
                         help="chrome-trace of per-stage steps with "
                              "recv/compute/send/blocked sub-slices")
    dt.add_argument("dag", nargs="?", default=None,
                    help="dag id (or prefix); default: every compiled DAG")
    dt.add_argument("--address", default=None)
    dt.add_argument("--out", default="dag_timeline.json")
    dt.set_defaults(fn=cmd_dag)

    p = sub.add_parser("top", help="live cluster view: nodes, task "
                                   "rates/p99 with sparkline history, "
                                   "firing alerts, event tail")
    p.add_argument("--address", default=None)
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period seconds")
    p.add_argument("--window", type=float, default=120.0,
                   help="history window seconds for rates/sparklines")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (no screen clearing)")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("profile", help="cluster-wide wall-clock "
                                       "flamegraph (sampling profiler "
                                       "across workers)")
    p.add_argument("--address", default=None)
    p.add_argument("--duration", type=float, default=2.0,
                   help="seconds each targeted worker samples")
    p.add_argument("--hz", type=float, default=None,
                   help="sampling frequency (default RTPU_PROFILER_HZ)")
    p.add_argument("--task-id", default=None,
                   help="only the worker executing this task (prefix ok)")
    p.add_argument("--actor-id", default=None,
                   help="only the worker hosting this actor (prefix ok)")
    p.add_argument("--node", default=None,
                   help="only workers on this node (prefix ok)")
    p.add_argument("--worker-id", default=None,
                   help="only this worker (prefix ok)")
    p.add_argument("-o", "--out", default="profile.html",
                   help="flamegraph HTML output path")
    p.add_argument("--collapsed-out", default=None, metavar="FILE",
                   help="also write collapsed-stack text "
                        "(flamegraph.pl/speedscope format)")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("drain", help="gracefully drain a node "
                                     "(migrate actors, re-queue tasks, "
                                     "then remove it)")
    p.add_argument("node", help="node id (or unique prefix) to drain")
    p.add_argument("--address", default=None)
    p.add_argument("--reason", default="manual",
                   choices=["manual", "preemption", "idle_scale_down"],
                   help="drain reason (rtpu_node_drains_total label)")
    p.add_argument("--deadline", type=float, default=None,
                   help="grace seconds for running tasks "
                        "(default RTPU_DRAIN_DEADLINE_S)")
    p.add_argument("--wait", type=float, default=0.0, metavar="S",
                   help="block up to S seconds until the node is drained")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("memory", help="cluster object census: who owns "
                                      "which bytes, in which tier")
    p.add_argument("--address", default=None)
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--group-by", default="owner", dest="group_by",
                   choices=["owner", "tier", "node", "callsite"],
                   help="grouped byte/count summary (callsite needs "
                        "RTPU_CALLSITE=1 on the producing processes)")
    p.add_argument("--min-size", type=int, default=0, dest="min_size",
                   help="hide per-object rows smaller than this many "
                        "bytes (group totals still count everything)")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("serve", help="deploy/inspect Serve applications")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    sr = ssub.add_parser("run", help="import module:app and serve it")
    sr.add_argument("target")
    sr.add_argument("--address", default=None)
    sr.add_argument("--port", type=int, default=8000)
    sr.set_defaults(fn=cmd_serve)
    for name in ("status", "shutdown"):
        sp = ssub.add_parser(name)
        sp.add_argument("--address", default=None)
        sp.set_defaults(fn=cmd_serve)
    sq = ssub.add_parser("requests",
                         help="the cluster request ledger: finished serve "
                              "requests with status, latency, token stats")
    sq.add_argument("--address", default=None)
    sq.add_argument("--model", default=None,
                    help="filter by deployment-name prefix")
    sq.add_argument("--status", default=None,
                    choices=["ok", "error", "shed", "deadline",
                             "cancelled", "inflight"])
    sq.add_argument("--min-latency-s", type=float, default=None,
                    dest="min_latency_s",
                    help="only requests slower than this many seconds")
    sq.add_argument("--since-s", type=float, default=None, dest="since_s",
                    help="only requests that started in the last N seconds")
    sq.add_argument("--limit", type=int, default=50)
    sq.set_defaults(fn=cmd_serve)
    st_ = ssub.add_parser("trace",
                          help="per-hop waterfall of one request "
                               "(request id may be a unique prefix)")
    st_.add_argument("request_id")
    st_.add_argument("--address", default=None)
    st_.set_defaults(fn=cmd_serve)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--address", default=None)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--dash-port", type=int, default=8265)
    p.add_argument("--grafana-out", default=None, metavar="FILE",
                   help="write importable Grafana dashboard JSON generated "
                        "from the live metric registry, then exit")
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("job")
    p.add_argument("--address", default=None)
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--working-dir", default=None)
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("--max-attempts", type=int, default=None,
                   help="entrypoint retry budget (default "
                        "RTPU_JOB_MAX_ATTEMPTS; preempted attempts are "
                        "free)")
    j.add_argument("entrypoint", nargs=argparse.REMAINDER,
                   help="command after --")
    for name in ("status", "logs", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("job_id")
        if name == "logs":
            j.add_argument("--follow", "-f", action="store_true",
                           help="stream until the job is terminal "
                                "(rides the controller long-poll; "
                                "survives supervisor failover)")
    jsub.add_parser("list")
    p.set_defaults(fn=cmd_job)

    args = ap.parse_args(argv)
    if args.cmd == "job":
        # strip a leading "--" in the remainder
        ep = getattr(args, "entrypoint", None)
        if ep and ep[0] == "--":
            args.entrypoint = ep[1:]
    if args.cmd == "exec":
        cl = getattr(args, "command", None)
        if cl and cl[0] == "--":
            args.command = cl[1:]
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
