"""Vision Transformer encoder, TPU-first (BASELINE.json config 5: ViT-L
batch inference on a TPU actor pool).

The reference framework hosts torch ViTs; here the model is a first-class
jax implementation sharing the decoder's building blocks (ops.attention
with causal=False, the same logical-axis sharding names):

- Patch embedding is a reshape + ONE matmul ([B, N, p*p*C] @ [p*p*C, d]) —
  the im2col form XLA maps straight onto the MXU, instead of a strided
  conv the TPU backend would have to rewrite into the same thing.
- Encoder blocks are pre-LN MHA + GELU MLP over bf16 activations with
  f32 params, stacked with lax.scan (one compiled body, O(1) compile
  depth) exactly like models/transformer.py.
- CLS-token classification head in f32.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import maybe_constrain

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 1024       # ViT-L
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3

    def num_params(self) -> int:
        d, L, F = self.d_model, self.n_layers, self.d_ff
        per_layer = 4 * d * d + 2 * d * F + 4 * d
        return (self.patch_dim * d + d + (self.num_patches + 1) * d + d
                + L * per_layer + 2 * d + d * self.num_classes
                + self.num_classes)


def vit_l16(**overrides) -> ViTConfig:
    return ViTConfig(**overrides)


def vit_tiny(**overrides) -> ViTConfig:
    kw = dict(image_size=32, patch_size=8, num_classes=10, d_model=64,
              n_layers=2, n_heads=4, d_ff=128)
    kw.update(overrides)
    return ViTConfig(**kw)


def init_params(key: jax.Array, cfg: ViTConfig) -> Params:
    d, L, F = cfg.d_model, cfg.n_layers, cfg.d_ff
    ks = jax.random.split(key, 8)
    pd = cfg.param_dtype

    def dense(k, shape, scale=None):
        std = scale if scale is not None else 1.0 / math.sqrt(shape[0])
        return (jax.random.normal(k, shape) * std).astype(pd)

    def stack(k, shape, scale=None):
        kk = jax.random.split(k, L)
        return jnp.stack([dense(kk[i], shape, scale) for i in range(L)])

    return {
        "patch_embed": dense(ks[0], (cfg.patch_dim, d)),
        "patch_bias": jnp.zeros((d,), pd),
        "pos_embed": (jax.random.normal(ks[1], (cfg.num_patches + 1, d))
                      * 0.02).astype(pd),
        "cls_token": jnp.zeros((d,), pd),
        "layers": {
            "ln1": jnp.ones((L, d), pd),
            "ln1_b": jnp.zeros((L, d), pd),
            "wqkv": stack(ks[2], (d, 3, cfg.n_heads, d // cfg.n_heads)),
            "wo": stack(ks[3], (d, d), scale=1.0 / math.sqrt(2 * L * d)),
            "ln2": jnp.ones((L, d), pd),
            "ln2_b": jnp.zeros((L, d), pd),
            "w_up": stack(ks[4], (d, F)),
            "w_down": stack(ks[5], (F, d), scale=1.0 / math.sqrt(2 * L * F)),
        },
        "final_ln": jnp.ones((d,), pd),
        "final_ln_b": jnp.zeros((d,), pd),
        "head": dense(ks[6], (d, cfg.num_classes), scale=0.02),
        "head_b": jnp.zeros((cfg.num_classes,), pd),
    }


def param_logical_specs(cfg: ViTConfig) -> Params:
    return {
        "patch_embed": (None, "embed"),
        "patch_bias": (None,),
        "pos_embed": (None, "embed"),
        "cls_token": (None,),
        "layers": {
            "ln1": ("layers", None),
            "ln1_b": ("layers", None),
            "wqkv": ("layers", "embed", None, "heads", None),
            "wo": ("layers", "heads", "embed"),
            "ln2": ("layers", None),
            "ln2_b": ("layers", None),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_ln": (None,),
        "final_ln_b": (None,),
        "head": ("embed", "vocab"),
        "head_b": (None,),
    }


def _ln(x, w, b):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + 1e-6)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, N, p*p*C] (im2col via reshape/transpose only)."""
    B, H, W, C = images.shape
    p = cfg.patch_size
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, Hp, Wp, p, p, C]
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def forward(params: Params, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """images [B, H, W, C] float -> logits [B, num_classes] f32."""
    B = images.shape[0]
    H = cfg.n_heads
    hd = cfg.d_model // H
    x = patchify(images.astype(cfg.dtype), cfg)
    x = x @ params["patch_embed"].astype(cfg.dtype)
    x = x + params["patch_bias"].astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls_token"].astype(cfg.dtype),
                           (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)[None]
    x = maybe_constrain(x, ("batch", None, "embed"))

    def block(h, layer):
        # Weight access via maybe_dequant: int8 weight-only quantized
        # params (models/quantize.py) work for ViT batch inference the
        # same way they do for transformer decoding.
        from .quantize import maybe_dequant as _mq

        S = h.shape[1]
        y = _ln(h, layer["ln1"], layer["ln1_b"])
        qkv = jnp.einsum("bsd,dcnh->bscnh", y, _mq(layer, "wqkv", cfg.dtype))
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        o = attention(q, k, v, causal=False)
        h = h + o.reshape(B, S, H * hd) @ _mq(layer, "wo", cfg.dtype)
        y = _ln(h, layer["ln2"], layer["ln2_b"])
        y = jax.nn.gelu(y @ _mq(layer, "w_up", cfg.dtype))
        h = h + y @ _mq(layer, "w_down", cfg.dtype)
        return h, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    cls_out = _ln(x[:, 0], params["final_ln"], params["final_ln_b"])
    logits = (cls_out.astype(jnp.float32)
              @ params["head"].astype(jnp.float32)
              + params["head_b"].astype(jnp.float32))
    return logits
