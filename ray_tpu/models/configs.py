"""Named model configs covering the baseline workloads (BASELINE.json):
GPT-2 125M (config 2), Llama-3-8B (config 3), plus tiny variants for tests."""
from __future__ import annotations

from .transformer import TransformerConfig


def gpt2_125m(**overrides) -> TransformerConfig:
    kw = dict(
        vocab_size=50257,
        d_model=768,
        n_layers=12,
        n_heads=12,
        max_seq_len=1024,
        norm="layernorm",
        activation="gelu",
        positional="learned",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def llama3_8b(**overrides) -> TransformerConfig:
    kw = dict(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=8192,
        norm="rmsnorm",
        activation="swiglu",
        positional="rope",
        rope_theta=500000.0,
        tie_embeddings=False,
        remat=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def llama_tiny(**overrides) -> TransformerConfig:
    """Llama-family shape small enough for CPU tests and dry-runs."""
    kw = dict(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        max_seq_len=128,
        norm="rmsnorm",
        activation="swiglu",
        positional="rope",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def gpt2_tiny(**overrides) -> TransformerConfig:
    kw = dict(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        max_seq_len=128,
        norm="layernorm",
        activation="gelu",
        positional="learned",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


# The single-chip bench model: large enough to saturate the MXU on one chip,
# small enough to fit HBM with optimizer state.
def bench_350m(**overrides) -> TransformerConfig:
    kw = dict(
        vocab_size=32000,
        d_model=1024,
        n_layers=24,
        n_heads=16,
        max_seq_len=1024,
        norm="rmsnorm",
        activation="swiglu",
        positional="rope",
        tie_embeddings=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def moe_tiny(**overrides) -> TransformerConfig:
    """Tiny mixture-of-experts decoder for CPU tests and EP dry-runs."""
    kw = dict(
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        max_seq_len=128,
        norm="rmsnorm",
        activation="swiglu",
        positional="rope",
        tie_embeddings=True,
        moe_num_experts=4,
        moe_experts_per_token=2,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)
