"""Decoder-only transformer, TPU-first: one functional implementation covering
the GPT-2 family (LayerNorm/GELU/learned positions) and the Llama family
(RMSNorm/SwiGLU/RoPE/GQA), selected by config.

Design choices driven by XLA/TPU, not by the reference (which has no models —
it hosts torch):
- Pure functional: params are a pytree of arrays; no module framework in the
  hot path, nothing to trace but array math.
- Layers are stacked and iterated with lax.scan → one compiled layer body,
  O(1) compile time in depth, and the natural seam for pipeline parallelism.
- Every array dim carries a logical axis name; `param_logical_specs` returns
  the matching pytree so any sharding strategy (DP/FSDP/TP/SP) is a rule
  table away (ray_tpu.parallel.sharding).
- Activations in bfloat16, params/optimizer in float32 (MXU-native mix).
- Optional jax.checkpoint on the layer body (remat) to trade FLOPs for HBM.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import maybe_constrain

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # None => MHA
    d_ff: Optional[int] = None  # None => 4*d_model (gelu) or 8/3*d_model (swiglu)
    max_seq_len: int = 2048
    norm: str = "rmsnorm"  # rmsnorm | layernorm
    activation: str = "swiglu"  # swiglu | gelu
    positional: str = "rope"  # rope | learned
    rope_theta: float = 500000.0
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # Remat granularity when remat=True:
    # - "full": recompute the whole layer body in the backward (max memory
    #   saving, ~33% extra FLOPs).
    # - "dots": save matmul outputs, recompute elementwise/norm work — BUT
    #   also recomputes the flash-attention forward (a Pallas custom call is
    #   not a dot), which dominates at long sequence lengths.
    # - "dots_attn": "dots" plus the attention output (tagged "attn_out") —
    #   the backward no longer re-runs the flash forward kernel (a Pallas
    #   custom call is not a dot, so plain "dots" recomputes it; measured
    #   ~1/3 of the in-model attention cost, benchmarks/probe_ceiling2.py).
    #   One extra [B,S,H*hd] bf16 residual per layer.
    # - "min": save everything except the two fat fused-projection outputs
    #   (qkv and gate_up, tagged via checkpoint_name below) — flash
    #   residuals stay saved, recompute is one einsum + elementwise. The
    #   cheapest policy that still bounds activation memory.
    # Default "dots": the axon AOT compile helper crashes (HTTP 500) on the
    # larger live sets "min"/no-remat produce at bench shapes; "dots" is the
    # fastest policy that reliably compiles there (benchmarks/mfu_sweep.py).
    remat_policy: str = "dots"
    # Mixture-of-Experts MLP (ops/moe.py, GShard capacity-based top-k):
    # 0 = dense. The expert dim shards over the `expert` mesh axis.
    moe_num_experts: int = 0
    moe_experts_per_token: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    # Chunked fused lm-head+CE (ops/fused_ce.py): never materializes the
    # [B*S, V] logits/dlogits tensors (~1GB each way at bench shapes) —
    # vocab chunks stream through online logsumexp fwd / recompute bwd.
    fused_ce: bool = False

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.activation == "swiglu":
            # Llama sizing: 2/3 * 4d rounded to a multiple of 128 (MXU tile).
            d = int(8 * self.d_model / 3)
            return (d + 127) // 128 * 128
        return 4 * self.d_model

    def num_params(self) -> int:
        d, L, V = self.d_model, self.n_layers, self.vocab_size
        h = self.head_dim
        attn = d * (self.n_heads * h) + 2 * d * (self.kv_heads * h) + (self.n_heads * h) * d
        if self.moe_num_experts:
            mlp = self.moe_num_experts * 3 * d * self.ff_dim + d * self.moe_num_experts
        elif self.activation == "swiglu":
            mlp = 3 * d * self.ff_dim
        else:
            mlp = 2 * d * self.ff_dim
        norms = 2 * d * L + d
        if self.norm == "layernorm":
            norms *= 2  # biases alongside scales
        emb = V * d * (1 if self.tie_embeddings else 2)
        pos = 0 if self.positional == "rope" else self.max_seq_len * d
        return L * (attn + mlp) + norms + emb + pos

    def num_active_params(self) -> int:
        """Params touched per token: for MoE, only experts_per_token of the
        E experts execute, so compute-oriented uses (FLOPs/MFU) must not
        count the full expert bank."""
        if not self.moe_num_experts:
            return self.num_params()
        d, L, F = self.d_model, self.n_layers, self.ff_dim
        full_mlp = self.moe_num_experts * 3 * d * F
        active_mlp = self.moe_experts_per_token * 3 * d * F
        return self.num_params() - L * (full_mlp - active_mlp)

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Forward+backward FLOPs/token ≈ 6*N_active + 12*L*S*d (attn)."""
        S = seq_len or self.max_seq_len
        return (6.0 * self.num_active_params()
                + 12.0 * self.n_layers * S * self.d_model)


def _dense_init(key, shape, param_dtype, scale: Optional[float] = None):
    fan_in = shape[0]
    std = scale if scale is not None else (1.0 / math.sqrt(fan_in))
    return (jax.random.normal(key, shape) * std).astype(param_dtype)


def init_params(key: jax.Array, cfg: TransformerConfig) -> Params:
    d, L, V, F = cfg.d_model, cfg.n_layers, cfg.vocab_size, cfg.ff_dim
    H, KVH, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    keys = jax.random.split(key, 12)

    def stack(initializer, shape, k):
        ks = jax.random.split(k, L)
        return jnp.stack([initializer(ks[i], shape, cfg.param_dtype) for i in range(L)])

    # Projections are FUSED into single matmuls (one MXU op instead of 2-3:
    # q/k/v together for MHA, k/v together for GQA, gate/up together for
    # swiglu). The fusion factor is its own array dim — NOT folded into the
    # feature dim — so tensor-parallel sharding of heads/mlp stays aligned
    # to shard boundaries (Megatron fused-qkv, done the GSPMD-friendly way).
    layers = {
        "attn_norm": jnp.ones((L, d), cfg.param_dtype),
        "wo": stack(lambda k, s, pd: _dense_init(k, s, pd, scale=1.0 / math.sqrt(2 * L * s[0])),
                    (H * hd, d), keys[3]),
        "mlp_norm": jnp.ones((L, d), cfg.param_dtype),
    }
    if not cfg.moe_num_experts:
        layers["w_down"] = stack(
            lambda k, s, pd: _dense_init(k, s, pd,
                                         scale=1.0 / math.sqrt(2 * L * s[0])),
            (F, d), keys[5])
    if KVH == H:
        layers["wqkv"] = stack(_dense_init, (d, 3, H, hd), keys[0])
    else:
        layers["wq"] = stack(_dense_init, (d, H, hd), keys[0])
        layers["wkv"] = stack(_dense_init, (d, 2, KVH, hd), keys[1])
    if cfg.moe_num_experts:
        E = cfg.moe_num_experts
        layers["router"] = stack(_dense_init, (d, E), keys[6])
        # Explicit scales: _dense_init's shape[0] fan-in heuristic would read
        # E (the expert dim) instead of the real matmul fan-ins d and F.
        layers["moe_w_gate_up"] = stack(
            lambda k, s, pd: _dense_init(k, s, pd, scale=1.0 / math.sqrt(d)),
            (E, d, 2, F), keys[4])
        layers["moe_w_down"] = stack(
            lambda k, s, pd: _dense_init(k, s, pd,
                                         scale=1.0 / math.sqrt(2 * L * F)),
            (E, F, d), keys[5])
    elif cfg.activation == "swiglu":
        layers["w_gate_up"] = stack(_dense_init, (d, 2, F), keys[4])
    else:
        layers["w_up"] = stack(_dense_init, (d, F), keys[4])
    if cfg.norm == "layernorm":
        layers["attn_norm_b"] = jnp.zeros((L, d), cfg.param_dtype)
        layers["mlp_norm_b"] = jnp.zeros((L, d), cfg.param_dtype)

    params: Params = {
        "embed": (jax.random.normal(keys[7], (V, d)) * 0.02).astype(cfg.param_dtype),
        "final_norm": jnp.ones((d,), cfg.param_dtype),
        "layers": layers,
    }
    if cfg.norm == "layernorm":
        params["final_norm_b"] = jnp.zeros((d,), cfg.param_dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(keys[8], (d, V), cfg.param_dtype, scale=0.02)
    if cfg.positional == "learned":
        params["pos_embed"] = (
            jax.random.normal(keys[9], (cfg.max_seq_len, d)) * 0.02
        ).astype(cfg.param_dtype)
    return params


def param_logical_specs(cfg: TransformerConfig) -> Params:
    """Pytree of logical axis names matching init_params' structure
    (consumed by parallel.sharding.tree_shardings)."""
    # The leading dim is the layer stack: logical axis "layers" maps onto
    # the `pipe` mesh axis so each pipeline stage holds a contiguous range
    # of layers (parallel/pipeline.py).
    layers = {
        "attn_norm": ("layers", None),
        "wo": ("layers", "heads", "embed"),
        "mlp_norm": ("layers", None),
    }
    if not cfg.moe_num_experts:
        layers["w_down"] = ("layers", "mlp", "embed")
    if cfg.kv_heads == cfg.n_heads:
        layers["wqkv"] = ("layers", "embed", None, "heads", None)
    else:
        layers["wq"] = ("layers", "embed", "heads", None)
        layers["wkv"] = ("layers", "embed", None, "kv_heads", None)
    if cfg.moe_num_experts:
        layers["router"] = ("layers", "embed", None)
        layers["moe_w_gate_up"] = ("layers", "expert", "embed", None, "mlp")
        layers["moe_w_down"] = ("layers", "expert", "mlp", "embed")
    elif cfg.activation == "swiglu":
        layers["w_gate_up"] = ("layers", "embed", None, "mlp")
    else:
        layers["w_up"] = ("layers", "embed", "mlp")
    if cfg.norm == "layernorm":
        layers["attn_norm_b"] = ("layers", None)
        layers["mlp_norm_b"] = ("layers", None)
    specs: Params = {
        "embed": ("vocab", "embed"),
        "final_norm": (None,),
        "layers": layers,
    }
    if cfg.norm == "layernorm":
        specs["final_norm_b"] = (None,)
    if not cfg.tie_embeddings:
        specs["lm_head"] = ("embed", "vocab")
    if cfg.positional == "learned":
        specs["pos_embed"] = (None, "embed")
    return specs


def _norm(x, w, b, kind: str):
    xf = x.astype(jnp.float32)
    if kind == "rmsnorm":
        x2 = jnp.mean(xf * xf, axis=-1, keepdims=True)
        out = xf * jax.lax.rsqrt(x2 + 1e-6) * w.astype(jnp.float32)
    else:
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = (xf - mu) * jax.lax.rsqrt(var + 1e-5) * w.astype(jnp.float32)
        if b is not None:
            out = out + b.astype(jnp.float32)
    return out.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over the last dim of [B, S, H, D]."""
    D = x.shape[-1]
    half = D // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _w(layer: Params, name: str, cfg: TransformerConfig) -> jax.Array:
    """Weight access for the layer helpers: compute-dtype view,
    transparently dequantizing int8 weight-only params
    (models/quantize.py) when a scale sibling is present."""
    from .quantize import maybe_dequant

    return maybe_dequant(layer, name, cfg.dtype)


def _qkv_proj(cfg: TransformerConfig, h: jax.Array, layer: Params,
              positions: jax.Array):
    """Projection + rope shared by training forward and KV-cache decode
    (models/generate.py) — ONE home for the layer's q/k/v convention."""
    if "wqkv" in layer:
        qkv = jnp.einsum("bsd,dcnh->bscnh", h, _w(layer, "wqkv", cfg))
        qkv = checkpoint_name(qkv, "qkv_proj")
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    else:
        q = jnp.einsum("bsd,dnh->bsnh", h, _w(layer, "wq", cfg))
        kv = jnp.einsum("bsd,dcnh->bscnh", h, _w(layer, "wkv", cfg))
        kv = checkpoint_name(kv, "qkv_proj")
        k, v = kv[:, :, 0], kv[:, :, 1]
    if cfg.positional == "rope":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _mlp_block(cfg: TransformerConfig, h: jax.Array, layer: Params):
    """Post-attention FFN (moe / swiglu / gelu), shared with the decode
    path; returns (delta, moe_aux)."""
    if cfg.moe_num_experts:
        from ray_tpu.ops.moe import moe_ffn

        return moe_ffn(
            h, layer["router"], layer["moe_w_gate_up"], layer["moe_w_down"],
            experts_per_token=cfg.moe_experts_per_token,
            capacity_factor=cfg.moe_capacity_factor,
            dtype=cfg.dtype)
    aux = jnp.zeros((), jnp.float32)
    if cfg.activation == "swiglu":
        gu = jnp.einsum("bsd,dcf->bscf", h, _w(layer, "w_gate_up", cfg))
        gu = checkpoint_name(gu, "gate_up")
        act = jax.nn.silu(gu[:, :, 0]) * gu[:, :, 1]
        return act @ _w(layer, "w_down", cfg), aux
    act = checkpoint_name(h @ _w(layer, "w_up", cfg), "gate_up")
    act = jax.nn.gelu(act)
    return act @ _w(layer, "w_down", cfg), aux


def _layer_body(cfg: TransformerConfig, x: jax.Array, layer: Params,
                positions: jax.Array, return_kv: bool = False):
    B, S, d = x.shape
    H, KVH, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim

    h = _norm(x, layer["attn_norm"], layer.get("attn_norm_b"), cfg.norm)
    q, k, v = _qkv_proj(cfg, h, layer, positions)
    q = maybe_constrain(q, ("batch", "seq_act", "heads", None))
    o = checkpoint_name(attention(q, k, v, causal=True), "attn_out")
    x = x + o.reshape(B, S, H * hd) @ _w(layer, "wo", cfg)
    x = maybe_constrain(x, ("batch", "seq_act", "embed"))

    h = _norm(x, layer["mlp_norm"], layer.get("mlp_norm_b"), cfg.norm)
    delta, aux = _mlp_block(cfg, h, layer)
    x = x + delta
    x = maybe_constrain(x, ("batch", "seq_act", "embed"))
    if return_kv:
        return x, aux, k, v
    return x, aux


def _layer_body_kv(cfg: TransformerConfig, x: jax.Array, layer: Params,
                   positions: jax.Array):
    """Layer forward that also surfaces this layer's (roped) K/V — the
    prefill path of models/generate.py primes its cache from these."""
    x, _aux, k, v = _layer_body(cfg, x, layer, positions, return_kv=True)
    return x, k, v


def embed_tokens(params: Params, tokens: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """tokens [B, S] -> embeddings [B, S, d] (cfg.dtype)."""
    B, S = tokens.shape
    # Replicate the table for the lookup (FSDP all-gather-at-use): a gather
    # from a vocab/embed-sharded operand forces GSPMD into involuntary full
    # rematerialization when resharding the output onto the batch/seq axes
    # (MULTICHIP_r01). With a replicated operand the gather partitions
    # trivially along the token sharding; the vocab-sharded original still
    # feeds the lm_head matmul below, and the backward scatter-add
    # reduce-scatters back into the sharded param layout.
    tbl = maybe_constrain(params["embed"].astype(cfg.dtype), (None, None))
    x = tbl[tokens]
    x = maybe_constrain(x, ("batch", "seq_act", "embed"))
    if cfg.positional == "learned":
        x = x + params["pos_embed"].astype(cfg.dtype)[:S][None]
    return x


def layer_scan_body(cfg: TransformerConfig, positions: jax.Array):
    """The (remat-wrapped) per-layer scan body; shared by the plain forward
    and the pipeline-parallel stage apply (parallel/pipeline.py). The scan's
    per-layer output is the MoE aux loss (zeros for dense layers)."""
    body = lambda carry, layer: _layer_body(cfg, carry, layer, positions)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "dots_attn":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names("attn_out"),
                ),
            )
        elif cfg.remat_policy == "min":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.save_anything_except_these_names(
                    "qkv_proj", "gate_up"
                ),
            )
        elif cfg.remat_policy == "full":
            body = jax.checkpoint(body)
        else:
            # "half_*" is resolved by forward_with_aux (it splits the stack
            # and re-enters here with full/dots/remat=False); any other name
            # reaching this point is a config error — a silent full-remat
            # fallback would mis-measure the policy being asked for.
            raise ValueError(
                f"unhandled remat_policy {cfg.remat_policy!r} at the scan "
                f"level (half_* composes only through the plain forward, "
                f"not the pipeline path)")
    return body


def forward(params: Params, tokens: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V] (f32)."""
    return forward_with_aux(params, tokens, cfg)[0]


def forward_with_aux(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
) -> Tuple[jax.Array, jax.Array]:
    """forward + summed MoE load-balancing aux loss (0 for dense stacks)."""
    x, aux = backbone_with_aux(params, tokens, cfg)
    return lm_head(params, x, cfg), aux


def backbone_with_aux(
    params: Params, tokens: jax.Array, cfg: TransformerConfig
) -> Tuple[jax.Array, jax.Array]:
    """Everything before the lm head: tokens -> hidden [B,S,d] + MoE aux
    (the fused-CE loss path consumes the hidden states directly)."""
    B, S = tokens.shape
    x = embed_tokens(params, tokens, cfg)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    if cfg.remat and cfg.remat_policy.startswith("half"):
        # Mixed remat: the FIRST half of the stack checkpoints (its saved
        # activations would live longest — from forward until the very end
        # of the backward), the second half keeps activations. Halves the
        # backward recompute at roughly half of full-remat's memory saving,
        # using only standard policies the AOT helper accepts.
        inner = dataclasses.replace(
            cfg, remat_policy="dots" if cfg.remat_policy == "half_dots"
            else "full")
        plain = dataclasses.replace(cfg, remat=False)
        half = cfg.n_layers // 2
        first = jax.tree.map(lambda a: a[:half], params["layers"])
        second = jax.tree.map(lambda a: a[half:], params["layers"])
        x, aux1 = jax.lax.scan(layer_scan_body(inner, positions), x, first)
        x, aux2 = jax.lax.scan(layer_scan_body(plain, positions), x, second)
        aux = aux1.sum() + aux2.sum()
    else:
        x, auxs = jax.lax.scan(
            layer_scan_body(cfg, positions), x, params["layers"])
        aux = auxs.sum()
    return x, aux


def final_hidden_and_head(
    params: Params, x: jax.Array, cfg: TransformerConfig
) -> Tuple[jax.Array, jax.Array]:
    """THE head-weight convention (final norm + tied-or-separate head),
    shared by the unfused lm_head and the fused-CE loss path so the two
    can never drift."""
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), cfg.norm)
    head = params.get("lm_head", None)
    if head is None:
        head = params["embed"].T
    return x, head.astype(cfg.dtype)


def lm_head(params: Params, x: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Final norm + (tied) output projection: hidden [B,S,d] -> logits f32."""
    x, head = final_hidden_and_head(params, x, cfg)
    return (x @ head).astype(jnp.float32)


def token_cross_entropy(logits: jax.Array, targets: jax.Array,
                        valid: jax.Array) -> jax.Array:
    """Mean CE of logits [B,S,V] vs targets [B,S] over positions where
    ``valid`` (f32 weights) is nonzero.

    Fused: ll = logits[target] - logsumexp(logits) avoids materializing a
    second [B, S, V] f32 log-softmax tensor (at V=32k that tensor dominates
    HBM traffic for the loss epilogue).
    """
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    at_target = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ll = at_target - lse
    return -(ll * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def shift_targets_valid(tokens: jax.Array, mask: Optional[jax.Array] = None):
    """targets/valid weights for the shift_inputs convention: tokens is
    [B,S+1], the forward ran on tokens[:, :-1]. Shared by loss_fn and
    parallel.pipeline.pipeline_loss_fn so the convention cannot drift."""
    targets = tokens[:, 1:]
    valid = jnp.ones(targets.shape, jnp.float32)
    if mask is not None:
        valid = valid * mask[:, 1:].astype(jnp.float32)
    return targets, valid


def inplace_targets_valid(batch: Dict[str, jax.Array]):
    """targets/valid for the in-place convention (final position masked)."""
    tokens = batch["tokens"]
    B, S = tokens.shape
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)], axis=1)
    valid = jnp.concatenate(
        [jnp.ones((B, S - 1), jnp.float32), jnp.zeros((B, 1), jnp.float32)],
        axis=1)
    mask = batch.get("mask")
    if mask is not None:
        shifted = jnp.concatenate(
            [mask[:, 1:], jnp.zeros((B, 1), mask.dtype)], axis=1)
        valid = valid * shifted.astype(jnp.float32)
    return targets, valid


def next_token_loss(logits: jax.Array, batch: Dict[str, jax.Array]) -> jax.Array:
    """Next-token CE over logits [B,S,V]; loss over tokens[1:] (the final
    position is masked out — in-place convention, see loss_fn)."""
    targets, valid = inplace_targets_valid(batch)
    return token_cross_entropy(logits, targets, valid)


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: TransformerConfig,
            *, shift_inputs: bool = False) -> jax.Array:
    """Next-token cross-entropy.

    Two token conventions:
    - in-place (default): batch tokens [B,S]; the forward runs on the FULL
      sequence and the final position's logits are masked out of the loss.
      Keeps the activation sequence length equal to the (power-of-two)
      input length, which the `seq` mesh axis divides under context
      parallelism.
    - shift_inputs: batch tokens [B,S+1]; forward on tokens[:, :-1],
      targets tokens[:, 1:], every position valid. This is the
      high-throughput convention: with S+1 fed through the in-place path
      the whole model would run at an odd length (e.g. 1025), misaligning
      every matmul tile and forcing an extra padded+masked block row/col
      into the flash grid — measured ~12% step-time overhead at bench
      shapes. The sliced length S is the power of two, so context
      parallelism composes too.
    """
    tokens = batch["tokens"]
    if cfg.fused_ce:
        from ..ops.fused_ce import fused_next_token_loss

        tokens_in = tokens[:, :-1] if shift_inputs else tokens
        x, aux = backbone_with_aux(params, tokens_in, cfg)
        x, head = final_hidden_and_head(params, x, cfg)
        if shift_inputs:
            targets, valid = shift_targets_valid(tokens, batch.get("mask"))
        else:
            targets, valid = inplace_targets_valid(batch)
        loss = fused_next_token_loss(
            x.astype(cfg.dtype), head, targets, valid)
    elif shift_inputs:
        logits, aux = forward_with_aux(params, tokens[:, :-1], cfg)
        targets, valid = shift_targets_valid(tokens, batch.get("mask"))
        loss = token_cross_entropy(logits, targets, valid)
    else:
        logits, aux = forward_with_aux(params, tokens, cfg)  # [B, S, V]
        loss = next_token_loss(logits, batch)
    if cfg.moe_num_experts:
        loss = loss + cfg.moe_aux_coef * aux
    return loss
