"""Autoregressive decoding with a static-shape KV cache.

The reference framework ships no model layer (SURVEY.md §5.7) — this is the
TPU-first inference path its Serve/Data users would otherwise build by hand:

- **Static shapes end to end**: the cache is allocated at `max_len` up
  front; the decode loop is ONE `lax.scan` over step indices, so the whole
  generation compiles once (no per-length recompiles, no dynamic shapes —
  XLA's requirement, not a style choice).
- **Prefill/decode split**: the prompt runs through the normal batched
  forward (MXU-friendly [B, S] matmuls) capturing per-layer K/V; each
  decode step is a [B, 1] pass attending over the cache (a dot against
  cached keys — flash tiling buys nothing for a single query row).
- **GQA-aware**: cached K/V keep `kv_heads`; query heads fold into groups
  at the attention einsum exactly like ops/attention.py's training path.

Layout: cache K/V are [L, B, max_len, KVH, hd] — layer-major so the decode
scan over layers consumes them as `xs` alongside the stacked layer params.
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .transformer import (
    TransformerConfig,
    _layer_body_kv,
    _mlp_block,
    _norm,
    _qkv_proj,
    _w,
    embed_tokens,
    final_hidden_and_head,
)

Params = Dict[str, jax.Array]


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, max_len, KVH, hd] (cfg.dtype)
    v: jax.Array  # [L, B, max_len, KVH, hd]
    # Tokens filled so far: [] int32 (uniform batch) or [B] int32 (ragged
    # batch — per-row prompt lengths; decode masks and writes per row).
    pos: jax.Array


def prefill(params: Params, tokens: jax.Array, cfg: TransformerConfig,
            max_len: int,
            lengths: Optional[jax.Array] = None) -> Tuple[jax.Array, KVCache]:
    """Run the prompt [B, S] through the batched forward, returning logits
    for the last REAL position [B, V] and the primed cache.

    `lengths` [B] enables RAGGED prompts: rows are right-padded to S, each
    row's logits come from index lengths[i]-1, and cache.pos = lengths.
    Right-padding is safe without a key mask: causal attention means real
    tokens never attend pad positions (pads sit after them), pad rows'
    outputs go unused, and decode overwrites the pad K/V slot at pos[i]
    BEFORE the attention einsum runs (the valid mask is slot <= pos[i],
    which includes the just-written slot — ordering of _write before
    attend in decode_step's body is load-bearing)."""
    B, S = tokens.shape
    if S > max_len:
        raise ValueError(f"prompt length {S} exceeds cache max_len {max_len}")
    x = embed_tokens(params, tokens, cfg)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))

    def body(carry, layer):
        x, k, v = _layer_body_kv(cfg, carry, layer, positions)
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])
    # Pad [L, B, S, KVH, hd] out to the static max_len.
    pad = [(0, 0), (0, 0), (0, max_len - S), (0, 0), (0, 0)]
    if lengths is None:
        last = x[:, -1:]
        pos = jnp.asarray(S, jnp.int32)
    else:
        last = jnp.take_along_axis(
            x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)
        pos = lengths.astype(jnp.int32)
    cache = KVCache(k=jnp.pad(ks, pad), v=jnp.pad(vs, pad), pos=pos)
    h, head = final_hidden_and_head(params, last, cfg)
    logits = (h @ head).astype(jnp.float32)[:, 0]
    return logits, cache


def decode_step(params: Params, cache: KVCache, token: jax.Array,
                cfg: TransformerConfig) -> Tuple[jax.Array, KVCache]:
    """One token [B] int32 -> logits [B, V] + the cache advanced by one."""
    if cfg.positional == "learned":
        raise NotImplementedError(
            "decode_step: learned positional embeddings index by absolute "
            "position, which embed_tokens applies only for full sequences; "
            "use rope (the flagship configs) for incremental decoding")
    B = token.shape[0]
    H, KVH, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    max_len = cache.k.shape[2]
    pos = cache.pos
    # Overflow guard (eager callers only — the manual prefill/decode_step
    # loop): under jit `pos` is traced and dynamic_update_slice would CLAMP
    # the write to the last slot, silently overwriting it. generate() can't
    # overflow (its scan length is sized against max_len); hand-rolled
    # loops get the same contract as prefill's length check where possible.
    try:
        hi = int(pos) if getattr(pos, "ndim", 0) == 0 else int(pos.max())
        if hi >= max_len:
            raise ValueError(
                f"decode_step: cache full (pos {hi} >= max_len "
                f"{max_len}); size prefill's max_len for the tokens you "
                f"intend to generate")
    except (jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        pass
    ragged = getattr(pos, "ndim", 0) == 1  # per-row positions [B]
    x = embed_tokens(params, token[:, None], cfg)  # [B, 1, d]
    if ragged:
        positions = pos[:, None].astype(jnp.int32)
        # [B,1,1,S]: row i may attend cache slots < pos[i] plus its own
        # just-written slot.
        valid = (jnp.arange(max_len)[None] <= pos[:, None])[:, None, None]
    else:
        positions = jnp.full((B, 1), pos, jnp.int32)
        valid = (jnp.arange(max_len) <= pos)[None, None, None, :]

    def _write(ck, k):
        """Append this step's K (or V) at each row's position."""
        if ragged:
            return jax.vmap(
                lambda c, kk, p: jax.lax.dynamic_update_slice(
                    c, kk, (p, 0, 0)))(ck, k.astype(ck.dtype), pos)
        return jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                            (0, pos, 0, 0))

    def body(x, xs):
        layer, ck, cv = xs  # ck/cv: [B, max_len, KVH, hd]
        h = _norm(x, layer["attn_norm"], layer.get("attn_norm_b"), cfg.norm)
        q, k, v = _qkv_proj(cfg, h, layer, positions)
        ck = _write(ck, k)
        cv = _write(cv, v)
        # GQA: fold query heads into KVH groups of size G.
        G = H // KVH
        qg = q.reshape(B, 1, KVH, G, hd)
        scores = jnp.einsum("bqkgh,bskh->bkgqs", qg.astype(jnp.float32),
                            ck.astype(jnp.float32)) / (hd ** 0.5)
        scores = jnp.where(valid[:, :, :, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bkgqs,bskh->bqkgh", probs,
                       cv.astype(jnp.float32)).astype(cfg.dtype)
        o = o.reshape(B, 1, H * hd)
        x = x + o @ _w(layer, "wo", cfg)

        h = _norm(x, layer["mlp_norm"], layer.get("mlp_norm_b"), cfg.norm)
        delta, _aux = _mlp_block(cfg, h, layer)
        x = x + delta
        return x, (ck, cv)

    x, (nk, nv) = jax.lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x, head = final_hidden_and_head(params, x, cfg)
    logits = (x @ head).astype(jnp.float32)[:, 0]
    return logits, KVCache(k=nk, v=nv, pos=pos + 1)


def _decode_loop(params, cfg, cache, logits, pick, rng, max_new_tokens,
                 eos_id):
    """Shared first-token + eos-freeze + lax.scan machinery for
    generate()/generate_ragged() — ONE home so sampling/eos semantics can
    never drift between the uniform and ragged paths. Returns
    (first [B], rest [max_new_tokens-1, B])."""
    B = logits.shape[0]
    rng, r0 = jax.random.split(rng)
    first = pick(logits, r0)
    # The first generated token may itself be eos — done0 reflects it.
    done0 = jnp.zeros((B,), bool) if eos_id is None else first == eos_id

    def step(carry, step_rng):
        cache, tok, done = carry
        logits, cache = decode_step(params, cache, tok, cfg)
        nxt = pick(logits, step_rng)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done), nxt

    keys = jax.random.split(rng, max(max_new_tokens - 1, 0))
    (_, _, _), rest = jax.lax.scan(step, (cache, first, done0), keys)
    return first, rest


def generate(params: Params, tokens: jax.Array, cfg: TransformerConfig,
             max_new_tokens: int, *, temperature: float = 0.0,
             top_k: int = 0, rng: Optional[jax.Array] = None,
             eos_id: Optional[int] = None) -> jax.Array:
    """Greedy (temperature=0) or sampled continuation of `tokens` [B, S] ->
    [B, S + max_new_tokens]. Once a row emits `eos_id` it keeps repeating
    it (the static output shape never changes — consumers mask on eos).
    jit-able as a whole; the step loop is a lax.scan. `temperature` may be
    a traced jax scalar (serving passes client values without recompiles);
    a Python float stays static and compiles only its branch."""
    B, S = tokens.shape
    max_len = S + max_new_tokens
    logits, cache = prefill(params, tokens, cfg, max_len)
    if rng is None:
        rng = jax.random.key(0)

    static_temp = isinstance(temperature, (int, float))

    def pick(logits, step_rng):
        # `temperature` may be a TRACED scalar (a serving path must not
        # recompile per client-supplied float): then both branches compute
        # and a where() selects. A static Python float keeps the one-branch
        # program.
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)
        if static_temp and temperature <= 0.0:
            return greedy
        scaled = logits / jnp.maximum(temperature, 1e-6)
        if top_k:
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]  # O(V log k)
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        sampled = jax.random.categorical(step_rng, scaled).astype(jnp.int32)
        if static_temp:
            return sampled
        return jnp.where(temperature <= 0.0, greedy, sampled)

    first, rest = _decode_loop(params, cfg, cache, logits, pick, rng,
                               max_new_tokens, eos_id)
    out = jnp.concatenate(
        [tokens, first[:, None], rest.T.astype(tokens.dtype)], axis=1)
    return out[:, :max_len]


def generate_ragged(params: Params, tokens: jax.Array, lengths: jax.Array,
                    cfg: TransformerConfig, max_new_tokens: int, *,
                    temperature=0.0, rng: Optional[jax.Array] = None,
                    eos_id: Optional[int] = None) -> jax.Array:
    """Mixed-length batched generation: prompts right-padded to [B, S] with
    true `lengths` [B] -> GENERATED tokens [B, max_new_tokens].

    One compiled program serves every batch composition: per-row cache
    positions remove the uniform-prompt-length restriction, and
    `temperature` may be a [B] vector (per-request sampling — rows with
    temperature<=0 decode greedily) or a scalar/float. Serving uses this
    to batch heterogeneous requests without per-length recompiles."""
    B, S = tokens.shape
    max_len = S + max_new_tokens
    logits, cache = prefill(params, tokens, cfg, max_len, lengths=lengths)
    if rng is None:
        rng = jax.random.key(0)
    temp = jnp.asarray(temperature, jnp.float32)
    if temp.ndim == 0:
        temp = jnp.broadcast_to(temp, (B,))
    tcol = temp[:, None]

    def pick(logits, step_rng):
        greedy = jnp.argmax(logits, -1).astype(jnp.int32)
        scaled = logits / jnp.maximum(tcol, 1e-6)
        sampled = jax.random.categorical(step_rng, scaled).astype(jnp.int32)
        return jnp.where(temp <= 0.0, greedy, sampled)

    first, rest = _decode_loop(params, cfg, cache, logits, pick, rng,
                               max_new_tokens, eos_id)
    return jnp.concatenate([first[:, None], rest.T], axis=1).astype(jnp.int32)
