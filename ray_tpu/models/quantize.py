"""Weight-only int8 quantization for inference.

Decode is HBM-bandwidth-bound (every step reads all parameters once:
models/generate.py docstring), so storing layer weights as int8 with
per-output-channel bf16 scales nearly halves the bytes each decode step
streams — XLA fuses the `q * scale` dequant into the matmul's operand
read, so there is no materialized bf16 copy.

Scheme: symmetric absmax per OUTPUT channel — for a weight of shape
[d_in, ...out], the scale has shape [...out] (reduction over d_in), so the
worst-case relative error per channel is 1/127. Activations stay bf16
(weight-only), which preserves the training forward untouched: the layer
helpers (transformer._qkv_proj/_mlp_block) dequantize transparently when a
`<name>_q8_scale` sibling is present.

The embedding/lm-head stay unquantized in v1: the (tied) table feeds BOTH
the token gather and the head matmul, and gather output quality is far
more scale-sensitive than the FFN mats.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

# Per-layer weights worth quantizing (the stacked [L, ...] leaves).
DEFAULT_NAMES = ("wqkv", "wq", "wkv", "wo", "w_gate_up", "w_up", "w_down")

SCALE_SUFFIX = "_q8_scale"


def _quantize_leaf(w: jax.Array) -> tuple:
    """[d_in, ...out] -> (int8 [same shape], scale [1, ...out] f32).

    The scale KEEPS the reduced d_in axis as size 1, so `q * scale`
    broadcasts identically whether the caller holds the stacked
    [L, d_in, ...out] tree leaf (scale [L, 1, ...out]) or one layer's
    slice inside a lax.scan (scale [1, ...out])."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def quantize_params_int8(params: Params,
                         names: Iterable[str] = DEFAULT_NAMES) -> Params:
    """Same tree with each named layer weight replaced by int8 plus a
    `<name>_q8_scale` sibling. Layer weights are stacked [L, ...]; the
    scale keeps the leading L so each layer dequantizes with its own
    channels."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in names:
        w = layers.get(name)
        if w is None:
            continue
        if w.dtype == jnp.int8 or name + SCALE_SUFFIX in layers:
            # Already quantized: re-quantizing would compute absmax over
            # the int8 CODES (~127), overwrite the real scale with ~1.0,
            # and silently corrupt every channel. Idempotent skip.
            continue
        q, scale = jax.vmap(_quantize_leaf)(w)  # map over the L axis
        layers[name] = q
        layers[name + SCALE_SUFFIX] = scale
    out["layers"] = layers
    return out


def maybe_dequant(layer: Params, name: str, dtype) -> jax.Array:
    """The layer weight in compute dtype, dequantizing if quantized —
    THE access path transformer's layer helpers use for every weight."""
    w = layer[name]
    scale = layer.get(name + SCALE_SUFFIX)
    if scale is None:
        return w.astype(dtype)
    # The scale carries a size-1 d_in axis (see _quantize_leaf), so this
    # broadcast is layout-agnostic; XLA fuses it into the consuming
    # matmul's operand read (no bf16 copy in HBM).
    return (w.astype(jnp.float32) * scale).astype(dtype)
