from . import configs, transformer, vit
from .generate import KVCache, decode_step, generate, prefill

__all__ = ["configs", "transformer", "vit",
           "KVCache", "decode_step", "generate", "prefill"]
