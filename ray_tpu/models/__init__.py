from . import configs, transformer, vit
from .generate import KVCache, decode_step, generate, prefill
from .quantize import quantize_params_int8

__all__ = ["configs", "transformer", "vit",
           "KVCache", "decode_step", "generate", "prefill",
           "quantize_params_int8"]
