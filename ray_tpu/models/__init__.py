from . import configs, transformer, vit
from .generate import (KVCache, decode_step, generate,
                       generate_ragged, prefill)
from .quantize import quantize_params_int8

__all__ = ["configs", "transformer", "vit",
           "KVCache", "decode_step", "generate", "generate_ragged",
           "prefill",
           "quantize_params_int8"]
